"""Per-shard storage engine: versioned CRUD over immutable tensor segments.

The analog of the reference InternalEngine
(/root/reference/src/main/java/org/elasticsearch/index/engine/InternalEngine.java:65):
  * in-memory write buffer (SegmentBuilder) plays IndexWriter's RAM buffer
  * refresh() freezes the buffer into a device segment — NRT searcher analog
    (InternalEngine.java:80-83 SearcherManager; default 1s in the reference)
  * LiveVersionMap for realtime get + optimistic versioning
    (InternalEngine.java:94,107; version checks :255-270)
  * every op appended to the translog before ack (InternalEngine.java:331)
  * flush() = commit: persist segment state + roll/trim translog
  * tiered-ish merge: many small segments collapse into one (index/merge/)

Single-writer discipline per shard (the reference serializes writes per uid
via uid-locks; here a shard-level lock since ops are host-side builder
mutations — device state is only produced at refresh)."""

from __future__ import annotations

import math
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from ..mapping.mapper import MapperService
from .segment import Segment, SegmentBuilder, merge_segments
from .translog import Translog


# Leak detection (ISSUE 14, the AssertingSearcher / mock-directory
# discipline): when armed (testing.chaos.detectors.arm(), wired into
# tests/conftest.py for the whole suite), Engine.close() ASSERTS that every
# acquired searcher handle was released and that every byte the engine
# charged to its breaker was handed back — naming the acquire site of each
# leak, plus the reproducing CHAOS_SEED when one is set.
LEAK_CHECK = False


def _seed_tag() -> str:
    seed = os.environ.get("CHAOS_SEED")
    return f" [CHAOS_SEED={seed}]" if seed else ""


class SearcherLeakError(AssertionError):
    """An engine closed with acquired-but-unreleased state (searcher
    handles or breaker charges). Only raised when leak checking is armed."""


class SearcherHandle:
    """A refcounted searcher acquisition (ref AssertingSearcher): the
    acquire site is recorded so a leak names the code that forgot to
    release, not just 'something leaked'."""

    __slots__ = ("engine", "site", "released")

    def __init__(self, engine: "Engine", site: str):
        self.engine = engine
        self.site = site
        self.released = False

    def release(self) -> None:
        if self.released:
            return
        self.released = True
        self.engine._open_searchers.pop(id(self), None)


class VersionConflictException(Exception):
    def __init__(self, doc_id: str, current: int, expected: int):
        super().__init__(
            f"version conflict for [{doc_id}]: current [{current}], provided [{expected}]")
        self.current = current
        self.expected = expected


class DocumentMissingException(Exception):
    pass


class EngineResult:
    """__slots__, not a dataclass: one is built per write op and the
    generated kwargs __init__ is measurable at bulk rates (ISSUE 7)."""

    __slots__ = ("doc_id", "version", "created", "found")

    def __init__(self, doc_id: str, version: int, created: bool,
                 found: bool = True):
        self.doc_id = doc_id
        self.version = version
        self.created = created
        self.found = found


@dataclass
class GetResult:
    found: bool
    doc_id: str
    version: int = -1
    source: dict | None = None
    type_name: str = "_doc"
    routing: str | None = None
    parent: str | None = None
    timestamp: int | None = None     # _timestamp metadata (epoch ms)
    ttl_expiry: int | None = None    # _ttl expiry instant (epoch ms)


def _rough_doc_bytes(source: dict) -> int:
    """Cheap buffered-source size estimate (IndexingMemoryController input;
    exactness doesn't matter — relative shard pressure does)."""
    try:
        n = 64
        for k, v in source.items():
            c = v.__class__
            n += len(k) + (len(v) if c is str
                           else 8 * len(v) if c is list else 16)
        return n
    except Exception:  # noqa: BLE001 — estimates must never raise
        return 256


def _segment_long(seg: Segment, field: str, local: int) -> int | None:
    """Host-cached read of an i64 metadata column (_timestamp/_ttl_expiry)."""
    nc = seg.numerics.get(field)
    if nc is None:
        return None
    vals = getattr(nc, "_vals_np2", None)
    if vals is None:
        vals = (np.asarray(nc.vals), np.asarray(nc.missing))
        object.__setattr__(nc, "_vals_np2", vals)
    v, miss = vals
    return None if miss[local] else int(v[local])


def _segment_parent(seg: Segment, local: int) -> str | None:
    """The doc's _parent id from the keyword column (host-cached ords)."""
    kc = seg.keywords.get("_parent")
    if kc is None:
        return None
    ords = getattr(kc, "_ords_np", None)
    if ords is None:
        ords = np.asarray(kc.ords)
        object.__setattr__(kc, "_ords_np", ords)
    o = int(ords[local])
    return kc.values[o] if o >= 0 else None


class Engine:
    """Versioned, durable per-shard engine over tensor segments."""

    MERGE_SEGMENT_COUNT = 8          # merge trigger (TieredMergePolicy-ish)
    # doc-count refresh trigger (indexing buffer analog) — a backstop; the
    # real bound is the node-wide BYTE budget (check_indexing_memory /
    # indices.memory.index_buffer_size), so this sits above the 100k-doc
    # bench tier: one bulk ingest freezes into ONE segment instead of
    # paying a mid-request refresh plus a 2-segment force-merge
    MAX_BUFFER_DOCS = 131072

    def __init__(self, shard_path: str, mappers: MapperService,
                 type_name_default: str = "_doc", durability: str = "request",
                 breaker=None, fielddata_cache=None, index_name=None,
                 vectorized: bool = True, ann_cache=None):
        self.path = shard_path
        self.mappers = mappers
        # the vectorized bulk-ingest lane (index/bulk_ingest.py): batched
        # analysis in index_batch + columnar add_batch at refresh. Off
        # (`index.bulk.vectorized.enable: false`) the engine runs the
        # per-doc path end to end — the equivalence suite's control lane.
        self.vectorized = vectorized
        # HBM accounting (common/breaker.py; ref HierarchyCircuitBreaker-
        # Service): segments charge the "fielddata" breaker at build time
        self.breaker = breaker
        # node-level fielddata tier (indices/cache_service.FielddataCache):
        # when attached, built sort columns live THERE (LRU, evictable
        # under breaker pressure) instead of pinned per-segment dicts
        self.fielddata_cache = fielddata_cache
        # node-level IVF cluster-index tier (AnnIndexCache): the ANN kNN
        # lane's centroids + CSR live there, dying with their segment
        self.ann_cache = ann_cache
        self.index_name = index_name
        self._blocked_reason = None
        os.makedirs(shard_path, exist_ok=True)
        from .store import SegmentStore
        self.store = SegmentStore(shard_path)
        self.translog = Translog(os.path.join(shard_path, "translog"), durability)
        self._lock = threading.RLock()
        self.segments: list[Segment] = []
        # deletes staged until the next refresh (NRT delete visibility);
        # the set mirror answers "is this copy stale?" for O(1) get checks
        self._pending_deletes: list[tuple] = []
        self._pending_set: set[tuple[int, int]] = set()
        self._buffer = SegmentBuilder(seg_id=0)
        # id -> (source, type, routing)
        # id -> (source, type, routing, parent, ParsedDocument)
        self._buffer_docs: dict[str, tuple] = {}
        # rough host bytes buffered (IndexingMemoryController's input);
        # per-doc estimates are remembered so eviction subtracts exactly
        # what admission added (the batch lane estimates from raw JSON
        # line length, the per-doc lane from a source-dict walk)
        self._buffer_bytes = 0
        self._buffer_sizes: dict[str, int] = {}
        self._next_seg_id = 1
        # LiveVersionMap: id -> (version, deleted)
        self.versions: dict[str, tuple[int, bool]] = {}
        self._dirty = False
        # monotonic mutation generations: `mutation_gen` bumps on EVERY
        # accepted write/delete; `percolator_gen` only when the registered
        # `.percolator` roster can have changed (a `.percolator` index, or
        # any delete — deletes don't carry a type). Cache tiers key on
        # these instead of buffer lengths, which alias across
        # delete-then-reinsert of the same count (ISSUE 18 bugfix).
        self.mutation_gen = 0
        self.percolator_gen = 0
        self.refresh_count = 0
        self.flush_count = 0
        self.merge_count = 0
        # leak-detector state (ISSUE 14): open searcher handles (id ->
        # handle) and the per-site breaker ledger — net bytes this engine
        # charged, keyed by the charge site; symmetric with every
        # add_estimate/release pair below, so close() can assert it drains
        self._open_searchers: dict[int, SearcherHandle] = {}
        self._charge_sites: dict[str, int] = {}
        self._closed = False
        self._load_commit()
        self._recover()

    # -- leak-detector seams (ISSUE 14) -----------------------------------

    def acquire_searcher(self, site: str = "?") -> SearcherHandle:
        """Acquire a refcounted searcher reference. The caller MUST call
        handle.release() when the searcher goes out of use; when leak
        checking is armed, close() fails naming `site` for every handle
        still open."""
        h = SearcherHandle(self, site)
        self._open_searchers[id(h)] = h
        return h

    def _ledger(self, site: str, delta: int) -> None:
        """Track the engine's own breaker traffic per charge site; a site
        that drains to zero leaves the ledger."""
        n = self._charge_sites.get(site, 0) + delta
        if n:
            self._charge_sites[site] = n
        else:
            self._charge_sites.pop(site, None)

    def _leak_check(self) -> None:
        problems = []
        for h in self._open_searchers.values():
            problems.append(f"searcher acquired at [{h.site}] never "
                            f"released")
        for site, n in sorted(self._charge_sites.items()):
            problems.append(f"breaker charge from [{site}] has {n} bytes "
                            f"outstanding")
        # cache-entry accounting: a closed engine's segments must not pin
        # fielddata / ANN cache entries (their removal listeners hand the
        # breaker charge back — an entry that survives leaks it forever)
        for s in self.segments:
            if self.fielddata_cache is not None:
                b = self.fielddata_cache.bytes_for(s)
                if b:
                    problems.append(
                        f"fielddata cache entries for segment "
                        f"{s.seg_id} survived close: {sorted(b)}")
        if problems:
            raise SearcherLeakError(
                f"engine [{self.path}] closed with leaks: "
                + "; ".join(problems) + _seed_tag())

    # -- recovery (translog replay, ref InternalEngine recoverFromTranslog) --

    def _load_commit(self) -> None:
        """Load the last commit point (gateway recovery analog, SURVEY §5.4b):
        binary segment files load directly onto device — no re-analysis, no
        re-tokenization; recovery cost is IO + device_put, not CPU parsing.
        Raises store.CorruptIndexException if any segment file fails its
        checksum (ref index/store/Store.java recovery verification)."""
        segments, tombstones = self.store.load()
        self.segments = segments
        for s in segments:
            self._adopt(s)              # fielddata loads charge it too
        if self.breaker is not None:
            # recovery loads regardless of pressure (unbreakable add) —
            # refusing to boot would lose availability, not memory
            for s in segments:
                self.breaker.add_estimate(s.memory_bytes(), check=False)
                self._ledger(f"segment:{s.seg_id}", s.memory_bytes())
        self._next_seg_id = max((s.seg_id for s in segments), default=0) + 1
        # rebuild the LiveVersionMap: manifest order is chronological, so
        # later segments override earlier ones for re-indexed docs
        for seg in segments:
            for local, doc_id in enumerate(seg.ids):
                if seg.live_host[local] \
                        and not seg.types[local].startswith("__"):
                    self.versions[doc_id] = (seg.versions[local], False)
        for doc_id, v in tombstones.items():
            self.versions[doc_id] = (int(v), True)

    def _recover(self) -> None:
        n = 0
        for op in self.translog.snapshot():
            kind = op["op"]
            if kind == "index":
                from ..mapping.mapper import AlreadyExpiredException
                try:
                    self._apply_index(op["id"], op["source"],
                                      op.get("type", "_doc"),
                                      version=op["version"],
                                      routing=op.get("routing"),
                                      parent=op.get("parent"),
                                      timestamp=op.get("ts"),
                                      ttl=op.get("ttl"))
                except AlreadyExpiredException:
                    continue    # the doc's TTL lapsed while we were down
            elif kind == "delete":
                self._apply_delete(op["id"], version=op["version"])
            n += 1
        if n:
            self.refresh()

    # -- version resolution ------------------------------------------------

    def current_version(self, doc_id: str) -> int:
        """-1 = not found; otherwise the live version."""
        v = self.versions.get(doc_id)
        if v is None or v[1]:
            return -1
        return v[0]

    def _check_version(self, doc_id: str, version: int | None,
                       version_type: str, op_type: str) -> int:
        """Returns the new version; raises VersionConflictException
        (ref InternalEngine.java:233-339 create/index/delete w/ conflicts)."""
        return self._resolve_version(self.versions.get(doc_id), doc_id,
                                     version, version_type, op_type)

    def _resolve_version(self, raw: tuple[int, bool] | None, doc_id: str,
                         version: int | None, version_type: str,
                         op_type: str) -> int:
        """_check_version over an explicit (version, deleted) state — the
        batch lane resolves against its in-flight overlay so duplicate
        ids WITHIN one bulk request see each other's versions."""
        cur = -1 if raw is None or raw[1] else raw[0]
        if op_type == "create" and cur != -1:
            raise VersionConflictException(doc_id, cur, -1)
        if version is None or version in (-1, -3):  # MATCH_ANY / internal
            # version continues across delete tombstones, like the
            # reference's LiveVersionMap (delete v2 -> reindex v3)
            return raw[0] + 1 if raw is not None else 1
        if version_type == "external":
            if raw is not None and version <= raw[0]:
                raise VersionConflictException(doc_id, raw[0], version)
            return version
        if version_type == "external_gte":
            # >= is acceptable (ref VersionType.EXTERNAL_GTE)
            if raw is not None and version < raw[0]:
                raise VersionConflictException(doc_id, raw[0], version)
            return version
        if version_type == "force":
            return version          # ref VersionType.FORCE: always wins
        # internal: provided version must equal current
        if cur != version:
            raise VersionConflictException(doc_id, cur, version)
        return cur + 1

    # -- write ops ---------------------------------------------------------

    def index(self, doc_id: str, source: dict, type_name: str = "_doc",
              version: int | None = None, version_type: str = "internal",
              op_type: str = "index", sync: bool | None = None,
              routing: str | None = None,
              parent: str | None = None,
              timestamp=None, ttl=None) -> EngineResult:
        with self._lock:
            if self._blocked_reason is not None \
                    or len(self._buffer_docs) >= self.MAX_BUFFER_DOCS:
                # flush-or-reject happens BEFORE this write applies: a
                # breaker trip here is a clean 429 with no partial state
                # (the doc is neither buffered nor in the translog), and a
                # previously-blocked engine re-attempts the refresh in case
                # the budget was freed
                self.refresh()
            new_version = self._check_version(doc_id, version, version_type, op_type)
            created = self.current_version(doc_id) == -1
            if timestamp is None:
                # resolve NOW so translog replay reproduces the same value
                timestamp = int(time.time() * 1000)
            self._apply_index(doc_id, source, type_name, new_version, routing,
                              parent, timestamp, ttl)
            op = {"op": "index", "id": doc_id, "type": type_name,
                  "source": source, "version": new_version,
                  "routing": routing, "ts": timestamp}
            if parent is not None:
                op["parent"] = parent
            if ttl is not None:
                op["ttl"] = ttl
            self.translog.add(op, sync=sync)
            return EngineResult(doc_id=doc_id, version=new_version, created=created)

    def _apply_index(self, doc_id: str, source: dict, type_name: str,
                     version: int, routing: str | None = None,
                     parent: str | None = None,
                     timestamp=None, ttl=None) -> None:
        # parse NOW, not at refresh: a malformed doc (bad date, missing
        # parent, wrong vector dims) must 400 this request — parsing lazily
        # would poison the shared refresh instead (ref IndexShard.prepareIndex
        # parses before the engine op; code review r5)
        mapper = self.mappers.document_mapper(type_name)
        parsed = mapper.parse(source, doc_id=doc_id, routing=routing,
                              parent=parent, timestamp=timestamp, ttl=ttl)
        self._delete_everywhere(doc_id)   # pops any buffered predecessor
        self._buffer_docs[doc_id] = (source, type_name, routing, parent,
                                     parsed)
        est = _rough_doc_bytes(source)
        self._buffer_sizes[doc_id] = est
        self._buffer_bytes += est
        self.versions[doc_id] = (version, False)
        self._dirty = True
        self.mutation_gen += 1
        if type_name == ".percolator":
            self.percolator_gen += 1

    def delete(self, doc_id: str, version: int | None = None,
               version_type: str = "internal",
               sync: bool | None = None) -> EngineResult:
        with self._lock:
            cur = self.current_version(doc_id)
            found = cur != -1
            new_version = self._check_version(doc_id, version, version_type, "delete") \
                if found or version is not None else 1
            self._apply_delete(doc_id, new_version)
            self.translog.add({"op": "delete", "id": doc_id,
                               "version": new_version}, sync=sync)
            return EngineResult(doc_id=doc_id, version=new_version,
                                created=False, found=found)

    def _apply_delete(self, doc_id: str, version: int) -> None:
        self._delete_everywhere(doc_id)
        self.versions[doc_id] = (version, True)
        self._dirty = True
        self.mutation_gen += 1
        self.percolator_gen += 1

    # -- batched write path (the vectorized bulk lane, ISSUE 7) ------------

    BULK_CHUNK = 16384               # ops per batched pass (< MAX_BUFFER_DOCS)

    def index_batch(self, ops, sync: bool | None = None) -> list:
        """Apply a run of BulkOps (index/create/delete) as ONE batched pass
        per chunk: sequential version resolution against an in-flight
        overlay (duplicate ids within the request see each other), per-doc
        mapper.parse with DEFERRED text analysis, one grouped batch-analysis
        flush, then buffer mutations plus a single group-commit translog
        write (ref TransportShardBulkAction.java:133 — the reference's
        shard-level bulk pass with one fsync per request).

        Returns a list aligned with `ops`: EngineResult on success, the
        raised exception object on per-item failure (the caller maps
        VersionConflict->409 / parse errors->400 / breaker->429)."""
        from .bulk_ingest import TextBatcher
        results: list = [None] * len(ops)
        wrote = False
        with self._lock:
            for c0 in range(0, len(ops), self.BULK_CHUNK):
                chunk = ops[c0:c0 + self.BULK_CHUNK]
                if self._blocked_reason is not None \
                        or len(self._buffer_docs) + len(chunk) \
                        > self.MAX_BUFFER_DOCS:
                    try:
                        self.refresh()
                    except Exception as e:  # noqa: BLE001 — per-item 429s
                        for i in range(len(chunk)):
                            results[c0 + i] = e
                        continue
                batcher = TextBatcher()
                overlay: dict[str, tuple[int, bool]] = {}
                overlay_get = overlay.get
                versions_get = self.versions.get
                type_mappers: dict = {}
                # one wall-clock read per chunk: every doc of a batched
                # pass stamps the same _timestamp (the per-doc path's
                # per-op ms resolution collapses to chunk resolution;
                # translog replay reproduces the stored value either way)
                now_ms = int(time.time() * 1000)
                # (global_i, op, new_version, parsed|None, created/found, ts)
                staged: list[tuple] = []
                stage = staged.append
                for i, op in enumerate(chunk):
                    gi = c0 + i
                    doc_id = op.doc_id
                    raw = overlay_get(doc_id) or versions_get(doc_id)
                    try:
                        action = op.action
                        if action == "delete":
                            found = raw is not None and not raw[1]
                            nv = self._resolve_version(
                                raw, doc_id, op.version, op.version_type,
                                "delete") \
                                if found or op.version is not None else 1
                            overlay[doc_id] = (nv, True)
                            stage((gi, op, nv, None, found, None))
                            continue
                        if op.version is None:
                            # MATCH_ANY fast path (the bulk-typical shape):
                            # no per-op _resolve_version call
                            if action == "create" and raw is not None \
                                    and not raw[1]:
                                raise VersionConflictException(
                                    doc_id, raw[0], -1)
                            nv = raw[0] + 1 if raw is not None else 1
                        else:
                            nv = self._resolve_version(
                                raw, doc_id, op.version, op.version_type,
                                "create" if action == "create" else "index")
                        created = raw is None or raw[1]
                        ts = op.timestamp
                        if ts is None:
                            # resolve NOW so translog replay reproduces it
                            ts = now_ms
                        mapper = type_mappers.get(op.type_name)
                        if mapper is None:
                            mapper = type_mappers[op.type_name] = \
                                self.mappers.document_mapper(op.type_name)
                        # positional call: 7 kwarg bindings cost ~0.5µs/doc
                        parsed = mapper.parse(op.source, doc_id, op.routing,
                                              op.parent, ts, op.ttl, batcher)
                        overlay[doc_id] = (nv, False)
                        stage((gi, op, nv, parsed, created, ts))
                    except Exception as e:  # noqa: BLE001 — per-item
                        results[gi] = e
                failed = batcher.flush()
                records: list[dict] = []
                for gi, op, nv, parsed, flag, ts in staged:
                    if parsed is not None and id(parsed) in failed:
                        results[gi] = failed[id(parsed)]
                        continue
                    doc_id = op.doc_id
                    if op.action == "delete":
                        self._apply_delete(doc_id, nv)
                        records.append({"op": "delete", "id": doc_id,
                                        "version": nv})
                        results[gi] = EngineResult(
                            doc_id=doc_id, version=nv, created=False,
                            found=flag)
                        continue
                    # _apply_index minus the (already done) parse
                    self._delete_everywhere(doc_id)
                    self._buffer_docs[doc_id] = (op.source, op.type_name,
                                                 op.routing, op.parent,
                                                 parsed)
                    # REST-lane ops carry the raw JSON line length — a
                    # better estimate than the dict walk, and free
                    est = op.raw_len or _rough_doc_bytes(op.source)
                    self._buffer_sizes[doc_id] = est
                    self._buffer_bytes += est
                    self.versions[doc_id] = (nv, False)
                    self._dirty = True
                    self.mutation_gen += 1
                    if op.type_name == ".percolator":
                        self.percolator_gen += 1
                    rec = {"op": "index", "id": doc_id,
                           "type": op.type_name, "source": op.source,
                           "version": nv, "routing": op.routing, "ts": ts}
                    if op.parent is not None:
                        rec["parent"] = op.parent
                    if op.ttl is not None:
                        rec["ttl"] = op.ttl
                    records.append(rec)
                    results[gi] = EngineResult(doc_id=doc_id, version=nv,
                                               created=flag)
                if records:
                    self.translog.add_batch(records, sync=False)
                    wrote = True
                self._analysis_batched = getattr(
                    self, "_analysis_batched", 0) + batcher.batched_values
                self._analysis_fallback = getattr(
                    self, "_analysis_fallback", 0) + batcher.fallback_values
            if wrote:
                if sync is None:
                    sync = self.translog.durability == "request"
                if sync:
                    self.translog.sync()
        return results

    def _delete_everywhere(self, doc_id: str) -> None:
        """Remove from the write buffer now; segment tombstones are
        DEFERRED to the next refresh — deletes are invisible to search
        until a new searcher, exactly the NRT contract (realtime GET sees
        them immediately through the version map; ref InternalEngine
        delete + refresh visibility)."""
        popped = self._buffer_docs.pop(doc_id, None)
        if popped is not None:
            est = self._buffer_sizes.pop(doc_id, None)
            self._buffer_bytes -= est if est is not None \
                else _rough_doc_bytes(popped[0])
        for seg in self.segments:
            local = seg.id_to_local.get(doc_id)
            if local is not None and seg.live_host[local]:
                self._pending_deletes.append((seg, local))
                self._pending_set.add((seg.seg_id, local))

    # -- read ops ----------------------------------------------------------

    def get(self, doc_id: str, realtime: bool = True) -> GetResult:
        """Realtime get: buffer first (translog-analog read,
        ref index/get/ShardGetService.java:66-99), then segments."""
        with self._lock:
            v = self.versions.get(doc_id)
            if v is None or v[1]:
                return GetResult(found=False, doc_id=doc_id)
            version = v[0]
            if realtime and doc_id in self._buffer_docs:
                src, tname, routing, parent, parsed = \
                    self._buffer_docs[doc_id]
                ts = parsed.longs.get("_timestamp")
                ex = parsed.longs.get("_ttl_expiry")
                return GetResult(found=True, doc_id=doc_id, version=version,
                                 source=src, type_name=tname,
                                 routing=routing, parent=parent,
                                 timestamp=ts[0] if ts else None,
                                 ttl_expiry=ex[0] if ex else None)
            for seg in self.segments:
                local = seg.id_to_local.get(doc_id)
                if local is not None and seg.live_host[local] \
                        and (seg.seg_id, local) not in self._pending_set:
                    # a pending-delete copy is stale: returning it would
                    # pair the OLD source with the NEW version (review r5)
                    return GetResult(found=True, doc_id=doc_id, version=version,
                                     source=seg.stored[local],
                                     type_name=seg.types[local],
                                     routing=seg.routings[local]
                                     if seg.routings else None,
                                     parent=_segment_parent(seg, local),
                                     timestamp=_segment_long(
                                         seg, "_timestamp", local),
                                     ttl_expiry=_segment_long(
                                         seg, "_ttl_expiry", local))
            # non-realtime get sees only refreshed (searchable) state — an
            # unrefreshed buffer doc is a miss (ref ShardGetService contract)
            return GetResult(found=False, doc_id=doc_id)

    # -- refresh / flush / merge ------------------------------------------

    def refresh(self) -> None:
        """Freeze the write buffer into a new device segment — the NRT
        'new searcher' event (ref InternalEngine refresh, default 1s).
        Charges the segment's device bytes against the breaker; a breach
        keeps the buffer, marks the engine write-blocked, and raises
        CircuitBreakingException (HTTP 429) — never an OOM."""
        with self._lock:
            if self._pending_deletes:
                for seg, local in self._pending_deletes:
                    seg.delete_local(local)
                self._pending_deletes.clear()
                self._pending_set.clear()
                self._maybe_merge()
            self._drop_dead_segments()
            if not self._buffer_docs:
                return
            builder = SegmentBuilder(seg_id=self._next_seg_id)
            if self.vectorized:
                # columnar lane: contiguous runs of non-nested docs append
                # through add_batch (one lexsort per field at build instead
                # of per-token dict work); nested blocks keep the per-doc
                # path so block-join row order is untouched. Runs preserve
                # buffer order, so local ids match the per-doc loop.
                run: list[tuple] = []
                for doc_id, (_src, tname, _routing, _parent, parsed) \
                        in self._buffer_docs.items():
                    v = self.versions[doc_id][0]
                    if parsed.nested:
                        if run:
                            builder.add_batch(run)
                            run = []
                        builder.add(parsed, tname, version=v)
                    else:
                        run.append((parsed, tname, v))
                if run:
                    builder.add_batch(run)
            else:
                for doc_id, (_src, tname, _routing, _parent, parsed) \
                        in self._buffer_docs.items():
                    builder.add(parsed, tname,
                                version=self.versions[doc_id][0])
            site = f"segment:{self._next_seg_id}"
            if self.breaker is not None:
                # charge BEFORE build() uploads device arrays: a tripped
                # breaker prevents the allocation itself, not just the
                # accounting (advisor r4). Estimate mirrors memory_bytes().
                est = builder.estimate_bytes()
                try:
                    self.breaker.add_estimate(est)
                except Exception as e:
                    self._blocked_reason = e
                    raise
                self._ledger(site, est)
            try:
                seg = builder.build()
            except BaseException:
                # device upload failed — undo the charge or the breaker
                # ratchets up on every retried refresh
                if self.breaker is not None:
                    self.breaker.release(est)
                    self._ledger(site, -est)
                raise
            if self.breaker is not None:
                # true up any estimate drift without re-tripping
                drift = seg.memory_bytes() - est
                if drift > 0:
                    self.breaker.add_estimate(drift, check=False)
                elif drift < 0:
                    self.breaker.release(-drift)
                self._ledger(site, drift)
            self._blocked_reason = None
            self._next_seg_id += 1
            self._adopt(seg)
            self.segments.append(seg)
            self._buffer_docs.clear()
            self._buffer_sizes.clear()
            self._buffer_bytes = 0
            self.refresh_count += 1
            self._maybe_merge()

    def _drop_dead_segments(self) -> None:
        """Dead-empty segments (zero live docs — fully tombstoned, or an
        empty load) leave the segment set at refresh: searchers stop
        paying per-query empty checks for them, their device bytes go
        back to the breaker, and loaded fielddata dies with them."""
        dead = [s for s in self.segments if s.live_count == 0]
        if not dead:
            return
        self.segments = [s for s in self.segments if s.live_count > 0]
        if self.breaker is not None:
            self.breaker.release(sum(s.memory_bytes() for s in dead))
            for s in dead:
                self._ledger(f"segment:{s.seg_id}", -s.memory_bytes())
        self._drop_fielddata(dead)

    def _maybe_merge(self) -> None:
        """Size-tiered merge selection (ref index/merge/policy/
        LogMergePolicy: segments in the same log_{factor}(size) tier merge
        when the tier fills) — small merges stay small; the corpus is never
        re-merged all-to-one on every trigger."""
        factor = self.MERGE_SEGMENT_COUNT
        tiers: dict[int, list[Segment]] = {}
        for seg in self.segments:
            t = int(math.log(max(seg.live_count, 1), factor))
            tiers.setdefault(t, []).append(seg)
        for t in sorted(tiers):
            if len(tiers[t]) >= factor:
                self._merge_subset(tiers[t])
                return   # one merge per trigger keeps refresh latency flat

    def _merge_subset(self, subset: list[Segment]) -> None:
        chosen = set(id(s) for s in subset)
        merged = merge_segments(subset, self._next_seg_id)
        self._charge_merge(merged, subset)
        self._next_seg_id += 1
        out: list[Segment] = []
        placed = False
        for s in self.segments:
            if id(s) in chosen:
                if not placed and merged.n_docs:
                    self._adopt(merged)
                    out.append(merged)
                    placed = True
            else:
                out.append(s)
        self.segments = out
        self.merge_count += 1

    def force_merge(self, max_num_segments: int = 1) -> None:
        """Merge segments (ref index/merge/ TieredMergePolicy + optimize API)."""
        with self._lock:
            self.refresh()     # staged docs AND deferred deletes first
            if len(self.segments) <= max_num_segments:
                # may still want to purge deletes
                if not any(s.live_count < s.n_docs for s in self.segments):
                    return
            merged = merge_segments(self.segments, self._next_seg_id)
            self._charge_merge(merged, self.segments)
            self._next_seg_id += 1
            self._adopt(merged)
            self.segments = [merged] if merged.n_docs else []
            self.merge_count += 1

    def _adopt(self, seg: Segment) -> None:
        """Stamp a segment with this shard's accounting hooks: the breaker
        its device bytes/fielddata charge, the node fielddata cache its
        sort columns live in, and the index name cache entries carry (so
        `_cache/clear?index=` can target them)."""
        seg.breaker = self.breaker
        seg.fielddata_cache = self.fielddata_cache
        seg.ann_cache = self.ann_cache
        seg.index_name = self.index_name

    def _drop_fielddata(self, sources: list[Segment]) -> None:
        """Loaded fielddata dies with its source segments: cache-managed
        columns invalidate through the cache (its removal listener hands
        bytes back to the breaker); legacy per-segment dicts release
        directly."""
        for s in sources:
            if getattr(s, "fielddata_cache", None) is not None:
                s.fielddata_cache.drop_segment(s)
            elif self.breaker is not None:
                self.breaker.release(sum(s.fielddata_bytes().values()))
            if getattr(s, "ann_cache", None) is not None:
                s.ann_cache.drop_segment(s)

    def _charge_merge(self, merged: Segment, sources: list[Segment]) -> None:
        """Swap breaker accounting from the source segments to the merged
        one (the merged set is usually smaller: tombstones purged). An
        all-tombstoned merge result is DROPPED by the callers, so it must
        not be charged — that leaked phantom bytes for the node lifetime."""
        if self.breaker is not None:
            if merged.n_docs:
                self.breaker.add_estimate(merged.memory_bytes(), check=False)
                self._ledger(f"segment:{merged.seg_id}",
                             merged.memory_bytes())
            self.breaker.release(sum(s.memory_bytes() for s in sources))
            for s in sources:
                self._ledger(f"segment:{s.seg_id}", -s.memory_bytes())
        self._drop_fielddata(sources)

    def flush(self) -> None:
        """Commit: write NEW segment files + the checksummed commit point,
        roll + trim translog (ref InternalEngine.flush -> Lucene commit +
        translog roll). Already-persisted segments are untouched — flush cost
        is O(new docs + deletes), independent of corpus size."""
        with self._lock:
            self.refresh()
            gen = self.translog.roll()
            tombstones = {k: v[0] for k, v in self.versions.items() if v[1]}
            self.store.commit(self.segments, tombstones)
            self.translog.trim(gen)
            self.flush_count += 1

    @staticmethod
    def open_committed(shard_path: str, mappers: MapperService, **kw) -> "Engine":
        """Recover an engine: committed state + translog replay on top.
        (The plain constructor performs the same recovery; kept as the
        explicit-recovery entry point.)"""
        eng = Engine(shard_path, mappers,
                     durability=kw.get("durability", "request"))
        eng.refresh()
        return eng

    # -- stats / introspection --------------------------------------------

    def doc_count(self) -> int:
        with self._lock:
            # root docs only — nested block rows are an implementation
            # detail of the block join, not user documents
            return sum(s.root_live_count for s in self.segments) \
                + len(self._buffer_docs)

    def segment_stats(self) -> dict:
        return {"count": len(self.segments),
                "docs": sum(s.live_count for s in self.segments),
                "deleted": sum(s.n_docs - s.live_count for s in self.segments),
                "memory_in_bytes": sum(s.memory_bytes() for s in self.segments),
                "buffered_docs": len(self._buffer_docs)}

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return          # idempotent: a second close must not
            self._closed = True  # double-release the breaker charges
        if self.breaker is not None:
            self.breaker.release(sum(s.memory_bytes()
                                     for s in self.segments))
            for s in self.segments:
                self._ledger(f"segment:{s.seg_id}", -s.memory_bytes())
        self._drop_fielddata(self.segments)
        self.translog.close()
        if LEAK_CHECK:
            self._leak_check()
