"""Pluggable similarity: per-field scoring configuration.

Analog of /root/reference/src/main/java/org/elasticsearch/index/similarity/
SimilarityService.java:36 + SimilarityModule: named similarity configs from
index settings (index.similarity.<name>.type/k1/b), resolved per field via
the mapping's "similarity" property.

Supported types:
  BM25 (default)  — parameterized k1/b; the sparse/packed device kernels
                    take k1/b as runtime scalars, so custom-parameter BM25
                    fields keep the fast lanes (plans group by (field,k1,b)).
  classic/default — Lucene ClassicSimilarity (TF-IDF): sqrt(tf) * idf^2
                    with 1/sqrt(dl) length norm; scored by a dedicated
                    dense kernel (ops/bm25.classic_score_batch) — the
                    sparse/packed lanes decline these fields.
  LMDirichlet     — language model with Dirichlet smoothing (`mu`,
                    default 2000): ops/bm25.lm_dirichlet_score_batch. The
                    collection probability p(t|C) is a precomputed
                    per-term weight (CollectionStats.pcoll), so the device
                    cost matches BM25's. Dense-lane only (sparse/stacked/
                    blockwise/mesh decline — plans group by (sim, mu)).
  LMJelinekMercer — language model with Jelinek-Mercer smoothing
                    (`lambda`, default 0.1): ops/bm25.lm_jm_score_batch.
                    Same lane contract as LMDirichlet.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Similarity:
    type: str = "BM25"        # "BM25" | "classic" | "LMDirichlet" | "LMJelinekMercer"
    k1: float = 1.2
    b: float = 0.75
    mu: float = 2000.0        # LMDirichlet smoothing
    lam: float = 0.1          # LMJelinekMercer smoothing


DEFAULT = Similarity()
CLASSIC = Similarity(type="classic")
LM_SIMS = ("lm_dirichlet", "lm_jm")   # MatchNode.sim tags (query_dsl)

_SIM_TAG = {"LMDirichlet": "lm_dirichlet", "LMJelinekMercer": "lm_jm"}


def sim_tag(sim: Similarity) -> str:
    """The MatchNode.sim tag a Similarity scores under."""
    return _SIM_TAG.get(sim.type, sim.type)


class SimilarityService:
    """Named similarity registry for one index."""

    def __init__(self, settings=None):
        self.named: dict[str, Similarity] = {
            "BM25": DEFAULT, "default": CLASSIC, "classic": CLASSIC,
            "LMDirichlet": Similarity(type="LMDirichlet"),
            "LMJelinekMercer": Similarity(type="LMJelinekMercer")}
        if settings is not None and hasattr(settings, "by_prefix"):
            for prefix in ("index.similarity.", "similarity."):
                sims = settings.by_prefix(prefix)
                names = {k.split(".")[0] for k in sims}
                for name in names:
                    sub = sims.by_prefix(name + ".")
                    stype = sub.get_str("type", "BM25")
                    if stype in ("classic", "default"):
                        self.named[name] = CLASSIC
                    elif stype == "LMDirichlet":
                        self.named[name] = Similarity(
                            type="LMDirichlet",
                            mu=sub.get_float("mu", 2000.0))
                    elif stype == "LMJelinekMercer":
                        self.named[name] = Similarity(
                            type="LMJelinekMercer",
                            lam=sub.get_float("lambda", 0.1))
                    else:
                        self.named[name] = Similarity(
                            type="BM25",
                            k1=sub.get_float("k1", 1.2),
                            b=sub.get_float("b", 0.75))

    def resolve(self, name: str | None) -> Similarity:
        if name is None:
            return DEFAULT
        return self.named.get(name, DEFAULT)

    def for_field(self, mappers, field: str) -> Similarity:
        """The similarity a text field scores with: the mapping's
        "similarity" property resolved through the named registry."""
        ft = mappers.field_type(field) if mappers is not None else None
        sim_name = getattr(ft, "similarity", None) if ft is not None else None
        return self.resolve(sim_name)
