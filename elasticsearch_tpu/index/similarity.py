"""Pluggable similarity: per-field scoring configuration.

Analog of /root/reference/src/main/java/org/elasticsearch/index/similarity/
SimilarityService.java:36 + SimilarityModule: named similarity configs from
index settings (index.similarity.<name>.type/k1/b), resolved per field via
the mapping's "similarity" property.

Supported types:
  BM25 (default)  — parameterized k1/b; the sparse/packed device kernels
                    take k1/b as runtime scalars, so custom-parameter BM25
                    fields keep the fast lanes (plans group by (field,k1,b)).
  classic/default — Lucene ClassicSimilarity (TF-IDF): sqrt(tf) * idf^2
                    with 1/sqrt(dl) length norm; scored by a dedicated
                    dense kernel (ops/bm25.classic_score_batch) — the
                    sparse/packed lanes decline these fields.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Similarity:
    type: str = "BM25"        # "BM25" | "classic"
    k1: float = 1.2
    b: float = 0.75


DEFAULT = Similarity()
CLASSIC = Similarity(type="classic")


class SimilarityService:
    """Named similarity registry for one index."""

    def __init__(self, settings=None):
        self.named: dict[str, Similarity] = {
            "BM25": DEFAULT, "default": CLASSIC, "classic": CLASSIC}
        if settings is not None and hasattr(settings, "by_prefix"):
            for prefix in ("index.similarity.", "similarity."):
                sims = settings.by_prefix(prefix)
                names = {k.split(".")[0] for k in sims}
                for name in names:
                    sub = sims.by_prefix(name + ".")
                    stype = sub.get_str("type", "BM25")
                    if stype in ("classic", "default"):
                        self.named[name] = CLASSIC
                    else:
                        self.named[name] = Similarity(
                            type="BM25",
                            k1=sub.get_float("k1", 1.2),
                            b=sub.get_float("b", 0.75))

    def resolve(self, name: str | None) -> Similarity:
        if name is None:
            return DEFAULT
        return self.named.get(name, DEFAULT)

    def for_field(self, mappers, field: str) -> Similarity:
        """The similarity a text field scores with: the mapping's
        "similarity" property resolved through the named registry."""
        ft = mappers.field_type(field) if mappers is not None else None
        sim_name = getattr(ft, "similarity", None) if ft is not None else None
        return self.resolve(sim_name)
