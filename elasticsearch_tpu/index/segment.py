"""Immutable tensor segments — the Lucene-segment analog, resident on device.

A segment is an immutable batch of documents (SURVEY.md §7 core bet):
  * text field   -> CSR postings tensors (term_offsets host-side, doc_ids/tf
                    on device) + per-doc field length (norms analog)
  * keyword      -> ordinal column i32[N] (+ host ord<->value tables) — the
                    global-ordinals analog (ref index/fielddata/ordinals/)
  * long/date/ip -> i64 column + missing mask (doc-values analog,
                    ref index/fielddata/plain/)
  * double/float -> f64 column + missing mask
  * dense_vector -> f32[N, dims] matrix for kNN / function_score
  * _source      -> host-side stored documents (fetch phase is host IO,
                    like the reference's stored-fields reads)
  * live         -> tombstone bitmap for deletes (Lucene liveDocs analog)

All device arrays are padded to size buckets (next power of two) so XLA
compile caches stay small while segments grow (SURVEY.md §7 hard part (e)).

Mutability model mirrors Lucene: segments are write-once; deletes only flip
the tombstone bitmap; updates are delete+reinsert into a newer segment; merges
rebuild (index/engine.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Iterable

import numpy as np
import jax
import jax.numpy as jnp

import threading

from ..mapping.mapper import (
    ParsedDocument, FieldType, TEXT, KEYWORD, DATE, BOOLEAN, IP,
    NUMERIC_TYPES, _INT_TYPES, DENSE_VECTOR,
)
from ..ops.bm25_sparse import required_padding

# serializes fielddata builds across segments (see Segment.text_fielddata)
_FIELDDATA_LOCK = threading.Lock()


# hard cap on token positions per doc: phrase verification packs positions
# as doc * 2^21 + (pos - offset + 2^10), so pos + bias must stay < 2^21
# (search/query_dsl.py _POS_SHIFT / _POS_BIAS)
_MAX_DOC_POSITIONS = (1 << 21) - (1 << 11)


def next_pow2(n: int, floor: int = 8) -> int:
    n = max(n, floor)
    return 1 << (n - 1).bit_length()


def pad_to(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    if arr.shape[0] >= size:
        return arr
    pad_shape = (size - arr.shape[0],) + arr.shape[1:]
    return np.concatenate([arr, np.full(pad_shape, fill, dtype=arr.dtype)], axis=0)


# ---------------------------------------------------------------------------
# Per-field device structures
# ---------------------------------------------------------------------------

@dataclass
class TextFieldIndex:
    """CSR postings for one text field (ref: Lucene postings lists, consumed
    by ops/bm25.py (dense) and ops/bm25_sparse.py (sort-reduce hot path)
    instead of BulkScorer)."""
    terms: dict[str, int]            # term -> term id (lexicographic)
    term_starts: np.ndarray          # i32[V] host: CSR starts
    term_lens: np.ndarray            # i32[V] host: postings length == df
    doc_ids: jax.Array               # i32[P_pad] device
    tf: jax.Array                    # f32[P_pad] device
    doc_len: jax.Array               # f32[N_pad] device
    dl: jax.Array                    # f32[P_pad] device: per-POSTING doc len
                                     # (denormalized so the sparse kernel
                                     # needs no doc_len[doc] gather)
    sum_dl: float                    # Σ field length (for avgdl)
    n_postings: int                  # un-padded P
    max_df: int = 0                  # largest postings list (slot budgeting)
    # positions (Lucene .pos analog): per-posting slice into a flat
    # occurrence array. Host-side — phrase verification runs over candidate
    # postings slices, not the whole corpus. None when loaded from a commit
    # written before positions existed (phrase degrades to AND).
    doc_ids_host: np.ndarray | None = None   # i32[P] host mirror
    pos_starts: np.ndarray | None = None     # i32[P] into positions[]
    pos_lens: np.ndarray | None = None       # i32[P] == tf
    positions: np.ndarray | None = None      # i32[O] token positions

    def lookup(self, term: str) -> tuple[int, int, int]:
        """-> (start, length==df, term_id) or (0, 0, -1) if absent."""
        tid = self.terms.get(term, -1)
        if tid < 0:
            return 0, 0, -1
        return int(self.term_starts[tid]), int(self.term_lens[tid]), tid

    def term_range(self, lo: str | None, hi: str | None,
                   include_lo=True, include_hi=True, prefix: str | None = None,
                   limit: int = 1024) -> list[str]:
        """Terms in lexicographic range / with prefix (wildcard & range-on-text
        support). Host-side over the sorted term dict."""
        out = []
        for t in self.terms:  # insertion order == lexicographic (built sorted)
            if prefix is not None:
                if t.startswith(prefix):
                    out.append(t)
                elif out:
                    break
                continue
            if lo is not None and (t < lo or (not include_lo and t == lo)):
                continue
            if hi is not None and (t > hi or (not include_hi and t == hi)):
                break
            out.append(t)
            if len(out) >= limit:
                break
        return out


@dataclass
class KeywordColumn:
    """Ordinal-encoded keyword column (ref: index/fielddata ordinals)."""
    ord_map: dict[str, int]          # value -> ordinal (lexicographic)
    values: list[str]                # ordinal -> value
    ords: jax.Array                  # i32[N_pad], -1 = missing

    def ord_of(self, value: str) -> int:
        return self.ord_map.get(value, -1)


@dataclass
class NumericColumn:
    """Dense numeric doc-values column. i64 for long/date/ip/bool, f64 for
    double/float (x64 enabled in package __init__; TPU-hot paths cast to f32)."""
    vals: jax.Array                  # [N_pad]
    missing: jax.Array               # bool[N_pad]
    dtype: str                       # "i64" | "f64"


@dataclass
class IvfData:
    """IVF cluster layout for one vector column (ops/ann.py): k-means
    centroids + a cluster->doc CSR in exactly the postings layout text
    fields use — clusters are "terms", members sorted by doc id. Built
    once per (segment, field, nlist), cached breaker-charged in
    indices/cache_service.AnnIndexCache."""
    centroids: jax.Array             # f32[nlist, dims]
    starts: jax.Array                # i32[nlist]  CSR starts (device)
    sizes: jax.Array                 # i32[nlist]  cluster sizes (device)
    slot_docs: jax.Array             # i32[N_pad]  docs sorted by (cluster, doc)
    norms: jax.Array                 # f32[N_pad]  per-doc L2 norms
    sizes_desc_cum: np.ndarray       # i64[nlist]  cumsum of sizes, desc
    nlist: int
    n_docs: int
    dims: int
    nbytes: int


@dataclass
class QuantData:
    """Quantized storage tier for one vector column's IVF cluster scan
    (ops/ann.py, ISSUE 12): int8 per-dimension affine codes (1/4 the f32
    bytes) or IVF-PQ residual codes (m bytes/vector, 1/(4·D/m)). Built
    once per (segment, field, nlist, mode, m), cached breaker-charged in
    indices/cache_service.AnnIndexCache's `ann_quant` tier — codes and
    codebooks account as SEPARATE entries so the exposition shows both."""
    mode: str                        # "int8" | "pq"
    codes: jax.Array                 # i8[N_pad, D] (int8) | u8[N_pad, m] (pq)
    scales: jax.Array | None         # f32[D]           (int8)
    codebooks: jax.Array | None      # f32[m, 256, dsub] (pq)
    m: int                           # subquantizers (pq; 0 for int8)
    nlist: int                       # the IVF layout this encodes against
    codes_nbytes: int
    books_nbytes: int

    @property
    def nbytes(self) -> int:
        return self.codes_nbytes + self.books_nbytes


@dataclass
class VectorColumn:
    vecs: jax.Array                  # f32[N_pad, dims]
    dims: int

    def build_ivf(self, n_docs: int, nlist: int | None = None, *,
                  iters: int | None = None) -> "IvfData | None":
        """Train k-means centroids (device Lloyd iterations over a
        deterministic sample) and build the cluster->doc CSR with ONE
        composite-key argsort. None when the column is too small to
        cluster usefully (callers fall back to exact kNN)."""
        from ..ops import ann as ann_ops
        n_pad = int(self.vecs.shape[0])
        if nlist is None:
            nlist = ann_ops.auto_nlist(n_docs)
        nlist = int(nlist)
        if n_docs < 2 * nlist or nlist < 2:
            return None
        iters = int(iters or ann_ops.DEFAULT_ITERS)
        # deterministic strided sample of real docs (no RNG: refresh→query
        # cycles must reproduce the same clustering bit-for-bit). The
        # sample pads to a pow2 bucket by wrapping around, so the jitted
        # Lloyd program's shape — and its compile-cache entry — is stable
        # across same-bucket segment sizes (test_ann retrace tripwire).
        step = max(1, n_docs // ann_ops.TRAIN_SAMPLE_CAP)
        sample_idx = np.arange(0, n_docs, step,
                               dtype=np.int64)[: ann_ops.TRAIN_SAMPLE_CAP]
        s_pad = min(next_pow2(len(sample_idx)), ann_ops.TRAIN_SAMPLE_CAP)
        sample_idx = np.resize(sample_idx, s_pad).astype(np.int32)
        sample = self.vecs[jnp.asarray(sample_idx)]
        init_idx = sample_idx[:: max(1, len(sample_idx) // nlist)][:nlist]
        if len(init_idx) < nlist:
            return None
        init = self.vecs[jnp.asarray(init_idx)]
        cents = ann_ops.train_centroids(sample, init, nlist=nlist,
                                        iters=iters)
        blk = ann_ops.assign_block_size(n_pad)
        assign = np.asarray(ann_ops.assign_clusters(
            self.vecs, cents, block=blk))
        # padding rows park in a phantom cluster `nlist` that is never
        # probed; real docs keep their trained assignment
        assign = assign.astype(np.int64)
        assign[n_docs:] = nlist
        order = np.argsort(assign * (n_pad + 1)
                           + np.arange(n_pad, dtype=np.int64),
                           kind="stable").astype(np.int32)
        counts = np.bincount(assign, minlength=nlist + 1)[: nlist + 1]
        starts = np.zeros(nlist, np.int64)
        starts[1:] = np.cumsum(counts[: nlist - 1])
        starts = starts.astype(np.int32)
        sizes = counts[:nlist].astype(np.int32)
        sizes_desc = np.sort(sizes)[::-1].astype(np.int64)
        norms = jnp.linalg.norm(self.vecs, axis=1)
        return IvfData(
            centroids=cents, starts=jnp.asarray(starts),
            sizes=jnp.asarray(sizes), slot_docs=jnp.asarray(order),
            norms=norms, sizes_desc_cum=np.cumsum(sizes_desc),
            nlist=nlist, n_docs=n_docs, dims=self.dims,
            nbytes=ann_ops.ivf_nbytes(n_pad, nlist, self.dims))

    def build_quant(self, ivf: "IvfData", mode: str,
                    m: int | None = None, *,
                    iters: int | None = None) -> "QuantData | None":
        """Quantized codes for this column against `ivf`'s cluster layout
        (ISSUE 12 tentpole): int8 per-dimension affine scales + i8 codes,
        or IVF-PQ codebooks trained on residuals against each doc's
        assigned centroid + u8[N, m] codes. Deterministic throughout (the
        same no-RNG discipline as build_ivf — refresh→query cycles must
        reproduce the clustering AND the codes bit-for-bit). None when
        the shape can't quantize (dims not divisible by m, too few docs
        to train 256 codes) — callers fall back to the f32 IVF scan."""
        from ..common import tracing
        from ..ops import ann as ann_ops
        n_pad = int(self.vecs.shape[0])
        blk = ann_ops.assign_block_size(n_pad)
        if mode == "int8":
            scales = ann_ops.train_int8_scales(self.vecs)
            codes = ann_ops.quantize_int8(self.vecs, scales, block=blk)
            cb, bb = ann_ops.quant_nbytes(n_pad, self.dims, "int8", 0)
            return QuantData(mode="int8", codes=codes, scales=scales,
                             codebooks=None, m=0, nlist=ivf.nlist,
                             codes_nbytes=cb, books_nbytes=bb)
        if mode != "pq":
            return None
        m = int(m or ann_ops.DEFAULT_PQ_M)
        if m < 1 or self.dims % m or ivf.n_docs < ann_ops.PQ_CODES:
            return None
        # recover each doc's cluster from the IVF CSR (slot_docs is docs
        # sorted by (cluster, doc)): no second assignment pass needed
        sizes = np.asarray(ivf.sizes)
        slot_docs = np.asarray(ivf.slot_docs)
        assign = np.full(n_pad, ivf.nlist - 1, np.int32)  # padding: any
        total = int(sizes.sum())                          # real cluster —
        assign[slot_docs[:total]] = np.repeat(            # rows are dead
            np.arange(ivf.nlist, dtype=np.int32), sizes)
        # deterministic strided residual sample, pow2-padded by wraparound
        # (same discipline as the Lloyd sample above)
        step = max(1, ivf.n_docs // ann_ops.TRAIN_SAMPLE_CAP)
        sample_idx = np.arange(0, ivf.n_docs, step,
                               dtype=np.int64)[: ann_ops.TRAIN_SAMPLE_CAP]
        s_pad = min(next_pow2(len(sample_idx)), ann_ops.TRAIN_SAMPLE_CAP)
        sample_idx = np.resize(sample_idx, s_pad).astype(np.int32)
        sv = self.vecs[jnp.asarray(sample_idx)]
        sa = jnp.asarray(assign[sample_idx])
        resid = (sv - ivf.centroids[sa]).reshape(
            s_pad, m, self.dims // m)
        samples = jnp.moveaxis(resid, 1, 0)               # [m, S, dsub]
        stride = max(1, s_pad // ann_ops.PQ_CODES)
        inits = samples[:, ::stride, :][:, : ann_ops.PQ_CODES, :]
        if inits.shape[1] < ann_ops.PQ_CODES:
            return None
        with tracing.span("pq_train", m=m, nlist=ivf.nlist,
                          sample=s_pad):
            books = ann_ops.train_pq_codebooks(
                samples, inits,
                iters=int(iters or ann_ops.DEFAULT_ITERS))
        codes = ann_ops.encode_pq(self.vecs, jnp.asarray(assign),
                                  ivf.centroids, books, block=blk)
        cb, bb = ann_ops.quant_nbytes(n_pad, self.dims, "pq", m)
        return QuantData(mode="pq", codes=codes, scales=None,
                        codebooks=books, m=m, nlist=ivf.nlist,
                        codes_nbytes=cb, books_nbytes=bb)


# ---------------------------------------------------------------------------
# Segment
# ---------------------------------------------------------------------------

@dataclass
class Segment:
    seg_id: int
    n_docs: int                      # real docs (un-padded)
    n_pad: int
    text: dict[str, TextFieldIndex]
    keywords: dict[str, KeywordColumn]
    numerics: dict[str, NumericColumn]
    vectors: dict[str, VectorColumn]
    stored: list[dict]               # host _source per local doc
    ids: list[str]                   # host _id per local doc
    types: list[str]                 # host _type per local doc
    id_to_local: dict[str, int]
    live_host: np.ndarray            # bool[N_pad] host mirror
    live_count: int = 0
    versions: list[int] = dc_field(default_factory=list)  # per local doc
    routings: list = dc_field(default_factory=list)       # per local doc
    # block-join layout (ref Lucene block join / ObjectMapper nested mode):
    # nested sub-document rows carry the local id of their ROOT document;
    # root rows carry -1. None when the segment has no nested rows (the
    # common case — zero overhead). Nested rows also appear in the
    # `_nested_path` keyword column; they are excluded from every normal
    # query/agg via root_live and only reachable through nested queries.
    parent_of: np.ndarray | None = None   # i32[N_pad] host

    def __post_init__(self):
        # device liveness is uploaded lazily: deletes only dirty the host
        # mirror, so a burst of deletes costs ONE upload at the next search
        # instead of an O(N) device_put per delete
        self._live_dev: jax.Array | None = None
        self._live_dirty = True
        self._live_padded: jax.Array | None = None
        self._live_all_dev: jax.Array | None = None
        self._parent_dev: jax.Array | None = None
        # monotonic tombstone generation: serving views (serving/packed_view)
        # cache packed liveness keyed on this, so delete-only changes refresh
        # one device row instead of rebuilding the view
        self.live_gen = 0
        if not self.live_count:
            self.live_count = int(self.live_host[: self.n_docs].sum())
        if not self.versions:
            self.versions = [1] * self.n_docs
        if not self.routings:
            self.routings = [None] * self.n_docs

    @property
    def live(self) -> jax.Array:
        """bool[N_pad] device ROOT-doc liveness: tombstone bitmap AND not a
        nested sub-row (Lucene liveDocs + the root-documents filter every
        top-level query carries, ref NonNestedDocsFilter). Queries, aggs and
        the packed/sparse lanes all consume this; nested rows are reachable
        only through `live_all` (the raw bitmap) inside nested queries."""
        if self._live_dirty or self._live_dev is None:
            self._live_dev = jnp.asarray(self.root_live_host)
            self._live_all_dev = None
            self._live_padded = None
            self._live_dirty = False
        return self._live_dev

    @property
    def root_live_host(self) -> np.ndarray:
        """bool[N_pad] host: live AND root (nested rows excluded)."""
        if self.parent_of is None:
            return self.live_host
        return self.live_host & (self.parent_of < 0)

    @property
    def live_all(self) -> jax.Array:
        """bool[N_pad] device raw tombstone bitmap INCLUDING nested rows —
        only nested-query/agg evaluation wants this."""
        if self.parent_of is None:
            return self.live
        if self._live_dirty or getattr(self, "_live_all_dev", None) is None:
            _ = self.live                       # refresh both mirrors
            self._live_all_dev = jnp.asarray(self.live_host)
        return self._live_all_dev

    @property
    def parent_dev(self) -> jax.Array | None:
        """i32[N_pad] device mirror of parent_of (lazy)."""
        if self.parent_of is None:
            return None
        if getattr(self, "_parent_dev", None) is None:
            self._parent_dev = jnp.asarray(self.parent_of)
        return self._parent_dev

    @property
    def root_live_count(self) -> int:
        """Live ROOT docs (what doc_count means to users)."""
        if self.parent_of is None:
            return self.live_count
        return int(self.root_live_host[: self.n_docs].sum())

    def delete_local(self, local: int) -> bool:
        """Flip the tombstone bit (cascading to the doc's nested block rows).
        Returns True if the doc was live."""
        if not self.live_host[local]:
            return False
        self.live_host[local] = False
        if self.parent_of is not None:
            for child in np.flatnonzero(self.parent_of == local):
                if self.live_host[child]:
                    self.live_host[child] = False
                    self.live_count -= 1
        self._live_dirty = True
        self.live_gen += 1
        self.live_count -= 1
        return True

    def live_padded(self):
        """bool[1, n_pad+1] liveness with a False PAD-sentinel column —
        the doc_mask shape ops/bm25_sparse.bm25_topk_sparse_masked gathers
        at candidate slots. Cached; invalidated on delete."""
        live = self.live                 # refreshes the dirty device mirror
        if self._live_padded is None:
            self._live_padded = jnp.concatenate(
                [live, jnp.zeros((1,), bool)])[None, :]
        return self._live_padded

    def doc_freq(self, field: str, term: str) -> int:
        fx = self.text.get(field)
        if fx is None:
            return 0
        return fx.lookup(term)[1]

    def total_term_freq(self, field: str, term: str) -> float:
        """Sum of the term's frequencies across its postings (Lucene
        totalTermFreq — the LM similarities' collection probability
        numerator). One small device slice-sum per (term, segment)."""
        fx = self.text.get(field)
        if fx is None:
            return 0.0
        s, ln, _ = fx.lookup(term)
        if ln == 0:
            return 0.0
        return float(np.asarray(fx.tf[s: s + ln]).sum())

    def field_stats(self, field: str) -> tuple[float, int]:
        """(sum_dl, doc_count) for avgdl computation across segments."""
        fx = self.text.get(field)
        if fx is None:
            return 0.0, 0
        return fx.sum_dl, self.n_docs

    def text_fielddata(self, field: str):
        """Lazily-built fielddata for sorting an ANALYZED text field:
        per-doc min/max term ordinal (Lucene's uninverted fielddata +
        MultiValueMode MIN/MAX; ref index/fielddata/plain/
        PagedBytesIndexFieldData.java — loaded on first sort, cached, and
        reported by `_cat/fielddata`).

        -> (min_ords i64[n_pad], max_ords i64[n_pad], missing bool[n_pad],
            vocab list[str], nbytes) or None if the field has no postings.
        """
        # one lock for all fielddata builds: concurrent first sorts on the
        # same field must not both build + charge the breaker (the release
        # paths only see ONE build's bytes)
        with _FIELDDATA_LOCK:
            return self._text_fielddata_locked(field)

    def _text_fielddata_locked(self, field: str):
        if self.text.get(field) is None:
            return None
        fdc = getattr(self, "fielddata_cache", None)
        if fdc is not None:
            # node-level fielddata tier (indices/cache_service): LRU
            # storage + breaker charge with eviction-under-pressure —
            # admission happens inside get_or_build, before the build
            return fdc.get_or_build(self, field,
                                    lambda: self._build_fielddata(field))
        cache = getattr(self, "_fielddata", None)
        if cache is None:
            cache = self._fielddata = {}
        fd = cache.get(field)
        if fd is not None:
            return fd
        breaker = getattr(self, "breaker", None)
        if breaker is not None:
            # admission control BEFORE building: loading fielddata under
            # memory pressure 429s cleanly (ref fielddata breaker in
            # HierarchyCircuitBreakerService)
            breaker.add_estimate(self.n_pad * 17)
        fd = self._build_fielddata(field)
        cache[field] = fd
        return fd

    def _build_fielddata(self, field: str):
        """Uninvert one text field into per-doc min/max term ordinals —
        the expensive part both caching paths share."""
        fx = self.text.get(field)
        V = len(fx.terms)
        lens = np.asarray(fx.term_lens[:V], np.int64)
        starts = np.asarray(fx.term_starts[:V], np.int64)
        docs_host = fx.doc_ids_host if fx.doc_ids_host is not None \
            else np.asarray(fx.doc_ids)
        total = int(lens.sum())
        # posting index per (term, occurrence): CSR starts + within offsets
        off = np.arange(total, dtype=np.int64) \
            - np.repeat(np.cumsum(lens) - lens, lens)
        pos = np.repeat(starts, lens) + off
        docs = np.asarray(docs_host, np.int64)[pos]
        tids = np.repeat(np.arange(V, dtype=np.int64), lens)
        mn = np.full(self.n_pad, V, np.int64)
        np.minimum.at(mn, docs, tids)
        mx = np.full(self.n_pad, -1, np.int64)
        np.maximum.at(mx, docs, tids)
        miss = mx < 0
        return (mn, mx, miss, list(fx.terms),
                mn.nbytes + mx.nbytes + miss.nbytes)

    def fielddata_bytes(self) -> dict[str, int]:
        """field -> loaded fielddata bytes (empty until a sort loads it)."""
        fdc = getattr(self, "fielddata_cache", None)
        if fdc is not None:
            return fdc.bytes_for(self)
        return {f: fd[4]
                for f, fd in getattr(self, "_fielddata", {}).items()}

    def memory_bytes(self) -> int:
        total = 0
        for fx in self.text.values():
            total += fx.doc_ids.size * 4 + fx.tf.size * 4 + fx.doc_len.size * 4 \
                + fx.dl.size * 4
        for kc in self.keywords.values():
            total += kc.ords.size * 4
        for nc in self.numerics.values():
            total += nc.vals.size * 8 + nc.missing.size
        for vc in self.vectors.values():
            total += vc.vecs.size * 4
        return total


# ---------------------------------------------------------------------------
# Builder (host-side, numpy)
# ---------------------------------------------------------------------------

class SegmentBuilder:
    """Accumulates parsed documents, then freezes them into a Segment.

    The analog of Lucene's IndexWriter in-memory buffer + flush
    (ref index/engine/InternalEngine.java — IndexWriter.updateDocument), but
    the "flush" produces dense tensors instead of an on-disk segment.
    """

    def __init__(self, seg_id: int = 0):
        self.seg_id = seg_id
        self._postings: dict[str, dict[str, list]] = {}   # field -> term -> [(doc, tf)]
        self._doc_len: dict[str, dict[int, float]] = {}   # field -> doc -> len
        self._keywords: dict[str, dict[int, str]] = {}    # field -> doc -> value (first)
        self._longs: dict[str, dict[int, int]] = {}
        self._doubles: dict[str, dict[int, float]] = {}
        self._vectors: dict[str, dict[int, list[float]]] = {}
        self._vector_dims: dict[str, int] = {}
        # columnar side-store fed by add_batch (the vectorized bulk lane):
        # text fields accumulate OCCURRENCE arrays (term id into the
        # field's growing vocab dict, doc local, within-doc position) and
        # the scalar channels accumulate (locals, values) pairs; build()
        # merges them with the per-doc dicts through one lexsort per field
        self._batch_text: dict[str, dict] = {}
        # field -> ([locals lists], [token-count lists]): columnar doc_len
        # (doc lengths are integers, so float summation is EXACT in any
        # order — vectorizing cannot drift sum_dl/avgdl)
        self._batch_doclen: dict[str, tuple[list, list]] = {}
        self._batch_keywords: dict[str, tuple[list, list]] = {}
        self._batch_longs: dict[str, tuple[list, list]] = {}
        self._batch_doubles: dict[str, tuple[list, list]] = {}
        self._batch_vectors: dict[str, tuple[list, list]] = {}
        self._csr_memo: dict | None = None
        self.stored: list[dict] = []
        self.ids: list[str] = []
        self.types: list[str] = []
        self.versions: list[int] = []
        self.routings: list = []
        self.id_to_local: dict[str, int] = {}
        self.parent_of: list[int] = []   # per row; -1 = root
        self.n_docs = 0

    def add(self, doc: ParsedDocument, type_name: str = "_doc",
            version: int = 1) -> int:
        """Add one document — and its nested block, children-first, root
        last (Lucene block-join order; ref ObjectMapper nested mode).
        Returns the ROOT row's local id."""
        # validate BEFORE mutating builder state: a mid-add raise must not
        # leave a half-indexed ghost doc behind (code review r3)
        for d in [doc] + [sub for _, sub in doc.nested]:
            for field, tokens in d.tokens.items():
                if len(tokens) > _MAX_DOC_POSITIONS:
                    # position keys pack as doc * 2^21 + (pos + bias); a
                    # longer doc would collide with its neighbor's key space
                    # (search/query_dsl.py _POS_SHIFT/_POS_BIAS; advisor r2)
                    raise ValueError(
                        f"field [{field}] has {len(tokens)} tokens; the "
                        f"maximum is {_MAX_DOC_POSITIONS} per document")
        child_rows: list[int] = []
        for path, sub in doc.nested:
            row = self._add_row(sub, "__" + path, version,
                                doc_id=f"{doc.doc_id}#n{self.n_docs}",
                                register_id=False)
            self._keywords.setdefault("_nested_path", {})[row] = path
            child_rows.append(row)
        local = self._add_row(doc, type_name, version, doc_id=doc.doc_id,
                              register_id=True)
        for r in child_rows:
            self.parent_of[r] = local
        return local

    def _add_row(self, doc: ParsedDocument, type_name: str, version: int,
                 doc_id: str, register_id: bool) -> int:
        local = self.n_docs
        self.n_docs += 1
        self._csr_memo = None
        self.stored.append(doc.source)
        self.ids.append(doc_id)
        self.types.append(type_name)
        self.versions.append(version)
        self.routings.append(doc.routing)
        self.parent_of.append(-1)
        if register_id:
            self.id_to_local[doc_id] = local

        for field, tokens in doc.tokens.items():
            fld = self._postings.setdefault(field, {})
            pos_map: dict[str, list[int]] = {}
            for p, t in enumerate(tokens):
                pos_map.setdefault(t, []).append(p)
            for t, ps in pos_map.items():
                fld.setdefault(t, []).append((local, len(ps), ps))
            self._doc_len.setdefault(field, {})[local] = float(len(tokens))
        for field, vals in doc.keywords.items():
            if vals:
                self._keywords.setdefault(field, {})[local] = vals[0]
        for field, vals in doc.longs.items():
            if vals:
                self._longs.setdefault(field, {})[local] = vals[0]
        for field, vals in doc.numerics.items():
            if vals:
                self._doubles.setdefault(field, {})[local] = vals[0]
        for field, (lat, lon) in doc.geo.items():
            # geo_point lands as two numeric columns — persistence, merge,
            # breaker accounting and columnar filters all come for free
            # (queries read <field>.lat / <field>.lon; search/query_parser)
            self._doubles.setdefault(field + ".lat", {})[local] = lat
            self._doubles.setdefault(field + ".lon", {})[local] = lon
        for field, vec in doc.vectors.items():
            self._vectors.setdefault(field, {})[local] = vec
            self._vector_dims[field] = len(vec)
        return local

    def add_batch(self, batch: list[tuple[ParsedDocument, str, int]]) -> list[int]:
        """Columnar append of a run of parsed documents — the vectorized
        bulk lane's segment write (ISSUE 7). Entries are (parsed, type,
        version) tuples WITHOUT nested blocks (the caller routes nested
        docs through add()). Builder state ends EXACTLY as sequential
        add() calls would leave it — same locals, same per-(term, doc)
        postings/positions, same ordinal/numeric/vector values — but text
        tokens land as numpy occurrence blocks and the scalar channels as
        (locals, values) runs, so build() does one lexsort per field
        instead of per-token dict work. Returns the new local ids."""
        base = self.n_docs
        # pass 1 — collect into LOCAL structures, validating as we go: no
        # builder state mutates until the whole batch has been walked, so
        # a mid-batch raise leaves no half-indexed ghost docs (mirror add())
        fld: dict[str, tuple] = {}      # field -> (locals, toks, encs, lens)
        fld_get = fld.get
        scalars: dict[int, dict] = {0: {}, 1: {}, 2: {}, 3: {}}
        kw_loc, long_loc, dbl_loc, vec_loc = (scalars[i] for i in range(4))
        max_pos = _MAX_DOC_POSITIONS
        for i, (doc, type_name, version) in enumerate(batch):
            if doc.nested:
                raise ValueError("add_batch cannot take nested blocks; "
                                 "route nested documents through add()")
            local = base + i
            enc = doc.token_enc
            for field, tokens in doc.tokens.items():
                n_tok = len(tokens)
                if n_tok > max_pos:
                    raise ValueError(
                        f"field [{field}] has {n_tok} tokens; the "
                        f"maximum is {max_pos} per document")
                ent = fld_get(field)
                if ent is None:
                    ent = fld[field] = ([], [], [], [])
                ent[0].append(local)
                ent[1].append(tokens)
                ent[2].append(enc.get(field) if enc is not None else None)
                ent[3].append(n_tok)
            if doc.keywords:
                for field, vals in doc.keywords.items():
                    if vals:
                        blk = kw_loc.get(field)
                        if blk is None:
                            blk = kw_loc[field] = ([], [])
                        blk[0].append(local)
                        blk[1].append(vals[0])
            if doc.longs:
                for field, vals in doc.longs.items():
                    if vals:
                        blk = long_loc.get(field)
                        if blk is None:
                            blk = long_loc[field] = ([], [])
                        blk[0].append(local)
                        blk[1].append(vals[0])
            if doc.numerics:
                for field, vals in doc.numerics.items():
                    if vals:
                        blk = dbl_loc.get(field)
                        if blk is None:
                            blk = dbl_loc[field] = ([], [])
                        blk[0].append(local)
                        blk[1].append(vals[0])
            if doc.geo:
                for field, (lat, lon) in doc.geo.items():
                    for suffix, val in ((".lat", lat), (".lon", lon)):
                        blk = dbl_loc.get(field + suffix)
                        if blk is None:
                            blk = dbl_loc[field + suffix] = ([], [])
                        blk[0].append(local)
                        blk[1].append(val)
            if doc.vectors:
                for field, vec in doc.vectors.items():
                    blk = vec_loc.get(field)
                    if blk is None:
                        blk = vec_loc[field] = ([], [])
                    blk[0].append(local)
                    blk[1].append(vec)
        # pass 2 — commit: one C-level extend per column instead of seven
        # appends per doc
        self._csr_memo = None
        self.stored.extend(d.source for d, _t, _v in batch)
        self.ids.extend(d.doc_id for d, _t, _v in batch)
        self.types.extend(t for _d, t, _v in batch)
        self.versions.extend(v for _d, _t, v in batch)
        self.routings.extend(d.routing for d, _t, _v in batch)
        self.parent_of.extend([-1] * len(batch))
        self.id_to_local.update(
            zip((d.doc_id for d, _t, _v in batch),
                range(base, base + len(batch))))
        for local_map, store in ((kw_loc, self._batch_keywords),
                                 (long_loc, self._batch_longs),
                                 (dbl_loc, self._batch_doubles)):
            for field, (locs, vals) in local_map.items():
                blk = store.get(field)
                if blk is None:
                    store[field] = (locs, vals)
                else:
                    blk[0].extend(locs)
                    blk[1].extend(vals)
        for field, (locs, vecs) in vec_loc.items():
            blk = self._batch_vectors.get(field)
            if blk is None:
                self._batch_vectors[field] = (locs, vecs)
            else:
                blk[0].extend(locs)
                blk[1].extend(vecs)
            self._vector_dims[field] = len(vecs[-1])
        # text: encode occurrences against the field's growing vocab dict.
        # Docs that carry analysis-time integer encodings (ParsedDocument
        # .token_enc, filled by the bulk lane's TextBatcher) skip the
        # per-token dict encode entirely: their per-flush output vocab
        # remaps onto the builder vocab once per UNIQUE token, and the
        # occurrence ids are one numpy gather.
        for field, (locals_l, tok_lists, encs, lens_l) in fld.items():
            dlblk = self._batch_doclen.get(field)
            if dlblk is None:
                dlblk = self._batch_doclen[field] = ([], [])
            dlblk[0].append(locals_l)
            dlblk[1].append(lens_l)
            blk = self._batch_text.get(field)
            if blk is None:
                blk = self._batch_text[field] = {
                    "vocab": {}, "tids": [], "docs": [], "poss": []}
            vocab = blk["vocab"]
            setd = vocab.setdefault
            # split into encoded doc groups (by shared analysis vocab) and
            # the string-encode remainder
            enc_groups: dict[int, tuple] = {}  # id(avocab) -> (avocab, locals, ids)
            str_locals: list[int] = []
            str_toklists: list[list[str]] = []
            for local, toks, enc_list in zip(locals_l, tok_lists, encs):
                if enc_list:
                    avocab = enc_list[0][0]
                    if len(enc_list) == 1:
                        ids_arr = enc_list[0][1]
                    elif all(e[0] is avocab for e in enc_list[1:]):
                        ids_arr = np.concatenate([e[1] for e in enc_list])
                    else:       # mixed vocabs can't happen in one flush;
                        avocab = None               # be safe anyway
                    if avocab is not None and len(ids_arr) == len(toks):
                        g = enc_groups.get(id(avocab))
                        if g is None:
                            g = enc_groups[id(avocab)] = (avocab, [], [])
                        g[1].append(local)
                        g[2].append(ids_arr)
                        continue
                str_locals.append(local)
                str_toklists.append(toks)
            for avocab, locs, ids_arrs in enc_groups.values():
                lens = np.fromiter(map(len, ids_arrs), np.int64,
                                   count=len(ids_arrs))
                total = int(lens.sum())
                if not total:
                    continue
                local_ids = np.concatenate(ids_arrs)
                # remap analysis-vocab ids -> field-vocab ids, registering
                # ONLY tokens this field actually uses (the analysis vocab
                # is shared across all fields of an analyzer — blanket
                # registration would leak other fields' terms in here)
                used = np.unique(local_ids)
                lut = np.zeros(int(used[-1]) + 1, np.int64)
                for i in used.tolist():
                    lut[i] = setd(avocab[i], len(vocab))
                blk["tids"].append(lut[local_ids])
                blk["docs"].append(
                    np.repeat(np.asarray(locs, np.int64), lens))
                cum = np.cumsum(lens)
                blk["poss"].append(
                    np.arange(total, dtype=np.int64)
                    - np.repeat(cum - lens, lens))
            if str_toklists:
                ids: list[int] = []
                app = ids.append
                counts = np.empty(len(str_toklists), np.int64)
                for di, toks in enumerate(str_toklists):
                    counts[di] = len(toks)
                    for t in toks:
                        app(setd(t, len(vocab)))
                total = int(counts.sum())
                if total:
                    blk["tids"].append(np.asarray(ids, np.int64))
                    blk["docs"].append(
                        np.repeat(np.asarray(str_locals, np.int64),
                                  counts))
                    # within-doc position = index into doc.tokens[field]
                    cum = np.cumsum(counts)
                    blk["poss"].append(
                        np.arange(total, dtype=np.int64)
                        - np.repeat(cum - counts, counts))
        self.n_docs = base + len(batch)
        return list(range(base, self.n_docs))

    def _text_csr_all(self) -> dict[str, dict]:
        """Merge per-doc dict postings and columnar occurrence blocks into
        the final per-field CSR layout (one lexsort per field). Memoized —
        estimate_bytes() and build() run back-to-back in refresh and must
        see the same layout; any add invalidates."""
        if self._csr_memo is not None:
            return self._csr_memo
        out: dict[str, dict] = {}
        fields = list(self._postings)
        for f in self._batch_text:
            if f not in self._postings:
                fields.append(f)
        for field in fields:
            term_map = self._postings.get(field, {})
            blk = self._batch_text.get(field)
            vocab_set = set(term_map)
            if blk is not None:
                vocab_set.update(blk["vocab"])
            union_terms = sorted(vocab_set)
            tid_of = {t: i for i, t in enumerate(union_terms)}
            V = len(union_terms)
            occ_t, occ_d, occ_p = [], [], []
            if term_map:
                # expand the per-doc dict's (term, doc) entries into
                # occurrences (same loop cost the old build paid)
                tids: list[int] = []
                docs: list[int] = []
                lens: list[int] = []
                flat: list[int] = []
                for t, lst in term_map.items():
                    ti = tid_of[t]
                    for d, c, ps in lst:
                        tids.append(ti)
                        docs.append(d)
                        lens.append(c)
                        flat.extend(ps)
                lens_a = np.asarray(lens, np.int64)
                occ_t.append(np.repeat(np.asarray(tids, np.int64), lens_a))
                occ_d.append(np.repeat(np.asarray(docs, np.int64), lens_a))
                occ_p.append(np.asarray(flat, np.int64))
            if blk is not None and blk["tids"]:
                lut = np.fromiter((tid_of[t] for t in blk["vocab"]),
                                  np.int64, count=len(blk["vocab"]))
                occ_t.append(lut[np.concatenate(blk["tids"])])
                occ_d.append(np.concatenate(blk["docs"]))
                occ_p.append(np.concatenate(blk["poss"]))
            if occ_t:
                ot = np.concatenate(occ_t)
                od = np.concatenate(occ_d)
                op = np.concatenate(occ_p)
            else:
                ot = od = op = np.zeros(0, np.int64)
            # (term, doc, pos) triples are unique, so one argsort over a
            # packed composite key equals the 3-key lexsort at ~40% of the
            # cost; positions stay < 2^21 (_MAX_DOC_POSITIONS) and the doc
            # axis < 2^22, so the pack fits i64 whenever V <= 2^20
            if V <= (1 << 20) and self.n_docs < (1 << 22):
                order = np.argsort((ot << 43) | (od << 21) | op)
            else:
                order = np.lexsort((op, od, ot))
            ot, od, op = ot[order], od[order], op[order]
            O = len(ot)
            if O:
                new_g = np.empty(O, bool)
                new_g[0] = True
                new_g[1:] = (ot[1:] != ot[:-1]) | (od[1:] != od[:-1])
                g_start = np.flatnonzero(new_g)
                g_len = np.diff(np.append(g_start, O))
                g_tid = ot[g_start]
                g_doc = od[g_start]
            else:
                g_start = g_len = g_tid = g_doc = np.zeros(0, np.int64)
            P = len(g_start)
            lens_v = np.bincount(g_tid, minlength=V).astype(np.int32) \
                if V else np.zeros(0, np.int32)
            max_df = int(lens_v.max()) if V and P else 0
            out[field] = {"union_terms": union_terms, "lens": lens_v,
                          "max_df": max_df, "P": P, "g_doc": g_doc,
                          "g_len": g_len, "g_start": g_start,
                          "positions": op}
        self._csr_memo = out
        return out

    def estimate_bytes(self) -> int:
        """Device-byte estimate from host-side builder state, BEFORE any
        device allocation — must mirror Segment.memory_bytes() exactly so
        breaker charge/release stay balanced. Lets the engine charge the
        breaker before build() uploads arrays (a tripped breaker then
        really does prevent the allocation, not just account for it)."""
        n_pad = next_pow2(self.n_docs, floor=8)
        total = 0
        for c in self._text_csr_all().values():
            p_pad = required_padding(c["P"], c["max_df"])
            # doc_ids + tf + dl are p_pad-sized; doc_len is n_pad-sized
            total += p_pad * 4 * 3 + n_pad * 4
        n_kw = len(set(self._keywords) | set(self._batch_keywords))
        total += n_kw * n_pad * 4
        n_num = len(set(self._longs) | set(self._batch_longs)) \
            + len(set(self._doubles) | set(self._batch_doubles))
        total += n_num * (n_pad * 8 + n_pad)
        for field in set(self._vectors) | set(self._batch_vectors):
            total += n_pad * self._vector_dims[field] * 4
        return total

    def build(self) -> Segment:
        n = self.n_docs
        n_pad = next_pow2(n, floor=8)

        # text: unified columnar CSR over BOTH sources (per-doc dict + batch
        # occurrence blocks) — one lexsort per field groups occurrences into
        # (term, doc) postings in exactly the order the old per-entry loop
        # produced (terms lexicographic, docs ascending, positions ascending)
        text: dict[str, TextFieldIndex] = {}
        for field, c in self._text_csr_all().items():
            union_terms = c["union_terms"]
            term_ids = {t: i for i, t in enumerate(union_terms)}
            lens = c["lens"]
            starts = np.zeros(len(union_terms), np.int32)
            if len(lens):
                starts[1:] = np.cumsum(lens)[:-1]
            P = c["P"]
            max_df = c["max_df"]
            p_pad = required_padding(P, max_df)
            doc_ids = np.full(p_pad, n_pad, np.int32)   # PAD sentinel
            doc_ids[:P] = c["g_doc"]
            tf = np.zeros(p_pad, np.float32)
            tf[:P] = c["g_len"]
            dl_map = self._doc_len.get(field, {})
            doc_len = np.ones(n_pad, np.float32)  # pad with 1 to avoid div-by-0
            for d, L in dl_map.items():
                doc_len[d] = max(L, 1.0)
            sum_dl = float(sum(dl_map.values()))
            dlblk = self._batch_doclen.get(field)
            if dlblk is not None:
                for locs, lens_l in zip(*dlblk):
                    la = np.asarray(locs, np.int64)
                    lv = np.asarray(lens_l, np.int64)
                    doc_len[la] = np.maximum(lv, 1).astype(np.float32)
                    # integer token counts: float accumulation is exact,
                    # so this np.sum cannot differ from the per-doc sum
                    sum_dl += float(lv.sum())
            dl = np.ones(p_pad, np.float32)
            dl[:P] = doc_len[np.minimum(doc_ids[:P], n_pad - 1)]
            text[field] = TextFieldIndex(
                terms=term_ids, term_starts=starts, term_lens=lens,
                doc_ids=jnp.asarray(doc_ids), tf=jnp.asarray(tf),
                doc_len=jnp.asarray(doc_len), dl=jnp.asarray(dl),
                sum_dl=sum_dl, n_postings=P,
                max_df=max_df,
                doc_ids_host=doc_ids[:P].copy(),
                pos_starts=c["g_start"].astype(np.int32),
                pos_lens=c["g_len"].astype(np.int32),
                positions=c["positions"].astype(np.int32))

        keywords: dict[str, KeywordColumn] = {}
        kw_fields = list(self._keywords)
        kw_fields += [f for f in self._batch_keywords
                      if f not in self._keywords]
        for field in kw_fields:
            val_map = self._keywords.get(field, {})
            blk = self._batch_keywords.get(field)
            vals_set = set(val_map.values())
            if blk is not None:
                vals_set.update(blk[1])
            uniq = sorted(vals_set)
            ord_map = {v: i for i, v in enumerate(uniq)}
            ords = np.full(n_pad, -1, np.int32)
            for d, v in val_map.items():
                ords[d] = ord_map[v]
            if blk is not None and blk[0]:
                ords[np.asarray(blk[0], np.int64)] = np.fromiter(
                    (ord_map[v] for v in blk[1]), np.int32,
                    count=len(blk[1]))
            keywords[field] = KeywordColumn(ord_map=ord_map, values=uniq,
                                            ords=jnp.asarray(ords))

        numerics: dict[str, NumericColumn] = {}
        for val_maps, blocks, np_dtype, tag in (
                (self._longs, self._batch_longs, np.int64, "i64"),
                (self._doubles, self._batch_doubles, np.float64, "f64")):
            num_fields = list(val_maps)
            num_fields += [f for f in blocks if f not in val_maps]
            for field in num_fields:
                val_map = val_maps.get(field, {})
                blk = blocks.get(field)
                vals = np.zeros(n_pad, np_dtype)
                missing = np.ones(n_pad, bool)
                for d, v in val_map.items():
                    vals[d] = v
                    missing[d] = False
                if blk is not None and blk[0]:
                    la = np.asarray(blk[0], np.int64)
                    vals[la] = np.asarray(blk[1], np_dtype)
                    missing[la] = False
                numerics[field] = NumericColumn(jnp.asarray(vals),
                                                jnp.asarray(missing), tag)

        vectors: dict[str, VectorColumn] = {}
        vec_fields = list(self._vectors)
        vec_fields += [f for f in self._batch_vectors
                       if f not in self._vectors]
        for field in vec_fields:
            dims = self._vector_dims[field]
            mat = np.zeros((n_pad, dims), np.float32)
            for d, v in self._vectors.get(field, {}).items():
                mat[d] = v
            blk = self._batch_vectors.get(field)
            if blk is not None and blk[0]:
                mat[np.asarray(blk[0], np.int64)] = \
                    np.asarray(blk[1], np.float32)
            vectors[field] = VectorColumn(jnp.asarray(mat), dims)

        live = np.zeros(n_pad, bool)
        live[:n] = True
        parent_of = None
        if any(p >= 0 for p in self.parent_of):
            parent_of = np.full(n_pad, -1, np.int32)
            parent_of[:n] = self.parent_of
        return Segment(
            seg_id=self.seg_id, n_docs=n, n_pad=n_pad, text=text,
            keywords=keywords, numerics=numerics, vectors=vectors,
            stored=self.stored, ids=self.ids, types=self.types,
            id_to_local=dict(self.id_to_local), live_host=live,
            versions=list(self.versions), routings=list(self.routings),
            parent_of=parent_of)


def merge_segments(segments: list[Segment], new_seg_id: int,
                   mapper_for_type=None) -> Segment:
    """Merge segments tensor-natively, dropping tombstoned docs
    (ref index/merge/ + Lucene SegmentMerger — but over CSR tensors).

    NO re-tokenization and NO mapper involvement (mapper_for_type is kept
    for call-site compatibility and ignored): postings are concatenated and
    re-grouped by a stable host argsort over the union term ids, doc ids are
    remapped through per-segment liveness compaction, keyword ordinals are
    remapped through the union vocabulary, and numeric/vector columns are
    boolean-mask concatenations. Work is O(P log V) numpy on host — merge
    cost no longer scales with analyzer complexity, and per-term postings
    stay sorted by doc id (stable sort + order-preserving remap).
    """
    # -- doc remap: old (seg, local) -> new local, dead docs dropped -------
    keeps: list[np.ndarray] = []
    remaps: list[np.ndarray] = []    # old local -> new local (-1 = dead)
    base = 0
    for seg in segments:
        keep = np.flatnonzero(seg.live_host[: seg.n_docs])
        remap = np.full(seg.n_pad + 1, -1, np.int64)  # +1: PAD sentinel slot
        remap[keep] = base + np.arange(len(keep))
        keeps.append(keep)
        remaps.append(remap)
        base += len(keep)
    n = base
    n_pad = next_pow2(n, floor=8)

    stored: list[dict] = []
    ids: list[str] = []
    types: list[str] = []
    versions: list[int] = []
    routings: list = []
    for seg, keep in zip(segments, keeps):
        for old in keep:
            stored.append(seg.stored[old])
            ids.append(seg.ids[old])
            types.append(seg.types[old])
            versions.append(seg.versions[old])
            routings.append(seg.routings[old] if seg.routings else None)

    # -- text fields: CSR concat + stable re-group by union term id --------
    text: dict[str, TextFieldIndex] = {}
    all_text_fields = {f for seg in segments for f in seg.text}
    for field in all_text_fields:
        srcs = [(si, seg.text[field]) for si, seg in enumerate(segments)
                if field in seg.text]
        union_terms = sorted(set().union(*(fx.terms for _, fx in srcs)))
        union_pos = {t: i for i, t in enumerate(union_terms)}
        V = len(union_terms)
        have_positions = all(fx.positions is not None and
                             fx.pos_starts is not None for _, fx in srcs)

        tid_parts, doc_parts, tf_parts = [], [], []
        ps_parts, pl_parts, posflat_parts = [], [], []
        pos_off = 0
        for si, fx in srcs:
            P = fx.n_postings
            if P == 0:
                continue
            docs_h = fx.doc_ids_host if fx.doc_ids_host is not None \
                else np.asarray(fx.doc_ids)[:P]
            tf_h = np.asarray(fx.tf)[:P]
            # per-posting union term id: repeat each term id by its df
            seg_terms = list(fx.terms)  # insertion order == sorted
            seg_to_union = np.array([union_pos[t] for t in seg_terms],
                                    np.int64)
            per_post_tid = np.repeat(seg_to_union, fx.term_lens[: len(seg_terms)])
            alive = remaps[si][docs_h] >= 0
            tid_parts.append(per_post_tid[alive])
            doc_parts.append(remaps[si][docs_h][alive])
            tf_parts.append(tf_h[alive])
            if have_positions:
                ps_parts.append(fx.pos_starts[:P][alive] + pos_off)
                pl_parts.append(fx.pos_lens[:P][alive])
                posflat_parts.append(fx.positions)
                pos_off += len(fx.positions)

        if tid_parts:
            tids = np.concatenate(tid_parts)
            docs = np.concatenate(doc_parts)
            tfs = np.concatenate(tf_parts)
        else:
            tids = np.zeros(0, np.int64)
            docs = np.zeros(0, np.int64)
            tfs = np.zeros(0, np.float32)
        # stable: within a term, segment order then doc order == ascending
        # new doc ids (remap preserves per-segment order, bases ascend)
        order = np.argsort(tids, kind="stable")
        tids, docs, tfs = tids[order], docs[order], tfs[order]
        P = len(tids)
        lens = np.bincount(tids, minlength=V).astype(np.int32) if V else \
            np.zeros(0, np.int32)
        starts = np.zeros(V, np.int32)
        if V:
            starts[1:] = np.cumsum(lens)[:-1]
        max_df = int(lens.max()) if V and P else 0
        p_pad = required_padding(P, max_df)
        doc_ids = np.full(p_pad, n_pad, np.int32)
        doc_ids[:P] = docs
        tf = np.zeros(p_pad, np.float32)
        tf[:P] = tfs

        # per-doc field length: gather old doc_len at kept docs
        doc_len = np.ones(n_pad, np.float32)
        for si, fx in srcs:
            old_dl = np.asarray(fx.doc_len)
            keep = keeps[si]
            doc_len[remaps[si][keep]] = old_dl[np.minimum(
                keep, old_dl.shape[0] - 1)]
        dl = np.ones(p_pad, np.float32)
        dl[:P] = doc_len[np.minimum(doc_ids[:P], n_pad - 1)]
        # Σ field length over LIVE docs == Σ tf (tf sums to token count)
        sum_dl = float(tfs.sum())

        pos_starts = pos_lens = positions = doc_ids_host = None
        doc_ids_host = docs.astype(np.int32)
        if have_positions and P:
            ps = np.concatenate(ps_parts)[order]
            pl = np.concatenate(pl_parts)[order]
            posflat = np.concatenate(posflat_parts) if posflat_parts \
                else np.zeros(0, np.int32)
            ends = np.cumsum(pl)
            total = int(ends[-1]) if len(ends) else 0
            flat_idx = np.arange(total) - np.repeat(ends - pl, pl) \
                + np.repeat(ps, pl)
            positions = posflat[flat_idx].astype(np.int32)
            pos_lens = pl.astype(np.int32)
            pos_starts = np.zeros(P, np.int32)
            if P:
                pos_starts[1:] = ends[:-1]
        elif have_positions:
            positions = np.zeros(0, np.int32)
            pos_starts = np.zeros(0, np.int32)
            pos_lens = np.zeros(0, np.int32)

        text[field] = TextFieldIndex(
            terms={t: i for i, t in enumerate(union_terms)},
            term_starts=starts, term_lens=lens,
            doc_ids=jnp.asarray(doc_ids), tf=jnp.asarray(tf),
            doc_len=jnp.asarray(doc_len), dl=jnp.asarray(dl),
            sum_dl=sum_dl, n_postings=P, max_df=max_df,
            doc_ids_host=doc_ids_host,
            pos_starts=pos_starts, pos_lens=pos_lens, positions=positions)

    # -- keyword columns: ordinal remap through the union vocabulary -------
    keywords: dict[str, KeywordColumn] = {}
    all_kw = {f for seg in segments for f in seg.keywords}
    for field in all_kw:
        srcs = [(si, seg.keywords[field]) for si, seg in enumerate(segments)
                if field in seg.keywords]
        union_vals = sorted(set().union(*(kc.values for _, kc in srcs)))
        union_of = {v: i for i, v in enumerate(union_vals)}
        ords = np.full(n_pad, -1, np.int32)
        for si, kc in srcs:
            keep = keeps[si]
            old = np.asarray(kc.ords)[keep]
            # map via the union: ord -1 (missing) stays -1
            lut = np.array([union_of[v] for v in kc.values] + [-1], np.int32)
            ords[remaps[si][keep]] = lut[old]
        keywords[field] = KeywordColumn(
            ord_map=union_of, values=union_vals, ords=jnp.asarray(ords))

    # -- numeric columns ----------------------------------------------------
    numerics: dict[str, NumericColumn] = {}
    all_num = {f for seg in segments for f in seg.numerics}
    for field in all_num:
        dtype = next(seg.numerics[field].dtype for seg in segments
                     if field in seg.numerics)
        vals = np.zeros(n_pad, np.int64 if dtype == "i64" else np.float64)
        missing = np.ones(n_pad, bool)
        for si, seg in enumerate(segments):
            nc = seg.numerics.get(field)
            if nc is None:
                continue
            keep = keeps[si]
            vals[remaps[si][keep]] = np.asarray(nc.vals)[keep]
            missing[remaps[si][keep]] = np.asarray(nc.missing)[keep]
        numerics[field] = NumericColumn(jnp.asarray(vals),
                                        jnp.asarray(missing), dtype)

    # -- vector columns ------------------------------------------------------
    vectors: dict[str, VectorColumn] = {}
    all_vec = {f for seg in segments for f in seg.vectors}
    for field in all_vec:
        dims = next(seg.vectors[field].dims for seg in segments
                    if field in seg.vectors)
        mat = np.zeros((n_pad, dims), np.float32)
        for si, seg in enumerate(segments):
            vc = seg.vectors.get(field)
            if vc is None:
                continue
            keep = keeps[si]
            mat[remaps[si][keep]] = np.asarray(vc.vecs)[keep]
        vectors[field] = VectorColumn(jnp.asarray(mat), dims)

    live = np.zeros(n_pad, bool)
    live[:n] = True

    # -- block-join parent pointers: remap through the same doc compaction.
    # Children of dead roots are themselves dead (delete_local cascades),
    # so every kept child's parent is kept too.
    parent_of = None
    if any(seg.parent_of is not None for seg in segments):
        parent_of = np.full(n_pad, -1, np.int32)
        for si, seg in enumerate(segments):
            if seg.parent_of is None:
                continue
            keep = keeps[si]
            old_p = seg.parent_of[keep]
            has_p = old_p >= 0
            parent_of[remaps[si][keep[has_p]]] = \
                remaps[si][old_p[has_p]]
        if not (parent_of >= 0).any():
            parent_of = None

    return Segment(
        seg_id=new_seg_id, n_docs=n, n_pad=n_pad, text=text,
        keywords=keywords, numerics=numerics, vectors=vectors,
        stored=stored, ids=ids, types=types,
        # nested placeholder rows (type "__<path>") are not id-addressable
        id_to_local={d: i for i, d in enumerate(ids)
                     if not types[i].startswith("__")},
        live_host=live,
        versions=versions, routings=routings, parent_of=parent_of)
