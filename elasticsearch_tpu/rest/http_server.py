"""REST API over HTTP: the reference's port-9200 surface.

Analog of /root/reference/src/main/java/org/elasticsearch/rest/ (RestController
path-trie dispatch, rest/action/* 1:1 handlers) + http/netty/. The wire
contract targets the machine-readable specs in
/root/reference/rest-api-spec/api/*.json (ES 2.0 response shapes) so existing
clients can point at this server unchanged.

Implementation: stdlib ThreadingHTTPServer — the control plane is IO-bound
host code; the data plane stays on device. (A C++ server lands with the
native runtime milestone; the handler table below is transport-agnostic.)
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

from ..index.engine import VersionConflictException, DocumentMissingException
from ..node import (IndexAlreadyExistsException, IndexMissingException,
                    InvalidIndexNameException, NodeService)
from ..search.aggs import AggregationParsingException
from ..search.query_dsl import QueryParsingException


class RestError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


def _status_of(e: Exception) -> int:
    from ..common.breaker import CircuitBreakingException
    if isinstance(e, RestError):
        return e.status
    if isinstance(e, CircuitBreakingException):
        return 429     # TOO_MANY_REQUESTS, ref EsRejectedExecutionException
    from ..snapshots import (RepositoryException, SnapshotException,
                             SnapshotMissingException)
    if isinstance(e, SnapshotMissingException):
        return 404
    if isinstance(e, (RepositoryException, SnapshotException)):
        return 400
    if isinstance(e, IndexMissingException):
        return 404
    if isinstance(e, DocumentMissingException):
        return 404
    if isinstance(e, IndexAlreadyExistsException):
        return 400
    if isinstance(e, VersionConflictException):
        return 409
    from ..script.engine import ScriptException
    if isinstance(e, (InvalidIndexNameException, QueryParsingException,
                      AggregationParsingException, ScriptException,
                      json.JSONDecodeError, KeyError, ValueError)):
        return 400
    return 500


class RestController:
    """Method+path-pattern dispatch (ref rest/RestController.java:44,119,163
    path trie; regex table is equivalent at this route count)."""

    def __init__(self, node: NodeService):
        self.node = node
        self.routes: list[tuple[str, re.Pattern, Callable]] = []
        _register_routes(self, node)

    def register(self, method: str, pattern: str, handler: Callable) -> None:
        # {name} -> named group; e.g. /{index}/_search
        rx = re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern)
        # specificity: literal segments outrank parameters (the path-trie
        # rule — /_mget must beat /{index})
        segs = [s for s in pattern.split("/") if s]
        literal = sum(1 for s in segs if "{" not in s)
        self.routes.append((method, re.compile(f"^{rx}/?$"), handler,
                            (literal, -len(segs))))

    def dispatch(self, method: str, path: str, params: dict,
                 body: bytes) -> tuple[int, dict | str]:
        best = None
        for m, rx, handler, spec in self.routes:
            if m != method:
                continue
            match = rx.match(path)
            if match and (best is None or spec > best[2]):
                best = (handler, match, spec)
        if best is None:
            raise RestError(400, f"no handler for [{method} {path}]")
        handler, match, _ = best
        return handler(match.groupdict(), params, body)


def _json_body(body: bytes) -> dict:
    if not body:
        return {}
    return json.loads(body)


def _register_routes(c: RestController, node: NodeService) -> None:
    # -- cluster / node level ---------------------------------------------
    def root(g, p, b):
        return 200, {"status": 200, "name": "tpu-node-0",
                     "cluster_name": node.cluster_name,
                     "version": {"number": "2.0.0-tpu",
                                 "lucene_version": "tensor-native"},
                     "tagline": "You Know, for Search"}
    c.register("GET", "/", root)
    c.register("HEAD", "/", lambda g, p, b: (200, {}))

    c.register("GET", "/_cluster/health",
               lambda g, p, b: (200, node.cluster_health()))
    c.register("GET", "/_stats", lambda g, p, b: (200, node.stats()))
    c.register("GET", "/_cat/indices", _cat_indices(node))
    c.register("GET", "/_cat/health", _cat_health(node))

    def put_template(g, p, b):
        node.put_template(g["name"], _json_body(b))
        return 200, {"acknowledged": True}
    c.register("PUT", "/_template/{name}", put_template)

    # -- snapshots (ref rest/action/admin/cluster/snapshots/) --------------
    c.register("PUT", "/_snapshot/{repo}",
               lambda g, p, b: (200, node.snapshots.put_repository(
                   g["repo"], _json_body(b))))
    c.register("POST", "/_snapshot/{repo}",
               lambda g, p, b: (200, node.snapshots.put_repository(
                   g["repo"], _json_body(b))))
    c.register("GET", "/_snapshot/{repo}",
               lambda g, p, b: (200, node.snapshots.get_repository(g["repo"])))
    c.register("PUT", "/_snapshot/{repo}/{snap}",
               lambda g, p, b: (200, node.snapshots.create_snapshot(
                   g["repo"], g["snap"], _json_body(b))))
    c.register("GET", "/_snapshot/{repo}/{snap}",
               lambda g, p, b: (200, node.snapshots.get_snapshots(
                   g["repo"], g["snap"])))
    c.register("DELETE", "/_snapshot/{repo}/{snap}",
               lambda g, p, b: (200, node.snapshots.delete_snapshot(
                   g["repo"], g["snap"])))
    c.register("POST", "/_snapshot/{repo}/{snap}/_restore",
               lambda g, p, b: (200, node.snapshots.restore_snapshot(
                   g["repo"], g["snap"], _json_body(b))))

    # -- search (must register before the generic doc routes) -------------
    def search(g, p, b):
        body = _json_body(b)
        if "q" in p:   # URI search (ref RestSearchAction query_string support)
            body.setdefault("query", {"query_string": {"query": p["q"][0]}})
        if "size" in p:
            body["size"] = int(p["size"][0])
        if "from" in p:
            body["from"] = int(p["from"][0])
        scroll = p.get("scroll", [None])[0]
        return 200, node.search(g.get("index", "_all"), body, scroll=scroll)

    def scroll_next(g, p, b):
        body = _json_body(b)
        sid = body.get("scroll_id") or p.get("scroll_id", [None])[0]
        if not sid:
            raise RestError(400, "scroll_id is required")
        keep = body.get("scroll") or p.get("scroll", [None])[0]
        return 200, node.scroll(sid, keep)
    c.register("GET", "/_search/scroll", scroll_next)
    c.register("POST", "/_search/scroll", scroll_next)

    def clear_scroll(g, p, b):
        body = _json_body(b)
        sids = body.get("scroll_id", [])
        if isinstance(sids, str):
            sids = [sids]
        n = node.clear_scroll(sids)
        return 200, {"succeeded": True, "num_freed": n}
    c.register("DELETE", "/_search/scroll", clear_scroll)
    c.register("GET", "/{index}/_search", search)
    c.register("POST", "/{index}/_search", search)
    c.register("GET", "/_search", search)
    c.register("POST", "/_search", search)
    c.register("GET", "/{index}/{type}/_search",
               lambda g, p, b: search(g, p, b))
    c.register("POST", "/{index}/{type}/_search",
               lambda g, p, b: search(g, p, b))

    def count(g, p, b):
        return 200, node.count(g.get("index", "_all"), _json_body(b))
    c.register("GET", "/{index}/_count", count)
    c.register("POST", "/{index}/_count", count)
    c.register("GET", "/_count", count)

    def msearch(g, p, b):
        # NDJSON: alternating header / body lines
        # (ref rest/action/search/RestMultiSearchAction)
        lines = [json.loads(ln) for ln in b.decode("utf-8").split("\n")
                 if ln.strip()]
        if len(lines) % 2:
            raise RestError(400, "msearch body must be header/body pairs")
        requests = []
        for i in range(0, len(lines), 2):
            header = dict(lines[i])
            if g.get("index") and "index" not in header:
                header["index"] = g["index"]
            requests.append((header, lines[i + 1]))
        # raw=True: the packed serving lane pre-serializes hit JSON with
        # vectorized string ops; bytes pass straight through to the socket
        return 200, node.msearch(requests, raw=True)
    c.register("GET", "/_msearch", msearch)
    c.register("POST", "/_msearch", msearch)
    c.register("GET", "/{index}/_msearch", msearch)
    c.register("POST", "/{index}/_msearch", msearch)

    # -- bulk --------------------------------------------------------------
    def bulk(g, p, b):
        import time
        t0 = time.perf_counter()
        default_index = g.get("index")
        ops = _parse_bulk(b, default_index)
        items = node.bulk(ops)
        errors = any(next(iter(i.values())).get("status", 200) >= 300
                     for i in items)
        if p.get("refresh", ["false"])[0] != "false":
            node.refresh(default_index or "_all")
        return 200, {"took": int((time.perf_counter() - t0) * 1000),
                     "errors": errors, "items": items}
    c.register("POST", "/_bulk", bulk)
    c.register("PUT", "/_bulk", bulk)
    c.register("POST", "/{index}/_bulk", bulk)
    c.register("POST", "/{index}/{type}/_bulk", bulk)

    # -- admin per index ---------------------------------------------------
    def create_index(g, p, b):
        body = _json_body(b)
        node.create_index(g["index"], settings=body.get("settings"),
                          mappings=body.get("mappings"),
                          aliases=body.get("aliases"))
        return 200, {"acknowledged": True}
    c.register("PUT", "/{index}", create_index)
    c.register("POST", "/{index}", create_index)

    def delete_index(g, p, b):
        node.delete_index(g["index"])
        return 200, {"acknowledged": True}
    c.register("DELETE", "/{index}", delete_index)

    def index_exists(g, p, b):
        try:
            node._resolve(g["index"])
            return 200, {}
        except IndexMissingException:
            return 404, {}
    c.register("HEAD", "/{index}", index_exists)

    def refresh(g, p, b):
        node.refresh(g.get("index", "_all"))
        return 200, {"_shards": {"failed": 0}}
    c.register("POST", "/{index}/_refresh", refresh)
    c.register("POST", "/_refresh", refresh)

    def flush(g, p, b):
        node.flush(g.get("index", "_all"))
        return 200, {"_shards": {"failed": 0}}
    c.register("POST", "/{index}/_flush", flush)
    c.register("POST", "/_flush", flush)

    def optimize(g, p, b):
        node.force_merge(g.get("index", "_all"),
                         int(p.get("max_num_segments", [1])[0]))
        return 200, {"_shards": {"failed": 0}}
    c.register("POST", "/{index}/_optimize", optimize)
    c.register("POST", "/_optimize", optimize)
    c.register("POST", "/{index}/_forcemerge", optimize)

    def get_mapping(g, p, b):
        out = {}
        for n in node._resolve(g.get("index", "_all")):
            out[n] = {"mappings": node.indices[n].mappings_dict()}
        return 200, out
    c.register("GET", "/{index}/_mapping", get_mapping)
    c.register("GET", "/_mapping", get_mapping)

    def put_mapping(g, p, b):
        body = _json_body(b)
        tname = g.get("type", "_doc")
        mapping = body.get(tname, body)
        node.put_mapping(g["index"], tname, mapping)
        return 200, {"acknowledged": True}
    c.register("PUT", "/{index}/_mapping/{type}", put_mapping)
    c.register("PUT", "/{index}/{type}/_mapping", put_mapping)

    def get_settings(g, p, b):
        out = {}
        for n in node._resolve(g.get("index", "_all")):
            out[n] = {"settings": {"index": dict(node.indices[n].settings)}}
        return 200, out
    c.register("GET", "/{index}/_settings", get_settings)

    def analyze(g, p, b):
        body = _json_body(b)
        text = body.get("text") or (p.get("text", [""])[0])
        analyzer = body.get("analyzer", p.get("analyzer", ["standard"])[0])
        svc = node.index_service(g["index"]) if g.get("index") else None
        from ..analysis.analyzers import AnalysisService
        an = (svc.mappers.analysis if svc else AnalysisService())
        tokens = an.analyzer(analyzer).analyze(
            text if isinstance(text, str) else " ".join(text))
        return 200, {"tokens": [
            {"token": t, "start_offset": 0, "end_offset": 0,
             "type": "<ALPHANUM>", "position": i}
            for i, t in enumerate(tokens)]}
    c.register("GET", "/_analyze", analyze)
    c.register("POST", "/_analyze", analyze)
    c.register("GET", "/{index}/_analyze", analyze)
    c.register("POST", "/{index}/_analyze", analyze)

    def index_stats(g, p, b):
        out = {}
        for n in node._resolve(g.get("index", "_all")):
            out[n] = node.indices[n].stats()
        return 200, {"indices": out}
    c.register("GET", "/{index}/_stats", index_stats)

    # -- documents ---------------------------------------------------------
    def put_doc(g, p, b):
        kw = {}
        if "version" in p:
            kw["version"] = int(p["version"][0])
            kw["version_type"] = p.get("version_type", ["internal"])[0]
        if p.get("op_type", [None])[0] == "create":
            kw["op_type"] = "create"
        _, res = node.index_doc(g["index"], g.get("id"), _json_body(b),
                                type_name=g.get("type", "_doc"),
                                routing=p.get("routing", [None])[0], **kw)
        if p.get("refresh", ["false"])[0] != "false":
            node.refresh(g["index"])
        status = 201 if res.created else 200
        return status, {"_index": g["index"], "_type": g.get("type", "_doc"),
                        "_id": res.doc_id, "_version": res.version,
                        "created": res.created}
    c.register("PUT", "/{index}/{type}/{id}", put_doc)
    c.register("POST", "/{index}/{type}/{id}", put_doc)
    c.register("POST", "/{index}/{type}", put_doc)

    def create_doc(g, p, b):
        p = {**p, "op_type": ["create"]}
        return put_doc(g, p, b)
    c.register("PUT", "/{index}/{type}/{id}/_create", create_doc)

    def get_doc(g, p, b):
        realtime = p.get("realtime", ["true"])[0] != "false"
        res = node.get_doc(g["index"], g["id"],
                           routing=p.get("routing", [None])[0],
                           realtime=realtime)
        out = {"_index": g["index"], "_type": res.type_name, "_id": g["id"],
               "found": res.found}
        if res.found:
            out["_version"] = res.version
            out["_source"] = res.source
        return (200 if res.found else 404), out
    c.register("GET", "/{index}/{type}/{id}", get_doc)

    def get_source(g, p, b):
        res = node.get_doc(g["index"], g["id"])
        if not res.found:
            return 404, {"error": "not found", "status": 404}
        return 200, res.source
    c.register("GET", "/{index}/{type}/{id}/_source", get_source)

    def head_doc(g, p, b):
        res = node.get_doc(g["index"], g["id"])
        return (200 if res.found else 404), {}
    c.register("HEAD", "/{index}/{type}/{id}", head_doc)

    def delete_doc(g, p, b):
        res = node.delete_doc(g["index"], g["id"],
                              routing=p.get("routing", [None])[0])
        return (200 if res.found else 404), {
            "found": res.found, "_index": g["index"],
            "_type": g.get("type", "_doc"), "_id": g["id"],
            "_version": res.version}
    c.register("DELETE", "/{index}/{type}/{id}", delete_doc)

    def update_doc(g, p, b):
        res, noop = node.update_doc(g["index"], g["id"], _json_body(b),
                                    type_name=g.get("type", "_doc"))
        if p.get("refresh", ["false"])[0] != "false":
            node.refresh(g["index"])
        return 200, {"_index": g["index"], "_type": g.get("type", "_doc"),
                     "_id": g["id"], "_version": res.version}
    c.register("POST", "/{index}/{type}/{id}/_update", update_doc)

    def mget(g, p, b):
        body = _json_body(b)
        docs = []
        for d in body.get("docs", []):
            idx = d.get("_index", g.get("index"))
            res = node.get_doc(idx, d["_id"])
            entry = {"_index": idx, "_type": res.type_name,
                     "_id": d["_id"], "found": res.found}
            if res.found:
                entry["_version"] = res.version
                entry["_source"] = res.source
            docs.append(entry)
        return 200, {"docs": docs}
    c.register("GET", "/_mget", mget)
    c.register("POST", "/_mget", mget)
    c.register("GET", "/{index}/_mget", mget)
    c.register("POST", "/{index}/_mget", mget)


def _parse_bulk(body: bytes, default_index: str | None) -> list:
    """NDJSON bulk format (ref rest/action/bulk/RestBulkAction)."""
    ops = []
    lines = [ln for ln in body.decode("utf-8").split("\n") if ln.strip()]
    i = 0
    while i < len(lines):
        action_line = json.loads(lines[i])
        (action, meta), = action_line.items()
        meta = dict(meta)
        if default_index and "_index" not in meta:
            meta["_index"] = default_index
        i += 1
        source = None
        if action != "delete":
            source = json.loads(lines[i])
            i += 1
        ops.append((action, meta, source))
    return ops


def _cat_indices(node: NodeService):
    def handler(g, p, b):
        rows = []
        for n, svc in sorted(node.indices.items()):
            rows.append(f"green open {n} {svc.n_shards} {svc.n_replicas} "
                        f"{svc.doc_count()} 0")
        return 200, "\n".join(rows) + "\n"
    return handler


def _cat_health(node: NodeService):
    def handler(g, p, b):
        h = node.cluster_health()
        return 200, (f"{h['cluster_name']} {h['status']} "
                     f"{h['number_of_nodes']} {h['number_of_data_nodes']}\n")
    return handler


# ---------------------------------------------------------------------------

class HttpServer:
    """Threaded HTTP front-end (ref http/HttpServer.java + netty transport)."""

    def __init__(self, node: NodeService, host: str = "127.0.0.1",
                 port: int = 9200):
        self.controller = RestController(node)
        controller = self.controller

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):   # silence per-request logs
                pass

            def _handle(self, method: str):
                parsed = urlparse(self.path)
                params = parse_qs(parsed.query)
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                try:
                    status, payload = controller.dispatch(
                        method, parsed.path, params, body)
                except Exception as e:  # noqa: BLE001 — REST error contract
                    status = _status_of(e)
                    payload = {"error": f"{type(e).__name__}: {e}",
                               "status": status}
                if isinstance(payload, bytes):
                    data = payload           # pre-serialized JSON fast lane
                    ctype = "application/json; charset=UTF-8"
                elif isinstance(payload, str):
                    data = payload.encode("utf-8")
                    ctype = "text/plain; charset=UTF-8"
                else:
                    data = json.dumps(payload).encode("utf-8")
                    ctype = "application/json; charset=UTF-8"
                if method == "HEAD":
                    data = b""
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_DELETE(self):
                self._handle("DELETE")

            def do_HEAD(self):
                self._handle("HEAD")

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_port
        self._thread: threading.Thread | None = None

    def start(self) -> "HttpServer":
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
