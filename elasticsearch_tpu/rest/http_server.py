"""REST API over HTTP: the reference's port-9200 surface.

Analog of /root/reference/src/main/java/org/elasticsearch/rest/ (RestController
path-trie dispatch, rest/action/* 1:1 handlers) + http/netty/. The wire
contract targets the machine-readable specs in
/root/reference/rest-api-spec/api/*.json (ES 2.0 response shapes) so existing
clients can point at this server unchanged.

Implementation: stdlib ThreadingHTTPServer — the control plane is IO-bound
host code; the data plane stays on device. (A C++ server lands with the
native runtime milestone; the handler table below is transport-agnostic.)
"""

from __future__ import annotations

import contextlib
import json
import fnmatch
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

from ..index.engine import VersionConflictException, DocumentMissingException
from ..node import (IndexAlreadyExistsException, IndexClosedException,
                    IndexMissingException, InvalidIndexNameException,
                    NodeService)
from ..search.aggs import AggregationParsingException
from ..search.query_dsl import QueryParsingException


class RestError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


def _status_of(e: Exception) -> int:
    from ..common.breaker import CircuitBreakingException
    from ..common.threadpool import EsRejectedExecutionException
    from ..serving.qos import QosShedException
    if isinstance(e, RestError):
        return e.status
    if isinstance(e, (CircuitBreakingException, EsRejectedExecutionException,
                      QosShedException)):
        return 429     # TOO_MANY_REQUESTS, ref EsRejectedExecutionException
    from ..snapshots import (RepositoryException, SnapshotException,
                             SnapshotMissingException)
    if isinstance(e, SnapshotMissingException):
        return 404
    if isinstance(e, (RepositoryException, SnapshotException)):
        return 400
    if isinstance(e, IndexClosedException):
        return 403     # ClusterBlockException / INDEX_CLOSED_BLOCK
    if isinstance(e, IndexMissingException):
        return 404
    if isinstance(e, DocumentMissingException):
        return 404
    if isinstance(e, IndexAlreadyExistsException):
        return 400
    if isinstance(e, VersionConflictException):
        return 409
    from ..script.engine import ScriptException
    from ..mapping.mapper import (AlreadyExpiredException,
                                  MapperParsingException,
                                  MergeMappingException,
                                  RoutingMissingException)
    if isinstance(e, (InvalidIndexNameException, QueryParsingException,
                      AggregationParsingException, ScriptException,
                      MapperParsingException, MergeMappingException,
                      RoutingMissingException, AlreadyExpiredException,
                      json.JSONDecodeError, KeyError, ValueError)):
        return 400
    return 500


class RestController:
    """Method+path-pattern dispatch (ref rest/RestController.java:44,119,163
    path trie; regex table is equivalent at this route count)."""

    def __init__(self, node: NodeService, registrar: Callable | None = None):
        self.node = node
        self.routes: list[tuple[str, re.Pattern, Callable]] = []
        (registrar or _register_routes)(self, node)

    def register(self, method: str, pattern: str, handler: Callable) -> None:
        # {name} -> named group; e.g. /{index}/_search
        rx = re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern)
        # specificity: literal segments outrank parameters (the path-trie
        # rule — /_mget must beat /{index})
        segs = [s for s in pattern.split("/") if s]
        literal = sum(1 for s in segs if "{" not in s)
        self.routes.append((method, re.compile(f"^{rx}/?$"), handler,
                            (literal, -len(segs))))

    def dispatch(self, method: str, path: str, params: dict,
                 body: bytes,
                 headers: dict | None = None) -> tuple[int, dict | str]:
        from urllib.parse import unquote
        # percent-decode per segment (ref RestUtils.decodeComponent) —
        # unicode index names / ids arrive encoded
        path = "/".join(unquote(seg) for seg in path.split("/"))
        best = None
        for m, rx, handler, spec in self.routes:
            if m != method:
                continue
            match = rx.match(path)
            if match and (best is None or spec > best[2]):
                best = (handler, match, spec)
        if best is None:
            raise RestError(400, f"no handler for [{method} {path}]")
        handler, match, _ = best
        tasks = getattr(self.node, "tasks", None)
        if tasks is None:
            return handler(match.groupdict(), params, body)
        # every REST request is a registered task carrying the caller's
        # X-Opaque-Id plus a generated trace id; child scopes (per-shard
        # phases, transport handlers) inherit both via the task context.
        # The span tracer roots at the SAME trace id, so slowlog, task
        # listing, profile and GET /_traces correlate on one id;
        # `?trace=true` forces retention past the sampler.
        opaque = (headers or {}).get("x-opaque-id")
        with tasks.scope(_action_of(method, path),
                         description=f"{method} {path}",
                         opaque_id=opaque) as task:
            tracer = getattr(self.node, "tracer", None)
            if tracer is None or not tracer.enabled \
                    or path.startswith("/_traces"):
                # reading traces must never perturb the trace store
                return handler(match.groupdict(), params, body)
            with tracer.request(f"{method} {path}",
                                trace_id=task.trace_id,
                                force=_pbool(params, "trace", False),
                                opaque_id=opaque,
                                attrs={"method": method, "path": path,
                                       "action": task.action}):
                return handler(match.groupdict(), params, body)


def _action_of(method: str, path: str) -> str:
    """Reference-style action name for the task registry (each
    TransportAction declares one; here the route class implies it)."""
    seg = [s for s in path.split("/") if s]
    if any(s in ("_search", "_msearch", "_count", "_suggest", "_percolate",
                 "_mpercolate", "_mlt", "_explain", "_validate")
           for s in seg):
        return "indices:data/read/search"
    if "_bulk" in seg:
        return "indices:data/write/bulk"
    if "_mget" in seg:
        return "indices:data/read/mget"
    if "_tasks" in seg or ("_cat" in seg and "tasks" in seg):
        return "cluster:monitor/tasks/lists"
    if any(s in ("_nodes", "_cluster", "_cat", "_stats") for s in seg):
        return "cluster:monitor"
    if len(seg) >= 3 and not any(s.startswith("_") for s in seg[:2]):
        return "indices:data/read/get" if method in ("GET", "HEAD") \
            else "indices:data/write/index"
    return f"rest:{method.lower()}" + ("/" + seg[0] if seg else "/")


def _pbool(p: dict, name: str, default: bool) -> bool:
    """Boolean URL param: accepts true/false, 1/0, yes/no (ES client
    convention — the YAML suites use all three spellings)."""
    v = p.get(name, [None])[0]
    if v is None:
        return default
    return str(v).lower() not in ("false", "0", "no", "off")


def _meta_field_of(res, f: str):
    """_timestamp / _ttl rendering for `fields` (ref internal field
    mappers: _timestamp returns the index instant, _ttl the REMAINING
    time-to-live)."""
    import time as _time
    if f == "_timestamp":
        return res.timestamp
    if f == "_ttl" and res.ttl_expiry is not None:
        return res.ttl_expiry - int(_time.time() * 1000)
    return None


def _json_body(body: bytes) -> dict:
    if not body:
        return {}
    return json.loads(body)


def _register_routes(c: RestController, node: NodeService) -> None:
    def _resolve_lenient(expr, p):
        return _resolve_lenient_impl(node, expr, p)

    def _expand_indices(expr, p):
        return _expand_indices_impl(node, expr, p)

    # -- cluster / node level ---------------------------------------------
    def root(g, p, b):
        return 200, {"status": 200, "name": "tpu-node-0",
                     "cluster_name": node.cluster_name,
                     "version": {"number": "2.0.0-tpu",
                                 "lucene_version": "tensor-native"},
                     "tagline": "You Know, for Search"}
    c.register("GET", "/", root)
    c.register("HEAD", "/", lambda g, p, b: (200, {}))

    c.register("GET", "/_cluster/health",
               lambda g, p, b: (200, node.cluster_health(
                   p.get("level", ["cluster"])[0])))
    c.register("GET", "/_cluster/health/{index}",
               lambda g, p, b: (200, node.cluster_health(
                   p.get("level", ["cluster"])[0])))

    def put_template(g, p, b):
        if _pbool(p, "create", False) and g["name"] in node.templates:
            raise RestError(400, f"IndexTemplateAlreadyExistsException: "
                                 f"index_template [{g['name']}] already "
                                 f"exists")
        node.put_template(g["name"], _json_body(b))
        return 200, {"acknowledged": True}
    c.register("PUT", "/_template/{name}", put_template)

    # -- snapshots (ref rest/action/admin/cluster/snapshots/) --------------
    c.register("PUT", "/_snapshot/{repo}",
               lambda g, p, b: (200, node.snapshots.put_repository(
                   g["repo"], _json_body(b))))
    c.register("POST", "/_snapshot/{repo}",
               lambda g, p, b: (200, node.snapshots.put_repository(
                   g["repo"], _json_body(b))))
    def get_repo(g, p, b):
        name = g.get("repo")
        if name in (None, "_all", "*"):
            return 200, dict(node.snapshots.repos)
        return 200, node.snapshots.get_repository(name)
    c.register("GET", "/_snapshot", get_repo)
    c.register("GET", "/_snapshot/{repo}", get_repo)
    c.register("POST", "/_snapshot/{repo}/_verify",
               lambda g, p, b: (
                   200, {"nodes": {"tpu-node-0": {"name": "tpu-node-0"}}})
               if g["repo"] in node.snapshots.repos
               else (404, {"error": f"RepositoryMissingException: "
                                    f"[{g['repo']}] missing", "status": 404}))
    c.register("PUT", "/_snapshot/{repo}/{snap}",
               lambda g, p, b: (200, node.snapshots.create_snapshot(
                   g["repo"], g["snap"], _json_body(b))))
    c.register("GET", "/_snapshot/{repo}/{snap}",
               lambda g, p, b: (200, node.snapshots.get_snapshots(
                   g["repo"], g["snap"])))
    c.register("DELETE", "/_snapshot/{repo}/{snap}",
               lambda g, p, b: (200, node.snapshots.delete_snapshot(
                   g["repo"], g["snap"])))
    c.register("POST", "/_snapshot/{repo}/{snap}/_restore",
               lambda g, p, b: (200, node.snapshots.restore_snapshot(
                   g["repo"], g["snap"], _json_body(b))))

    # -- search (must register before the generic doc routes) -------------
    def search(g, p, b):
        body = _json_body(b)
        if "q" in p:   # URI search (ref RestSearchAction query_string support)
            body.setdefault("query", {"query_string": {"query": p["q"][0]}})
        if "size" in p:
            body["size"] = int(p["size"][0])
        if "from" in p:
            body["from"] = int(p["from"][0])
        if "sort" in p and "sort" not in body:
            # URI sort: "field", "field:desc", comma lists (RestSearchAction)
            clauses = []
            for part in p["sort"][0].split(","):
                if ":" in part:
                    f, o = part.rsplit(":", 1)
                    clauses.append({f: {"order": o}})
                else:
                    clauses.append(part)
            body["sort"] = clauses
        # URL _source/_source_include/_source_exclude override the body spec
        # (ref RestSearchAction.parseSearchSource fetchSource handling)
        s = p.get("_source", [None])[0]
        if s is not None:
            body["_source"] = False if s == "false" else \
                (True if s == "true" else s.split(","))
        inc = p.get("_source_include", p.get("_source_includes", [None]))[0]
        exc = p.get("_source_exclude", p.get("_source_excludes", [None]))[0]
        if inc or exc:
            # combine with any ?_source= list into ONE fetch-source context
            cur = body.get("_source")
            inc_l = inc.split(",") if inc else \
                (cur if isinstance(cur, list)
                 else [cur] if isinstance(cur, str) else None)
            body["_source"] = {"include": inc_l,
                               "exclude": exc.split(",") if exc else None}
        scroll = p.get("scroll", [None])[0]
        scan = p.get("search_type", [None])[0] == "scan"
        rc = p.get("request_cache", [None])[0]
        return 200, node.search(g.get("index", "_all"), body, scroll=scroll,
                                scan=scan,
                                request_cache=None if rc is None
                                else rc == "true")

    def scroll_next(g, p, b):
        body = _json_body(b) if b and b.strip().startswith(b"{") else {}
        sid = g.get("scroll_id") or body.get("scroll_id") \
            or p.get("scroll_id", [None])[0] \
            or (b.decode().strip() if b else None)
        if not sid:
            raise RestError(400, "scroll_id is required")
        keep = body.get("scroll") or p.get("scroll", [None])[0]
        return 200, node.scroll(sid, keep)
    c.register("GET", "/_search/scroll", scroll_next)
    c.register("POST", "/_search/scroll", scroll_next)
    c.register("GET", "/_search/scroll/{scroll_id}", scroll_next)
    c.register("POST", "/_search/scroll/{scroll_id}", scroll_next)

    def clear_scroll(g, p, b):
        body = _json_body(b)
        sids = g.get("scroll_id") or body.get("scroll_id") \
            or p.get("scroll_id", [None])[0] or []
        if isinstance(sids, str):
            sids = sids.split(",")
        if sids == ["_all"]:
            sids = list(node._scrolls)
            n = node.clear_scroll(sids)
        else:
            n = node.clear_scroll(sids)
            if n == 0 and sids:
                return 404, {"succeeded": True, "num_freed": 0}
        return 200, {"succeeded": True, "num_freed": n}
    c.register("DELETE", "/_search/scroll", clear_scroll)
    c.register("DELETE", "/_search/scroll/{scroll_id}", clear_scroll)
    c.register("GET", "/{index}/_search", search)
    c.register("POST", "/{index}/_search", search)
    c.register("GET", "/_search", search)
    c.register("POST", "/_search", search)
    c.register("GET", "/{index}/{type}/_search",
               lambda g, p, b: search(g, p, b))
    c.register("POST", "/{index}/{type}/_search",
               lambda g, p, b: search(g, p, b))

    def count(g, p, b):
        return 200, node.count(g.get("index", "_all"), _json_body(b))
    c.register("GET", "/{index}/_count", count)
    c.register("POST", "/{index}/_count", count)
    c.register("GET", "/_count", count)

    def msearch(g, p, b):
        # NDJSON: alternating header / body lines
        # (ref rest/action/search/RestMultiSearchAction)
        lines = [json.loads(ln) for ln in b.decode("utf-8").split("\n")
                 if ln.strip()]
        if len(lines) % 2:
            raise RestError(400, "msearch body must be header/body pairs")
        requests = []
        for i in range(0, len(lines), 2):
            header = dict(lines[i])
            if g.get("index") and "index" not in header:
                header["index"] = g["index"]
            requests.append((header, lines[i + 1]))
        # raw=True: the packed serving lane pre-serializes hit JSON with
        # vectorized string ops; bytes pass straight through to the socket
        return 200, node.msearch(requests, raw=True)
    def mlt_api(g, p, b):
        spec: dict = {"ids": [g["id"]]}
        if "mlt_fields" in p:
            spec["fields"] = p["mlt_fields"][0].split(",")
        for prm, key in (("min_term_freq", "min_term_freq"),
                         ("min_doc_freq", "min_doc_freq"),
                         ("max_query_terms", "max_query_terms")):
            if prm in p:
                spec[key] = int(p[prm][0])
        body = _json_body(b)
        body["query"] = {"more_like_this": spec}
        return 200, node.search(g["index"], body)
    c.register("GET", "/{index}/{type}/{id}/_mlt", mlt_api)
    c.register("POST", "/{index}/{type}/{id}/_mlt", mlt_api)

    def percolate_api(g, p, b, count_only=False):
        body = _json_body(b)
        doc_index, doc_type = g["index"], g.get("type", "_doc")
        # percolate_index/percolate_type: fetch the doc from one index,
        # match against ANOTHER's registered queries (ref
        # RestPercolateAction existing-doc routing)
        perc_index = p.get("percolate_index", [doc_index])[0]
        perc_type = p.get("percolate_type", [doc_type])[0]
        if g.get("id") is not None and "doc" not in (body or {}):
            got = node.get_doc(node._resolve(doc_index)[0], str(g["id"]))
            if not got.found:
                raise DocumentMissingException(
                    f"[{doc_type}][{g['id']}]: document missing")
            want_ver = p.get("version", [None])[0]
            if want_ver is not None and int(want_ver) != got.version:
                raise VersionConflictException(str(g["id"]), got.version,
                                               int(want_ver))
            body = {**(body or {}), "doc": got.source}
        out = node.percolate(perc_index, body, type_name=perc_type,
                             doc_id=None)
        if count_only:
            out = {k: v for k, v in out.items() if k != "matches"}
        return 200, out
    for m in ("GET", "POST"):
        c.register(m, "/{index}/{type}/_percolate", percolate_api)
        c.register(m, "/{index}/{type}/{id}/_percolate", percolate_api)
        c.register(m, "/{index}/{type}/_percolate/count",
                   lambda g, p, b: percolate_api(g, p, b, count_only=True))
        c.register(m, "/{index}/{type}/{id}/_percolate/count",
                   lambda g, p, b: percolate_api(g, p, b, count_only=True))

    def mpercolate_api(g, p, b):
        lines = [ln for ln in b.decode("utf-8").split("\n") if ln.strip()]
        items = []   # (index, type, body, doc_id, parse_error)
        i = 0
        while i < len(lines):
            start = i
            try:
                head = json.loads(lines[i])
                i += 1
                body = json.loads(lines[i]) if i < len(lines) else {}
                i += 1
                (_kind, meta), = head.items()
                items.append((meta.get("index", g.get("index", "_all")),
                              meta.get("type", "_doc"), body,
                              meta.get("id"), None))
            except Exception as e:  # noqa: BLE001 — per-item contract
                i = start + 2   # skip the malformed header+body pair
                items.append((None, None, None, None,
                              f"{type(e).__name__}[{e}]"))
        responses: list = [None] * len(items)
        # inline-doc items sharing an (index, type) batch into ONE dense
        # doc×query matrix dispatch (node.mpercolate, ISSUE 18); items
        # with an existing-doc id or a parse error run per item below
        groups: dict = {}
        for idx, (ix, tp, body, did, err) in enumerate(items):
            if err is None and did is None \
                    and isinstance(body, dict) and "doc" in body:
                groups.setdefault((ix, tp), []).append(idx)
        for (ix, tp), idxs in groups.items():
            try:
                outs = node.mpercolate(
                    ix, [items[j][2] for j in idxs],
                    type_name=tp)["responses"]
                for j, out in zip(idxs, outs):
                    responses[j] = out
            except Exception as e:  # noqa: BLE001 — per-item contract
                for j in idxs:
                    responses[j] = {"error": f"{type(e).__name__}[{e}]"}
        for idx, (ix, tp, body, did, err) in enumerate(items):
            if responses[idx] is not None:
                continue
            if err is not None:
                responses[idx] = {"error": err}
                continue
            try:
                responses[idx] = node.percolate(ix, body, type_name=tp,
                                                doc_id=did)
            except Exception as e:  # noqa: BLE001 — per-item contract
                responses[idx] = {"error": f"{type(e).__name__}[{e}]"}
        return 200, {"responses": responses}
    c.register("GET", "/_mpercolate", mpercolate_api)
    c.register("POST", "/_mpercolate", mpercolate_api)
    c.register("GET", "/{index}/_mpercolate", mpercolate_api)
    c.register("POST", "/{index}/_mpercolate", mpercolate_api)
    c.register("GET", "/{index}/{type}/_mpercolate", mpercolate_api)
    c.register("POST", "/{index}/{type}/_mpercolate", mpercolate_api)

    # -- search templates (ref RestSearchTemplateAction + script store) ----
    def put_search_template(g, p, b):
        body = _json_body(b)
        tpl = body.get("template", body)
        compact = tpl if isinstance(tpl, str) \
            else json.dumps(tpl, separators=(",", ":"))
        if re.search(r"\{\{\s*\}\}", compact):
            # empty mustache variable — the reference's compile-time reject
            raise RestError(
                400, "ElasticsearchIllegalArgumentException[Unable to parse "
                     "template: empty mustache variable]")
        created = g["id"] not in node.search_templates
        node.search_templates[g["id"]] = body.get("template", body)
        node._persist_search_templates()
        # templates live in the .scripts system index in the reference
        return (201 if created else 200), {
            "_index": ".scripts", "_type": "mustache", "_id": g["id"],
            "_version": 1, "created": created, "acknowledged": True}
    c.register("PUT", "/_search/template/{id}", put_search_template)
    c.register("POST", "/_search/template/{id}", put_search_template)

    def get_search_template(g, p, b):
        tpl = node.search_templates.get(g["id"])
        if tpl is None:
            return 404, {"_id": g["id"], "found": False}
        # the reference stores templates as COMPACT script strings
        rendered = tpl if isinstance(tpl, str) \
            else json.dumps(tpl, separators=(",", ":"))
        return 200, {"_index": ".scripts", "_type": "mustache",
                     "_id": g["id"], "found": True, "lang": "mustache",
                     "template": rendered}
    c.register("GET", "/_search/template/{id}", get_search_template)

    def delete_search_template(g, p, b):
        if node.search_templates.pop(g["id"], None) is None:
            return 404, {"_index": ".scripts", "_type": "mustache",
                         "_id": g["id"], "found": False}
        node._persist_search_templates()
        return 200, {"_index": ".scripts", "_type": "mustache",
                     "_id": g["id"], "_version": 2, "found": True,
                     "acknowledged": True}
    c.register("DELETE", "/_search/template/{id}", delete_search_template)

    def search_template(g, p, b):
        from ..search.templates import render_template
        body = render_template(_json_body(b), node.search_templates)
        return 200, node.search(g.get("index", "_all"), body)
    c.register("GET", "/_search/template", search_template)
    c.register("POST", "/_search/template", search_template)
    c.register("GET", "/{index}/_search/template", search_template)
    c.register("POST", "/{index}/_search/template", search_template)
    c.register("GET", "/{index}/{type}/_search/template", search_template)
    c.register("POST", "/{index}/{type}/_search/template", search_template)

    def suggest_api(g, p, b):
        out = node.suggest(g.get("index", "_all"), _json_body(b))
        return 200, {"_shards": {"total": 1, "successful": 1, "failed": 0},
                     **out}
    c.register("GET", "/_suggest", suggest_api)
    c.register("POST", "/_suggest", suggest_api)
    c.register("GET", "/{index}/_suggest", suggest_api)
    c.register("POST", "/{index}/_suggest", suggest_api)

    c.register("GET", "/_msearch", msearch)
    c.register("POST", "/_msearch", msearch)
    c.register("GET", "/{index}/_msearch", msearch)
    c.register("POST", "/{index}/_msearch", msearch)

    # -- bulk --------------------------------------------------------------
    def bulk(g, p, b):
        import time
        t0 = time.perf_counter()
        default_index = g.get("index")
        ops = _parse_bulk(b, default_index)
        items = node.bulk(ops)
        errors = any(next(iter(i.values())).get("status", 200) >= 300
                     for i in items)
        if p.get("refresh", ["false"])[0] != "false":
            node.refresh(default_index or "_all")
        # pre-serialized compact bytes: a 100k-doc ingest emits ~10MB of
        # item acks — compact separators + the handler's bytes fast lane
        # keep response encoding out of the ingest budget
        return 200, json.dumps(
            {"took": int((time.perf_counter() - t0) * 1000),
             "errors": errors, "items": items},
            separators=(",", ":")).encode()
    c.register("POST", "/_bulk", bulk)
    c.register("PUT", "/_bulk", bulk)
    c.register("POST", "/{index}/_bulk", bulk)
    c.register("POST", "/{index}/{type}/_bulk", bulk)

    # -- admin per index ---------------------------------------------------
    def create_index(g, p, b):
        body = _json_body(b)
        svc = node.create_index(g["index"], settings=body.get("settings"),
                                mappings=body.get("mappings"),
                                aliases=body.get("aliases"))
        if body.get("warmers"):
            svc.warmers = {w: {"types": spec.get("types", []),
                               "source": spec.get("source", {})}
                           for w, spec in body["warmers"].items()}
        return 200, {"acknowledged": True}
    c.register("PUT", "/{index}", create_index)
    c.register("POST", "/{index}", create_index)

    def delete_index(g, p, b):
        node.delete_index(g["index"])
        return 200, {"acknowledged": True}
    c.register("DELETE", "/{index}", delete_index)

    def index_exists(g, p, b):
        try:
            node._resolve(g["index"])
            return 200, {}
        except IndexClosedException:
            return 200, {}     # closed indices exist
        except IndexMissingException:
            return 404, {}
    c.register("HEAD", "/{index}", index_exists)

    def refresh(g, p, b):
        node.refresh(g.get("index", "_all"))
        return 200, {"_shards": {"failed": 0}}
    c.register("POST", "/{index}/_refresh", refresh)
    c.register("POST", "/_refresh", refresh)

    def flush(g, p, b):
        node.flush(g.get("index", "_all"))
        return 200, {"_shards": {"failed": 0}}
    c.register("POST", "/{index}/_flush", flush)
    c.register("POST", "/_flush", flush)

    def optimize(g, p, b):
        node.force_merge(g.get("index", "_all"),
                         int(p.get("max_num_segments", [1])[0]))
        return 200, {"_shards": {"failed": 0}}
    c.register("POST", "/{index}/_optimize", optimize)
    c.register("POST", "/_optimize", optimize)
    c.register("POST", "/{index}/_forcemerge", optimize)

    def get_mapping(g, p, b):
        tpat = g.get("type")
        out = {}
        found_type = False
        opens, closeds = _expand_indices(g.get("index", "_all"), p)
        for n in opens:
            md = node.indices[n].mappings_dict()
            if tpat and tpat not in ("_all", "*"):
                md = {t: m for t, m in md.items()
                      if any(fnmatch.fnmatch(t, pat)
                             for pat in tpat.split(","))}
            if md:
                found_type = True
            out[n] = {"mappings": md}
        for n in closeds:
            if n not in out:
                out[n] = {"mappings": node.closed[n].get("mappings") or {}}
                found_type = True
        if tpat and tpat not in ("_all", "*") and not found_type:
            return 200, {}     # no matching type: empty body, HTTP 200
        return 200, out
    c.register("GET", "/{index}/_mapping", get_mapping)
    c.register("GET", "/_mapping", get_mapping)
    c.register("GET", "/{index}/_mapping/{type}", get_mapping)
    c.register("GET", "/_mapping/{type}", get_mapping)
    c.register("GET", "/{index}/{type}/_mapping", get_mapping)

    def head_type(g, p, b):
        try:
            for n in node._resolve(g["index"]):
                if g["type"] in node.indices[n].mappers.types():
                    return 200, {}
        except IndexMissingException:
            pass
        return 404, {}
    c.register("HEAD", "/{index}/{type}", head_type)

    def field_mapping(g, p, b):
        """GET field mappings (ref indices.get_field_mapping spec +
        TransportGetFieldMappingsAction: full-path patterns key by full
        path, leaf-relative patterns key by leaf name; empty result = {};
        unknown explicit type = TypeMissingException 404)."""
        fields = g.get("field", "*").split(",")
        tpat = g.get("type")
        include_defaults = _pbool(p, "include_defaults", False)
        out = {}
        matched_type = False
        for n in node._resolve(g.get("index", "_all")):
            svc = node.indices[n]
            tmap = {}
            for t in svc.mappers.types():
                if tpat and tpat not in ("_all", "*") \
                        and not any(fnmatch.fnmatch(t, pp)
                                    for pp in tpat.split(",")):
                    continue
                matched_type = True
                dm = svc.mappers.document_mapper(t, create=False)
                fmap = {}
                for f in fields:
                    # full-name matches win; ONLY if a pattern matches no
                    # full name does it fall back to leaf (index-name)
                    # matching, keyed by the leaf-relative name
                    hits = [(path, path) for path in dm.fields
                            if fnmatch.fnmatch(path, f)]
                    if not hits:
                        hits = [(path.split(".")[-1], path)
                                for path in dm.fields
                                if fnmatch.fnmatch(path.split(".")[-1], f)]
                    for key, path in hits:
                        ft = dm.fields[path]
                        d = ft.to_dict()
                        if include_defaults and d.get("type") == "string" \
                                and "analyzer" not in d \
                                and d.get("index") != "not_analyzed":
                            d = {**d, "analyzer": "default"}
                        fmap[key] = {"full_name": path,
                                     "mapping": {path.split(".")[-1]: d}}
                if fmap:
                    tmap[t] = fmap
            if tmap:
                out[n] = {"mappings": tmap}
        if tpat and tpat not in ("_all", "*") and not matched_type:
            return 404, {"error": f"TypeMissingException: "
                                  f"type[[{tpat}]] missing", "status": 404}
        return 200, out
    c.register("GET", "/_mapping/field/{field}", field_mapping)
    c.register("GET", "/{index}/_mapping/field/{field}", field_mapping)
    c.register("GET", "/{index}/_mapping/{type}/field/{field}",
               field_mapping)
    c.register("GET", "/_mapping/{type}/field/{field}", field_mapping)

    def put_mapping(g, p, b):
        body = _json_body(b)
        tname = g.get("type", "_doc")
        mapping = body.get(tname, body)
        for n in node._resolve(g.get("index", "_all")):
            node.put_mapping(n, tname, mapping)
        return 200, {"acknowledged": True}
    c.register("PUT", "/{index}/_mapping/{type}", put_mapping)
    c.register("PUT", "/{index}/{type}/_mapping", put_mapping)
    c.register("PUT", "/{index}/_mapping", put_mapping)
    c.register("POST", "/{index}/_mapping/{type}", put_mapping)
    c.register("POST", "/{index}/{type}/_mapping", put_mapping)
    c.register("PUT", "/_mapping/{type}", put_mapping)   # blank index = _all
    c.register("POST", "/_mapping/{type}", put_mapping)

    def analyze(g, p, b):
        body = _json_body(b)
        text = body.get("text") or (p.get("text", [""])[0])
        svc = node.index_service(g["index"]) if g.get("index") else None
        from ..analysis.analyzers import AnalysisService, Analyzer
        an = (svc.mappers.analysis if svc else AnalysisService())
        tokenizer = body.get("tokenizer", p.get("tokenizer", [None])[0])
        filters = body.get("filters", body.get("token_filters"))
        if filters is None:
            filters = p.get("filters", [None])[0]
            filters = filters.split(",") if filters else []
        elif isinstance(filters, str):
            filters = filters.split(",")
        field = body.get("field", p.get("field", [None])[0])
        if tokenizer:
            analyzer_obj = an.custom(tokenizer, filters)
        elif field and svc is not None \
                and "analyzer" not in body and "analyzer" not in p:
            # field form: analyze with THAT field's analyzer — keyword /
            # not_analyzed fields preserve the raw token
            ft = svc.mappers.field_type(field)
            if ft is not None and ft.type == "keyword":
                analyzer_obj = an.analyzer("keyword")
            elif ft is not None:
                analyzer_obj = an.analyzer(ft.analyzer)
            else:
                analyzer_obj = an.analyzer("standard")
        else:
            name = body.get("analyzer", p.get("analyzer", ["standard"])[0])
            analyzer_obj = an.analyzer(name)
        tokens = analyzer_obj.analyze(
            text if isinstance(text, str) else " ".join(text))
        return 200, {"tokens": [
            {"token": t, "start_offset": 0, "end_offset": 0,
             "type": "<ALPHANUM>", "position": i}
            for i, t in enumerate(tokens)]}
    c.register("GET", "/_analyze", analyze)
    c.register("POST", "/_analyze", analyze)
    c.register("GET", "/{index}/_analyze", analyze)
    c.register("POST", "/{index}/_analyze", analyze)

    # -- documents ---------------------------------------------------------
    def put_doc(g, p, b):
        kw = {}
        if "version" in p:
            kw["version"] = int(p["version"][0])
            kw["version_type"] = p.get("version_type", ["internal"])[0]
        if p.get("op_type", [None])[0] == "create":
            kw["op_type"] = "create"
        if "version" in p:
            kw["version"] = int(p["version"][0])
        if "version_type" in p:
            kw["version_type"] = p["version_type"][0]
        routing = p.get("routing", [None])[0]
        parent = p.get("parent", [None])[0]
        _, res = node.index_doc(g["index"], g.get("id"), _json_body(b),
                                type_name=g.get("type", "_doc"),
                                routing=routing, parent=parent,
                                timestamp=p.get("timestamp", [None])[0],
                                ttl=p.get("ttl", [None])[0], **kw)
        if _pbool(p, "refresh", False):
            node.refresh_doc_shard(g["index"], res.doc_id,
                                   routing or parent)
        status = 201 if res.created else 200
        out = {"_index": g["index"], "_type": g.get("type", "_doc"),
               "_id": res.doc_id, "_version": res.version,
               "created": res.created,
               "_shards": _write_shards(node, g["index"])}
        # percolate-on-ingest (ref RestIndexAction ?percolate=): the just-
        # written doc runs against the registered queries of the SAME index
        # (or the query given in the param) through the dense matrix lane;
        # matches ride back on the index response
        if p.get("percolate", [None])[0] is not None:
            praw = p["percolate"][0]
            pbody: dict = {"doc": _json_body(b)}
            if praw not in ("", "*", "true", "1"):
                try:
                    pbody.update(json.loads(praw))
                except (ValueError, TypeError):
                    pass
            perc = node.percolate(g["index"], pbody,
                                  type_name=g.get("type", "_doc"))
            out["matches"] = perc["matches"]
        return status, out
    c.register("PUT", "/{index}/{type}/{id}", put_doc)
    c.register("POST", "/{index}/{type}/{id}", put_doc)
    c.register("POST", "/{index}/{type}", put_doc)

    def create_doc(g, p, b):
        p = {**p, "op_type": ["create"]}
        return put_doc(g, p, b)
    c.register("PUT", "/{index}/{type}/{id}/_create", create_doc)
    c.register("POST", "/{index}/{type}/{id}/_create", create_doc)

    def _resolve_get(g, p):
        """Shared GET semantics: realtime, version check, source filtering
        (ref index/get/ShardGetService + RestGetAction params)."""
        realtime = _pbool(p, "realtime", True)
        if _pbool(p, "refresh", False):
            node.refresh(g["index"])
        routing = p.get("routing", [None])[0]
        parent = p.get("parent", [None])[0]
        tname = g.get("type")
        if routing is None and parent is None and tname:
            svc = node.indices.get(g["index"])
            if svc is not None and svc.mappers.parent_type_of(tname):
                from ..mapping.mapper import RoutingMissingException
                raise RoutingMissingException(
                    f"routing is required for [{g['index']}]/[{tname}]/"
                    f"[{g['id']}]")
        res = node.get_doc(g["index"], g["id"],
                           routing=routing, parent=parent,
                           realtime=realtime)
        if res.found and "version" in p \
                and p.get("version_type", ["internal"])[0] != "force" \
                and int(p["version"][0]) != res.version:
            # force never conflicts on reads (ref VersionType.FORCE)
            raise VersionConflictException(
                g["id"], res.version, int(p["version"][0]))
        return res

    def _source_of(res, p):
        src = res.source
        spec = p.get("_source", [None])[0]
        if spec is not None:
            if spec in ("false", "no"):
                return None
            if spec not in ("true", "yes"):
                src = _source_filter_paths(src, spec.split(","), None)
        inc = p.get("_source_include", p.get("_source_includes", [None]))[0]
        exc = p.get("_source_exclude", p.get("_source_excludes", [None]))[0]
        if inc or exc:
            src = _source_filter_paths(src, inc.split(",") if inc else None,
                                       exc.split(",") if exc else None)
        return src

    def get_doc(g, p, b):
        res = _resolve_get(g, p)
        out = {"_index": g["index"], "_type": res.type_name, "_id": g["id"],
               "found": res.found}
        if res.found:
            out["_version"] = res.version
            src = _source_of(res, p)
            # fields param suppresses _source unless explicitly requested
            # (ref RestGetAction: fields and source are separate fetches)
            fld_list = p["fields"][0].split(",") if "fields" in p else None
            if src is not None and (fld_list is None
                                    or "_source" in fld_list
                                    or "_source" in p):
                out["_source"] = src
            if fld_list is not None:
                fields = {}
                for f in fld_list:
                    if f == "_source":
                        continue
                    if f == "_routing":
                        if res.routing is not None:
                            fields["_routing"] = res.routing
                        continue
                    if f == "_parent":
                        if res.parent is not None:
                            fields["_parent"] = res.parent
                        continue
                    if f in ("_timestamp", "_ttl"):
                        v = _meta_field_of(res, f)
                        if v is not None:
                            fields[f] = v
                        continue
                    v = res.source.get(f) if res.source else None
                    if v is not None:
                        fields[f] = v if isinstance(v, list) else [v]
                if fields:
                    out["fields"] = fields
        return (200 if res.found else 404), out
    c.register("GET", "/{index}/{type}/{id}", get_doc)

    def get_source(g, p, b):
        res = _resolve_get(g, p)
        if not res.found:
            return 404, {"error": "not found", "status": 404}
        src = _source_of(res, p)
        return 200, src if src is not None else {}
    c.register("GET", "/{index}/{type}/{id}/_source", get_source)

    def head_doc(g, p, b):
        try:
            res = _resolve_get(g, p)
        except IndexMissingException:
            return 404, {}
        return (200 if res.found else 404), {}
    c.register("HEAD", "/{index}/{type}/{id}", head_doc)
    c.register("HEAD", "/{index}/{type}/{id}/_source", head_doc)

    def delete_doc(g, p, b):
        kw = {}
        if "version" in p:
            kw["version"] = int(p["version"][0])
        if "version_type" in p:
            kw["version_type"] = p["version_type"][0]
        routing = p.get("routing", [None])[0]
        parent = p.get("parent", [None])[0]
        res = node.delete_doc(g["index"], g["id"],
                              routing=routing, parent=parent, **kw)
        if _pbool(p, "refresh", False):
            node.refresh_doc_shard(g["index"], g["id"],
                                   routing or parent)
        return (200 if res.found else 404), {
            "found": res.found, "_index": g["index"],
            "_type": g.get("type", "_doc"), "_id": g["id"],
            "_version": res.version,
            "_shards": _write_shards(node, g["index"])}
    c.register("DELETE", "/{index}/{type}/{id}", delete_doc)

    def update_doc(g, p, b):
        vt = p.get("version_type", ["internal"])[0]
        if vt not in ("internal", "force"):
            raise RestError(
                400, "ActionRequestValidationException: version type "
                     f"[{vt}] is not supported by the update API")
        kw = {}
        if "version" in p:
            kw["version"] = int(p["version"][0])
        res, noop = node.update_doc(g["index"], g["id"], _json_body(b),
                                    type_name=g.get("type", "_doc"),
                                    routing=p.get("routing", [None])[0],
                                    parent=p.get("parent", [None])[0],
                                    timestamp=p.get("timestamp", [None])[0],
                                    ttl=p.get("ttl", [None])[0], **kw)
        if _pbool(p, "refresh", False):
            node.refresh_doc_shard(g["index"], g["id"],
                                   p.get("routing", [None])[0]
                                   or p.get("parent", [None])[0])
        out = {"_index": g["index"], "_type": g.get("type", "_doc"),
               "_id": g["id"], "_version": res.version,
               "_shards": _write_shards(node, g["index"])}
        if "fields" in p:
            got = node.get_doc(g["index"], g["id"],
                               routing=p.get("routing", [None])[0],
                               parent=p.get("parent", [None])[0])
            if got.found:
                fields = {}
                src_included = False
                for f in p["fields"][0].split(","):
                    if f == "_source":
                        src_included = True
                        continue
                    v = (got.source or {}).get(f)
                    if v is not None:
                        fields[f] = v if isinstance(v, list) else [v]
                entry: dict = {"found": True, "_version": got.version}
                if src_included:
                    entry["_source"] = got.source
                if fields:
                    entry["fields"] = fields
                out["get"] = entry
        return 200, out
    c.register("POST", "/{index}/{type}/{id}/_update", update_doc)

    def mget(g, p, b):
        body = _json_body(b)
        items = body.get("docs")
        if items is None and "ids" in body:
            items = [{"_id": i} for i in body["ids"]]
        if not items:
            raise RestError(400, "ActionRequestValidationException: "
                                 "Validation Failed: 1: no documents "
                                 "to get;")
        realtime = _pbool(p, "realtime", True)
        if _pbool(p, "refresh", False):
            # refresh every index the request touches, incl. per-doc _index
            touched = {d.get("_index", g.get("index")) for d in items
                       if isinstance(d, dict)} | {g.get("index")}
            for idx in touched:
                if idx:
                    try:
                        node.refresh(idx)
                    except IndexMissingException:
                        pass
        url_fields = p.get("fields", [None])[0]
        if url_fields is not None:
            url_fields = url_fields.split(",")
        # URL-level _source / _source_include / _source_exclude apply to
        # every doc that doesn't carry its own spec (ref RestMultiGetAction
        # defaultFetchSource)
        url_spec = None
        s = p.get("_source", [None])[0]
        if s is not None:
            url_spec = False if s == "false" else \
                (True if s == "true" else s.split(","))
        inc = p.get("_source_include", p.get("_source_includes", [None]))[0]
        exc = p.get("_source_exclude", p.get("_source_excludes", [None]))[0]
        if inc or exc:
            url_spec = {"include": inc.split(",") if inc else None,
                        "exclude": exc.split(",") if exc else None}
        default_type = g.get("type")
        docs = []
        for d in items:
            if not isinstance(d, dict):
                d = {"_id": d}
            idx = d.get("_index", g.get("index"))
            if "_id" not in d:
                raise RestError(400, "ActionRequestValidationException: "
                                     "id is missing")
            if idx is None:
                raise RestError(400, "ActionRequestValidationException: "
                                     "index is missing")
            doc_id = str(d["_id"])
            want_type = d.get("_type", default_type)
            routing = d.get("_routing") or d.get("routing")
            parent = d.get("_parent") or d.get("parent")
            try:
                res = node.get_doc(
                    idx, doc_id,
                    routing=str(routing) if routing is not None else None,
                    parent=str(parent) if parent is not None else None,
                    realtime=realtime)
            except IndexMissingException as e:
                docs.append({"_index": idx,
                             "_type": want_type or "_doc",
                             "_id": doc_id,
                             "error": str(e), "found": False})
                continue
            # type filter: a requested type must MATCH the stored type
            # (ref TransportGetAction type resolution; "_all" matches any)
            found = res.found
            if found and want_type not in (None, "_all") \
                    and res.type_name != want_type:
                found = False
            entry = {"_index": idx,
                     "_type": res.type_name if found
                     else (want_type or "_doc"),
                     "_id": doc_id, "found": found}
            if found:
                entry["_version"] = res.version
                flds = d.get("fields", d.get("_fields", url_fields))
                if flds:
                    if isinstance(flds, str):
                        flds = [flds]
                    fields = {}
                    src_included = False
                    for f in flds:
                        if f == "_source":
                            src_included = True
                        elif f == "_routing":
                            if res.routing is not None:
                                fields["_routing"] = res.routing
                        elif f == "_parent":
                            if getattr(res, "parent", None) is not None:
                                fields["_parent"] = res.parent
                        else:
                            v = (res.source or {}).get(f)
                            if v is not None:
                                fields[f] = v if isinstance(v, list) else [v]
                    if fields:
                        entry["fields"] = fields
                    if src_included:
                        entry["_source"] = res.source
                else:
                    src = res.source
                    spec = d["_source"] if "_source" in d else url_spec
                    if spec is not None:
                        if spec is False:
                            src = None
                        elif spec is not True:
                            if isinstance(spec, str):
                                spec = [spec]
                            inc = spec if isinstance(spec, list) else \
                                spec.get("include", spec.get("includes"))
                            exc = None if isinstance(spec, list) else \
                                spec.get("exclude", spec.get("excludes"))
                            src = _source_filter_paths(src, inc, exc)
                    if src is not None:
                        entry["_source"] = src
            docs.append(entry)
        return 200, {"docs": docs}
    c.register("GET", "/_mget", mget)
    c.register("POST", "/_mget", mget)
    c.register("GET", "/{index}/_mget", mget)
    c.register("POST", "/{index}/_mget", mget)
    c.register("GET", "/{index}/{type}/_mget", mget)
    c.register("POST", "/{index}/{type}/_mget", mget)

    # -- termvectors / mtermvectors (ref action/termvectors/) -------------
    def termvectors(g, p, b):
        body = _json_body(b) if b else {}
        flds = p.get("fields", [None])[0]
        if flds is not None:
            flds = flds.split(",")
        elif body.get("fields"):
            flds = list(body["fields"])
        return 200, node.termvectors(
            g["index"], str(g.get("id", body.get("_id", ""))),
            type_name=g.get("type", "_doc"), fields=flds,
            realtime=_pbool(p, "realtime", True),
            term_statistics=_pbool(p, "term_statistics", False)
            or bool(body.get("term_statistics")),
            field_statistics=_pbool(p, "field_statistics", True),
            positions=_pbool(p, "positions", True),
            offsets=_pbool(p, "offsets", True),
            routing=p.get("routing", [None])[0],
            parent=p.get("parent", [None])[0])
    for pat in ("/{index}/{type}/{id}/_termvectors",
                "/{index}/{type}/{id}/_termvector",
                "/{index}/{type}/_termvectors",
                "/{index}/{type}/_termvector"):
        c.register("GET", pat, termvectors)
        c.register("POST", pat, termvectors)

    def mtermvectors(g, p, b):
        body = _json_body(b) if b else {}
        items = body.get("docs")
        if items is None and "ids" in body:
            items = [{"_id": i} for i in body["ids"]]
        if items is None and "ids" in p:
            items = [{"_id": i} for i in p["ids"][0].split(",")]
        if not items:
            raise RestError(400, "ActionRequestValidationException: "
                                 "Validation Failed: 1: no documents "
                                 "requested;")
        tstats = _pbool(p, "term_statistics", False) \
            or bool(body.get("term_statistics"))
        docs = []
        for d in items:
            idx = d.get("_index", g.get("index"))
            if idx is None:
                docs.append({"error": "index is missing"})
                continue
            try:
                docs.append(node.termvectors(
                    idx, str(d["_id"]),
                    type_name=d.get("_type", g.get("type", "_doc")),
                    fields=d.get("fields"),
                    realtime=_pbool(p, "realtime", True),
                    term_statistics=tstats or bool(d.get("term_statistics")),
                    routing=d.get("_routing") or d.get("routing"),
                    parent=d.get("_parent") or d.get("parent")))
            except Exception as e:  # noqa: BLE001 — per-item contract
                docs.append({"_index": idx, "_id": str(d.get("_id")),
                             "error": f"{type(e).__name__}[{e}]"})
        return 200, {"docs": docs}
    for pat in ("/_mtermvectors", "/{index}/_mtermvectors",
                "/{index}/{type}/_mtermvectors"):
        c.register("GET", pat, mtermvectors)
        c.register("POST", pat, mtermvectors)

    # -- search_shards (ref TransportSearchShardsAction) -------------------
    def search_shards(g, p, b):
        names = node._resolve(g.get("index", "_all"))
        shards = []
        nodes = {"node0": {"name": "tpu-node-0",
                           "transport_address": "local[1]"}}
        for n in names:
            for sid, _e in enumerate(node.indices[n].shards):
                shards.append([{"index": n, "shard": sid, "primary": True,
                                "state": "STARTED", "node": "node0"}])
        return 200, {"nodes": nodes, "shards": shards}
    c.register("GET", "/_search_shards", search_shards)
    c.register("POST", "/_search_shards", search_shards)
    c.register("GET", "/{index}/_search_shards", search_shards)
    c.register("POST", "/{index}/_search_shards", search_shards)

    # -- cache clear (ref indices/cache/clear/TransportClearIndicesCache-
    #    Action): real invalidation against the node cache subsystem.
    #    ?query= / ?request= / ?fielddata= select tiers (aliases the
    #    reference accepted — filter/filter_cache/query_cache/request_cache
    #    — map onto the same three); no flag at all clears everything. ----
    def clear_cache(g, p, b):
        names = node._resolve(g.get("index", "_all"))

        def flag(*keys):
            for k in keys:
                v = p.get(k, [None])[0]
                if v is not None:
                    # bare `?request` (no value) means true, like the ref
                    return str(v).strip().lower() not in ("false", "0", "no")
            return None
        q = flag("query", "query_cache", "filter", "filter_cache")
        r = flag("request", "request_cache")
        f = flag("fielddata", "field_data")
        if q is None and r is None and f is None:
            q = r = f = True
        cleared = node.caches.clear(
            query=bool(q), request=bool(r), fielddata=bool(f),
            indices=None if g.get("index") in (None, "", "_all", "*")
            else names)
        return 200, {"_shards": {
            "total": sum(len(node.indices[n].shards) for n in names),
            "successful": sum(len(node.indices[n].shards) for n in names),
            "failed": 0}, "cleared": cleared}
    for pat in ("/_cache/clear", "/{index}/_cache/clear"):
        c.register("POST", pat, clear_cache)
        c.register("GET", pat, clear_cache)

    # -- recovery status API (ref action/admin/indices/recovery) ----------
    def recovery_api(g, p, b):
        names = node._resolve(g.get("index", "_all"))
        out = {}
        for n in names:
            svc = node.indices[n]
            shards = []
            for sid, e in enumerate(svc.shards):
                nbytes = sum(s.memory_bytes() for s in e.segments)
                ep = {"id": "node0", "name": "tpu-node-0",
                      "host": "localhost", "transport_address":
                      "127.0.0.1:9300", "ip": "127.0.0.1"}
                shards.append({
                    "id": sid, "type": "GATEWAY", "stage": "DONE",
                    "primary": True,
                    "start_time_in_millis": 0, "total_time_in_millis": 0,
                    "source": dict(ep), "target": dict(ep),
                    "index": {
                        "size": {"total_in_bytes": nbytes,
                                 "reused_in_bytes": 0,
                                 "recovered_in_bytes": nbytes,
                                 "percent": "100.0%"},
                        "files": {"total": len(e.segments), "reused": 0,
                                  "recovered": len(e.segments),
                                  "percent": "100.0%"},
                        "total_time_in_millis": 0,
                        "source_throttle_time_in_millis": 0,
                        "target_throttle_time_in_millis": 0},
                    "translog": {"recovered": 0, "total": -1,
                                 "total_on_start": 0, "percent": "-1.0%",
                                 "total_time_in_millis": 0},
                    "start": {"check_index_time_in_millis": 0,
                              "total_time_in_millis": 0},
                })
            out[n] = {"shards": shards}
        return 200, out
    c.register("GET", "/_recovery", recovery_api)
    c.register("GET", "/{index}/_recovery", recovery_api)

    _register_indices_routes(c, node)


def _resolve_lenient_impl(node, expr, p) -> list[str]:
    """IndicesOptions handling at the REST seam: ignore_unavailable skips
    missing concrete names; whitespace in comma lists is trimmed
    (ref action/support/IndicesOptions)."""
    iu = _pbool(p, "ignore_unavailable", False)
    out: list[str] = []
    expr = str(expr or "_all")
    for part in expr.split(","):
        part = part.strip()
        try:
            out.extend(n for n in node._resolve(part) if n not in out)
        except IndexMissingException:
            if not iu:
                raise
        except IndexClosedException:
            if not iu:    # ignore_unavailable also skips closed indices
                raise
    if not out and not _pbool(p, "allow_no_indices", True) \
            and ("*" in expr or expr == "_all"):
        raise IndexMissingException(expr)
    return out


def _expand_indices_impl(node, expr, p) -> tuple[list[str], list[str]]:
    """-> (open_names, closed_names) honoring expand_wildcards
    (open/closed/all/none; ref IndicesOptions.fromRequest)."""
    ew = (p.get("expand_wildcards", ["open"])[0] or "open").split(",")
    if "all" in ew:
        ew = ["open", "closed"]
    expr = str(expr or "_all")
    parts = [x.strip() for x in expr.split(",")]
    if "none" in ew:
        return ([x for x in parts if x in node.indices],
                [x for x in parts if x in node.closed])
    opens = []
    closeds = []
    for part in parts:
        if part in node.closed:
            # expand_wildcards governs WILDCARD expansion only; a closed
            # index named concretely always resolves (IndicesOptions)
            if part not in closeds:
                closeds.append(part)
            continue
        if "open" in ew:
            try:
                opens.extend(n for n in _resolve_lenient_impl(node, part, p)
                             if n not in opens)
            except IndexClosedException:      # closed reached via alias
                pass
        elif part in node.indices:
            opens.append(part)
    if "closed" in ew:
        closeds.extend(
            n for n in node.closed
            if n not in closeds and any(fnmatch.fnmatch(n, x)
                                        or x in ("_all", "*")
                                        for x in parts))
    return opens, closeds


def _flat_settings(svc) -> dict:
    """Flat 'index.'-prefixed settings map with the implicit defaults the
    reference always reports (ref RestGetSettingsAction string rendering)."""
    out = {"index.number_of_shards": str(svc.n_shards),
           "index.number_of_replicas": str(svc.n_replicas),
           "index.version.created": "2000000"}
    for k, v in dict(svc.settings).items():
        key = k if k.startswith("index.") else f"index.{k}"
        out[key] = str(v)
    return out


def _nest_flat(flat: dict) -> dict:
    out: dict = {}
    for k, v in flat.items():
        parts = k.split(".")
        node = out
        for p in parts[:-1]:
            nxt = node.get(p)
            if not isinstance(nxt, dict):
                nxt = {}
                node[p] = nxt
            node = nxt
        node[parts[-1]] = v
    return out


def _render_settings(svc, flat: bool = False) -> dict:
    f = _flat_settings(svc)
    return f if flat else _nest_flat(f)


def _write_shards(node: NodeService, index: str) -> dict:
    try:
        svc = node.indices[node._resolve(index)[0]]
        total = 1 + svc.n_replicas
    except Exception:  # noqa: BLE001
        total = 1
    return {"total": total, "successful": 1, "failed": 0}


def _source_filter_paths(src: dict, includes, excludes) -> dict:
    from ..search.shard_searcher import _filter_source
    if isinstance(includes, str):
        includes = [includes]
    if isinstance(excludes, str):
        excludes = [excludes]
    spec: dict = {}
    if includes:
        spec["includes"] = [p if "*" in p else p + "*" for p in includes] \
            + list(includes)
    if excludes:
        spec["excludes"] = list(excludes)
    return _filter_source(src, spec)


def _register_indices_routes(c: RestController, node: NodeService) -> None:
    """Admin/index APIs beyond the core CRUD set (alias CRUD, templates,
    settings, validate, segments, stats, cluster info) — the breadth the
    rest-api-spec YAML suites exercise (ref rest/action/admin/)."""

    def _resolve_lenient(expr, p):
        return _resolve_lenient_impl(node, expr, p)

    def _expand_indices(expr, p):
        return _expand_indices_impl(node, expr, p)

    # -- GET method variants the specs allow -------------------------------
    def refresh(g, p, b):
        node.refresh(g.get("index", "_all"))
        return 200, {"_shards": {"failed": 0}}
    c.register("GET", "/{index}/_refresh", refresh)
    c.register("GET", "/_refresh", refresh)

    def flush(g, p, b):
        node.flush(g.get("index", "_all"))
        return 200, {"_shards": {"failed": 0}}
    c.register("GET", "/{index}/_flush", flush)
    c.register("GET", "/_flush", flush)

    def optimize(g, p, b):
        node.force_merge(g.get("index", "_all"),
                         int(p.get("max_num_segments", ["1"])[0]))
        return 200, {"_shards": {"failed": 0}}
    c.register("GET", "/{index}/_optimize", optimize)
    c.register("GET", "/_optimize", optimize)

    # -- open / close (ref rest/action/admin/indices/open+close) ----------
    def close_index(g, p, b):
        node.close_index(g["index"])
        return 200, {"acknowledged": True}
    c.register("POST", "/{index}/_close", close_index)

    def open_index(g, p, b):
        node.open_index(g["index"])
        return 200, {"acknowledged": True}
    c.register("POST", "/{index}/_open", open_index)

    # -- aliases (ref cluster/metadata/MetaDataIndicesAliasesService) ------
    def _alias_map(index_expr: str | None, name: str | None):
        """-> {index: [matching aliases]} honoring wildcards in `name`."""
        names = node._resolve(index_expr or "_all")
        out: dict[str, list[str]] = {}
        for n in names:
            aliases = sorted(node.indices[n].aliases)
            if name and name not in ("_all", "*"):
                pats = name.split(",")
                aliases = [a for a in aliases
                           if any(fnmatch.fnmatch(a, pat) for pat in pats)]
            out[n] = aliases
        return out

    def put_alias(g, p, b):
        from ..node import alias_dict
        props = alias_dict({g["name"]: _json_body(b)})[g["name"]]
        for n in node._resolve(g["index"]):
            node.indices[n].aliases[g["name"]] = props
            node._persist_index_meta(node.indices[n])
        return 200, {"acknowledged": True}
    for pat in ("/{index}/_alias/{name}", "/{index}/_aliases/{name}",
                "/_alias/{name}", "/_aliases/{name}"):
        c.register("PUT", pat, put_alias)
        c.register("POST", pat, put_alias)

    def delete_alias(g, p, b):
        removed = False
        for n in node._resolve(g["index"]):
            svc = node.indices[n]
            match = [a for a in svc.aliases
                     if any(fnmatch.fnmatch(a, pat)
                            for pat in g["name"].split(","))] \
                if g["name"] not in ("_all", "*") else list(svc.aliases)
            for a in match:
                svc.aliases.pop(a, None)
                removed = True
            if match:
                node._persist_index_meta(svc)
        if not removed:
            return 404, {"error": f"aliases [{g['name']}] missing",
                         "status": 404}
        return 200, {"acknowledged": True}
    c.register("DELETE", "/{index}/_alias/{name}", delete_alias)
    c.register("DELETE", "/{index}/_aliases/{name}", delete_alias)

    def get_alias(g, p, b):
        amap = _alias_map(g.get("index"), g.get("name"))
        if g.get("name") and not any(amap.values()):
            if g.get("index"):
                # missing alias scoped to an existing index: empty body
                # (ref get_alias REST contract)
                return 200, {}
            return 404, {"error": f"alias [{g['name']}] missing",
                         "status": 404}
        def render_props(n, a):
            props = node.indices[n].aliases.get(a, {})
            return {k: v for k, v in props.items()
                    if k in ("filter", "index_routing", "search_routing")}
        return 200, {n: {"aliases": {a: render_props(n, a) for a in al}}
                     for n, al in amap.items()
                     if al or not g.get("name")}
    for pat in ("/_alias", "/_alias/{name}", "/{index}/_alias",
                "/{index}/_alias/{name}"):
        c.register("GET", pat, get_alias)

    def get_aliases_old(g, p, b):
        # the legacy `_aliases` GET contract: matching indices always
        # appear, each with its (possibly empty) aliases map, HTTP 200 —
        # no 404 for a missing alias (ref RestGetAliasesAction vs
        # RestGetIndicesAliasesAction)
        amap = _alias_map(g.get("index"), g.get("name"))
        def render_props(n, a):
            props = node.indices[n].aliases.get(a, {})
            return {k: v for k, v in props.items()
                    if k in ("filter", "index_routing", "search_routing")}
        return 200, {n: {"aliases": {a: render_props(n, a) for a in al}}
                     for n, al in amap.items()}
    for pat in ("/_aliases", "/_aliases/{name}", "/{index}/_aliases",
                "/{index}/_aliases/{name}"):
        c.register("GET", pat, get_aliases_old)

    def head_alias(g, p, b):
        amap = _alias_map(g.get("index"), g.get("name"))
        return (200 if any(amap.values()) else 404), {}
    c.register("HEAD", "/_alias/{name}", head_alias)
    c.register("HEAD", "/{index}/_alias/{name}", head_alias)

    def update_aliases(g, p, b):
        from ..node import alias_dict
        body = _json_body(b)
        for action in body.get("actions", []):
            (kind, spec), = action.items()
            indices = spec.get("indices") or [spec["index"]]
            aliases = spec.get("aliases") or [spec["alias"]]
            props = alias_dict({"x": {
                k: v for k, v in spec.items()
                if k in ("filter", "routing", "index_routing",
                         "search_routing")}})["x"]
            for expr in indices:
                for n in node._resolve(expr):
                    svc = node.indices[n]
                    for a in aliases:
                        if kind == "add":
                            svc.aliases[a] = props
                        else:
                            svc.aliases.pop(a, None)
                    node._persist_index_meta(svc)
        return 200, {"acknowledged": True}
    c.register("POST", "/_aliases", update_aliases)

    # -- templates ---------------------------------------------------------
    def _tpl_render(tpl: dict, flat: bool) -> dict:
        # settings render in the normalized index.* string form, nested by
        # default / flat with flat_settings (ref MetaDataIndexTemplateService
        # -> RestGetIndexTemplateAction settings serialization)
        out = dict(tpl)
        f = {}
        for k, v in (tpl.get("settings") or {}).items():
            key = k if k.startswith("index.") else f"index.{k}"
            f[key] = str(v)
        out["settings"] = f if flat else _nest_flat(f)
        if tpl.get("aliases"):
            from ..node import alias_dict
            out["aliases"] = alias_dict(tpl["aliases"])
        return out

    def get_template(g, p, b):
        name = g.get("name")
        flat = p.get("flat_settings", ["false"])[0] == "true"
        if name is None:
            return 200, {t: _tpl_render(v, flat)
                         for t, v in node.templates.items()}
        out = {t: _tpl_render(v, flat) for t, v in node.templates.items()
               if any(fnmatch.fnmatch(t, pat) for pat in name.split(","))}
        if not out and "*" not in name:
            return 404, {"error": f"template [{name}] missing",
                         "status": 404}
        return 200, out
    c.register("GET", "/_template", get_template)
    c.register("GET", "/_template/{name}", get_template)

    def delete_template(g, p, b):
        match = [t for t in node.templates
                 if fnmatch.fnmatch(t, g["name"])]
        if not match:
            if "*" in g["name"]:    # wildcard deletes are no-match tolerant
                return 200, {"acknowledged": True}
            return 404, {"error": f"template [{g['name']}] missing",
                         "status": 404}
        for t in match:
            del node.templates[t]
        node._persist_templates()
        return 200, {"acknowledged": True}
    c.register("DELETE", "/_template/{name}", delete_template)

    c.register("HEAD", "/_template/{name}",
               lambda g, p, b: ((200 if any(
                   fnmatch.fnmatch(t, g["name"]) for t in node.templates)
                   else 404), {}))

    # -- indices.get / settings -------------------------------------------
    _GET_FEATURES = {"_settings": "settings", "_mappings": "mappings",
                     "_mapping": "mappings", "_warmers": "warmers",
                     "_warmer": "warmers", "_aliases": "aliases",
                     "_alias": "aliases"}

    def get_index(g, p, b):
        flat = p.get("flat_settings", ["false"])[0] == "true"
        feats = None
        if g.get("feature"):
            feats = []
            for f in g["feature"].split(","):
                if f not in _GET_FEATURES:
                    raise RestError(
                        400, f"no handler for [GET /{g['index']}/{f}]")
                feats.append(_GET_FEATURES[f])
        out = {}
        opens, closeds = _expand_indices(g["index"], p)
        for n in opens:
            svc = node.indices[n]
            sections = {"aliases": {a: svc.aliases[a]
                                    for a in sorted(svc.aliases)},
                        "mappings": svc.mappings_dict(),
                        "settings": _render_settings(svc, flat),
                        "warmers": getattr(svc, "warmers", {})}
            out[n] = sections if feats is None \
                else {k: v for k, v in sections.items() if k in feats}
        for n in closeds:
            if n in out:
                continue
            meta = node.closed[n]
            f = {f"index.{k}" if not k.startswith("index.") else k: str(v)
                 for k, v in (meta.get("settings") or {}).items()}
            f.setdefault("index.number_of_shards", "1")
            f.setdefault("index.number_of_replicas", "0")
            sections = {"aliases": meta.get("aliases") or {},
                        "mappings": meta.get("mappings") or {},
                        "settings": f if flat else _nest_flat(f),
                        "warmers": {}}
            out[n] = sections if feats is None \
                else {k: v for k, v in sections.items() if k in feats}
        return 200, out
    c.register("GET", "/{index}", get_index)
    c.register("GET", "/{index}/{feature}", get_index)

    def get_settings(g, p, b):
        flat = p.get("flat_settings", ["false"])[0] == "true"
        sel = g.get("setting") or p.get("name", [None])[0]
        if sel in ("_all", "*"):
            sel = None
        out = {}
        opens, closeds = _expand_indices(g.get("index", "_all"), p)
        flats = [(n, _flat_settings(node.indices[n])) for n in opens]
        for n in closeds:
            if any(n == m for m, _ in flats):
                continue
            meta = node.closed[n]
            f = {k if k.startswith("index.") else f"index.{k}": str(v)
                 for k, v in (meta.get("settings") or {}).items()}
            f.setdefault("index.number_of_shards", "1")
            f.setdefault("index.number_of_replicas", "0")
            flats.append((n, f))
        for n, f in flats:
            if sel:
                pats = sel.split(",")
                f = {k: v for k, v in f.items()
                     if any(fnmatch.fnmatch(k, pat)
                            or fnmatch.fnmatch(k[6:], pat)
                            for pat in pats)}
            out[n] = {"settings": f if flat else _nest_flat(f)}
        return 200, out
    c.register("GET", "/_settings", get_settings)
    c.register("GET", "/_settings/{setting}", get_settings)
    c.register("GET", "/{index}/_settings", get_settings)
    c.register("GET", "/{index}/_settings/{setting}", get_settings)

    # runtime-updatable index settings (ref cluster/settings/
    # DynamicSettings.java:30 + IndexDynamicSettings): everything else is
    # STATIC and rejected on an open index, like the reference
    _DYNAMIC_INDEX_SETTINGS = (
        "number_of_replicas", "refresh_interval", "max_result_window",
        "translog.", "slowlog.", "search.slowlog.", "indexing.slowlog.",
        "blocks.", "routing.", "merge.", "gc_deletes", "warmer.",
        "mapping.", "auto_expand_replicas", "mapper.",
    )

    def _is_dynamic_setting(key: str) -> bool:
        k = key[6:] if key.startswith("index.") else key
        return any(k == d or (d.endswith(".") and k.startswith(d))
                   for d in _DYNAMIC_INDEX_SETTINGS)

    def _flatten_settings(obj, prefix="") -> dict:
        out = {}
        for k, v in obj.items():
            if isinstance(v, dict):
                out.update(_flatten_settings(v, f"{prefix}{k}."))
            else:
                out[f"{prefix}{k}"] = v
        return out

    def put_settings(g, p, b):
        body = _json_body(b)
        flat = body.get("settings", body)
        flat = flat.get("index", flat) if isinstance(
            flat.get("index", None), dict) else flat
        flat = _flatten_settings(flat)   # nested {"translog": {...}} form
        for k in flat:
            if not _is_dynamic_setting(k):
                raise RestError(
                    400, f"IllegalArgumentException: can't update non "
                         f"dynamic settings [[{k}]] for open indices")
        for n in _resolve_lenient(g.get("index", "_all"), p):
            svc = node.indices[n]
            data = dict(svc.settings)
            for k, v in flat.items():
                data[k] = v
            from ..common.settings import Settings
            svc.settings = Settings(data)
            nr = svc.settings.get("number_of_replicas",
                                  svc.settings.get(
                                      "index.number_of_replicas"))
            if nr is not None:
                svc.n_replicas = int(nr)
            dur = svc.settings.get("index.translog.durability",
                                   svc.settings.get("translog.durability"))
            if dur is not None:
                for e in svc.shards:     # applied LIVE to running engines
                    e.translog.durability = str(dur).lower()
            node._persist_index_meta(svc)
        return 200, {"acknowledged": True}
    c.register("PUT", "/_settings", put_settings)
    c.register("PUT", "/{index}/_settings", put_settings)

    # -- validate / explain / delete-by-query ------------------------------
    def _lucene_str(q) -> str:
        """Rough Lucene toString rendering of a parsed query (enough for
        the validate_query explain contract; ref Query.toString())."""
        (kind, spec), = q.items() if isinstance(q, dict) and q else \
            (("match_all", {}),)
        if kind == "match_all":
            return "ConstantScore(*:*)"
        if kind in ("term", "match"):
            (f, v), = spec.items()
            if isinstance(v, dict):
                v = v.get("value", v.get("query"))
            return f"{f}:{v}"
        if kind == "query_string":
            return str(spec.get("query", ""))
        return json.dumps(q, separators=(",", ":"))

    def validate_query(g, p, b):
        body = _json_body(b)
        query = body.get("query", {"match_all": {}})
        names = node._resolve(g.get("index", "_all"))
        valid = True
        err = None
        try:
            from ..search.query_parser import QueryParser
            mappers = node.indices[names[0]].mappers if names else None
            QueryParser(mappers).parse(query)
        except Exception as e:  # noqa: BLE001 — that's the point
            valid = False
            err = str(e)
        out = {"valid": valid,
               "_shards": {"total": 1, "successful": 1, "failed": 0}}
        if p.get("explain", ["false"])[0] == "true":
            expl = {"index": names[0] if names else "_all", "valid": valid}
            if err:
                expl["error"] = err
            else:
                expl["explanation"] = _lucene_str(query)
            out["explanations"] = [expl]
        return 200, out
    for pat in ("/_validate/query", "/{index}/_validate/query",
                "/{index}/{type}/_validate/query"):
        c.register("GET", pat, validate_query)
        c.register("POST", pat, validate_query)

    def explain_doc(g, p, b):
        body = _json_body(b)
        query = body.get("query", {"match_all": {}})
        concrete = node._resolve(g["index"])[0]   # alias -> concrete name
        out = node.search(g["index"], {
            "query": {"bool": {"must": [query],
                               "filter": [{"ids": {"values": [g["id"]]}}]}},
            "size": 1, "track_scores": True})
        hits = out["hits"]["hits"]
        matched = bool(hits)
        resp = {"_index": concrete, "_type": g.get("type", "_doc"),
                "_id": g["id"], "matched": matched}
        if matched:
            score = hits[0]["_score"] or 0.0
            resp["explanation"] = {"value": score,
                                   "description": "sum of:", "details": []}
        # URL _source params attach the fetched doc as a `get` section
        # (ref RestExplainAction fetchSource handling)
        s = p.get("_source", [None])[0]
        inc = p.get("_source_include", p.get("_source_includes", [None]))[0]
        exc = p.get("_source_exclude", p.get("_source_excludes", [None]))[0]
        if s is not None or inc or exc:
            got = node.get_doc(concrete, str(g["id"]))
            if got.found:
                gsec: dict = {"found": True}
                if s != "false":
                    src = got.source
                    if s not in (None, "true"):
                        src = _source_filter_paths(src, s.split(","), None)
                    if inc or exc:
                        src = _source_filter_paths(
                            src, inc.split(",") if inc else None,
                            exc.split(",") if exc else None)
                    gsec["_source"] = src
                resp["get"] = gsec
        return 200, resp
    c.register("GET", "/{index}/{type}/{id}/_explain", explain_doc)
    c.register("POST", "/{index}/{type}/{id}/_explain", explain_doc)

    def delete_by_query(g, p, b):
        body = _json_body(b)
        if not body and "q" not in p:
            raise RestError(400, "delete_by_query requires a query")
        deleted = node.delete_by_query(g["index"], body)
        return 200, {"_indices": {g["index"]: {"_shards": {
            "total": 1, "successful": 1, "failed": 0}}},
            "deleted": deleted}
    c.register("DELETE", "/{index}/_query", delete_by_query)
    c.register("DELETE", "/{index}/{type}/_query", delete_by_query)

    # -- segments / cluster info ------------------------------------------
    def segments_api(g, p, b):
        out = {}
        names = _resolve_lenient(g.get("index", "_all"), p)
        total = sum(node.indices[n].n_shards for n in names)
        for n in names:
            svc = node.indices[n]
            shards = {}
            for si, e in enumerate(svc.shards):
                shards[str(si)] = [{
                    "routing": {"state": "STARTED", "primary": True},
                    "num_committed_segments": len(e.segments),
                    "num_search_segments": len(e.segments),
                    "segments": {
                        # Lucene generation names start at _0; seg ids at 1
                        f"_{seg.seg_id - 1}": {
                            "generation": seg.seg_id,
                            "num_docs": seg.live_count,
                            "deleted_docs": seg.n_docs - seg.live_count,
                            "memory_in_bytes": seg.memory_bytes(),
                            "search": True, "committed": True,
                        } for seg in e.segments}}]
            out[n] = {"shards": shards}
        return 200, {"_shards": {"total": total, "successful": total,
                                 "failed": 0}, "indices": out}
    c.register("GET", "/_segments", segments_api)
    c.register("GET", "/{index}/_segments", segments_api)

    c.register("GET", "/_cluster/pending_tasks",
               lambda g, p, b: (200, {"tasks": []}))
    def get_cluster_settings(g, p, b):
        cs = getattr(node, "_cluster_settings",
                     {"persistent": {}, "transient": {}})
        return 200, {"persistent": dict(cs["persistent"]),
                     "transient": dict(cs["transient"])}

    def put_cluster_settings(g, p, b):
        # per-component logger levels apply LIVE (ref
        # common/logging + RestClusterUpdateSettingsAction: the
        # `logger.<component>: <level>` dynamic settings)
        import logging as _logging
        body = _json_body(b)
        cs = getattr(node, "_cluster_settings", None)
        if cs is None:
            cs = node._cluster_settings = {"persistent": {},
                                           "transient": {}}
        def logger_for(k: str):
            name = k[len("logger."):]
            return _logging.getLogger(
                "elasticsearch_tpu" if name in ("_root", "")
                else f"elasticsearch_tpu.{name}")
        for scope in ("persistent", "transient"):
            for k, v in _flatten_settings(body.get(scope) or {}).items():
                if v is None:
                    cs[scope].pop(k, None)
                    if k.startswith("logger."):
                        # null RESTORES the default (inherit from parent)
                        logger_for(k).setLevel(_logging.NOTSET)
                    continue
                cs[scope][k] = v
                if k.startswith("logger."):
                    name = str(v).upper()
                    # ES supports TRACE below DEBUG; register it once
                    if name == "TRACE":
                        _logging.addLevelName(5, "TRACE")
                        lvl = 5
                    else:
                        lvl = getattr(_logging, name, None)
                    if isinstance(lvl, int):
                        logger_for(k).setLevel(lvl)
                    else:
                        raise RestError(
                            400, f"IllegalArgumentException: unknown "
                                 f"logger level [{v}] for [{k}]")
        return 200, {"acknowledged": True,
                     "persistent": dict(cs["persistent"]),
                     "transient": dict(cs["transient"])}
    c.register("GET", "/_cluster/settings", get_cluster_settings)
    c.register("PUT", "/_cluster/settings", put_cluster_settings)

    _BLOCK_IDS = {"read_only": ("5", "index read-only (api)"),
                  "read": ("7", "index read (api)"),
                  "write": ("8", "index write (api)"),
                  "metadata": ("9", "index metadata (api)")}

    def cluster_state(g, p, b):
        metrics = set((g.get("metric") or "_all").split(","))
        idx_expr = g.get("index")
        if idx_expr:
            opens, closeds = _expand_indices(idx_expr, p)
        else:
            opens, closeds = list(node.indices), list(node.closed)
        out: dict = {"cluster_name": node.cluster_name,
                     "master_node": "tpu-node-0"}
        if metrics & {"_all", "metadata"}:
            meta = {"indices": {}, "templates": dict(node.templates)}
            for n in opens:
                svc = node.indices[n]
                meta["indices"][n] = {
                    "state": "open",
                    "aliases": sorted(svc.aliases),
                    "mappings": svc.mappings_dict(),
                    "settings": _render_settings(svc)}
            for n in closeds:
                cm = node.closed[n]
                meta["indices"][n] = {
                    "state": "close",
                    "aliases": sorted(cm.get("aliases") or {}),
                    "mappings": cm.get("mappings") or {},
                    "settings": _nest_flat(
                        {k if k.startswith("index.") else f"index.{k}":
                         str(v)
                         for k, v in (cm.get("settings") or {}).items()})}
            out["metadata"] = meta
        if metrics & {"_all", "nodes"}:
            out["nodes"] = {"tpu-node-0": {"name": "tpu-node-0"}}
        if metrics & {"_all", "routing_table"}:
            out["routing_table"] = {"indices": {
                n: {"shards": {}} for n in opens}}
        if metrics & {"_all", "routing_nodes", "routing_table"}:
            out["routing_nodes"] = {"unassigned": [], "nodes": {
                "tpu-node-0": []}}
        if metrics & {"_all", "blocks"}:
            blocks: dict = {}
            bi: dict = {}
            for n in opens:
                ib = {}
                for key, (bid, desc) in _BLOCK_IDS.items():
                    v = node.indices[n].settings.get(f"index.blocks.{key}")
                    if str(v).lower() == "true":
                        ib[bid] = {"description": desc, "retryable": False,
                                   "levels": ["write", "metadata_write"]}
                if ib:
                    bi[n] = ib
            for n in closeds:
                bi[n] = {"4": {"description": "index closed",
                               "retryable": False,
                               "levels": ["read", "write"]}}
            if bi:
                blocks["indices"] = bi
            out["blocks"] = blocks
        return 200, out
    c.register("GET", "/_cluster/state", cluster_state)
    c.register("GET", "/_cluster/state/{metric}", cluster_state)
    c.register("GET", "/_cluster/state/{metric}/{index}", cluster_state)

    def cluster_reroute(g, p, b):
        # ref cluster/routing/allocation/command/* + RestClusterRerouteAction
        # (single-node build: commands are explained, never applied; the
        # real relocation machinery lives in cluster/state.py rebalance)
        body = _json_body(b) if b else {}
        explanations = []
        for cmd in (body.get("commands") or []):
            (kind, params), = cmd.items()
            params = {"allow_primary": False, **(params or {})}
            explanations.append({
                "command": kind,
                "parameters": params,
                "decisions": [{
                    "decider": f"{kind}_allocation_command",
                    "decision": "NO",
                    "explanation": f"[{kind}] cannot apply: no matching "
                                   f"started shard copy on this node"}]})
        metric = set((p.get("metric", [""])[0] or "").split(",")) - {""}
        state: dict = {"version": 1, "master_node": "tpu-node-0"}
        # metadata is EXCLUDED from the default reroute response
        # (ref RestClusterRerouteAction.DEFAULT_METRICS)
        if not metric or "nodes" in metric or "_all" in metric:
            if not metric or "_all" in metric:
                state["nodes"] = {"tpu-node-0": {"name": "tpu-node-0"}}
            elif "nodes" in metric:
                state["nodes"] = {"tpu-node-0": {"name": "tpu-node-0"}}
        if "metadata" in metric or "_all" in metric:
            state["metadata"] = {"indices": {
                n: {"state": "open"} for n in node.indices}}
        if not metric or "routing_table" in metric or "_all" in metric:
            state["routing_table"] = {"indices": {
                n: {"shards": {}} for n in node.indices}}
        out = {"acknowledged": True, "state": state}
        if _pbool(p, "explain", False):
            out["explanations"] = explanations
        return 200, out
    c.register("POST", "/_cluster/reroute", cluster_reroute)

    # -- _cat (RestTable contract: v/h/help, aligned columns) --------------
    from . import cat as _cat

    def cat_count(g, p, b):
        names = node._resolve(g.get("index", "_all"))
        total = sum(node.indices[n].doc_count() for n in names)
        return 200, _cat.render(p, [
            ("epoch", "seconds since 1970-01-01 00:00:00"),
            ("timestamp", "time in HH:MM:SS"),
            ("count", "the document count")],
            [{**_cat.now_cols(), "count": total}])
    c.register("GET", "/_cat/count", cat_count)
    c.register("GET", "/_cat/count/{index}", cat_count)

    def cat_health(g, p, b):
        h = node.cluster_health()
        return 200, _cat.render(p, [
            ("epoch", "seconds since 1970-01-01 00:00:00"),
            ("timestamp", "time in HH:MM:SS"),
            ("cluster", "cluster name"), ("status", "health status"),
            ("node.total", "total number of nodes"),
            ("node.data", "number of nodes that can store data"),
            ("shards", "total number of shards"),
            ("pri", "number of primary shards"),
            ("relo", "number of relocating nodes"),
            ("init", "number of initializing nodes"),
            ("unassign", "number of unassigned shards"),
            ("pending_tasks", "number of pending tasks")],
            [{**_cat.now_cols(), "cluster": h["cluster_name"],
              "status": h["status"], "node.total": h["number_of_nodes"],
              "node.data": h["number_of_data_nodes"],
              "shards": h["active_shards"],
              "pri": h["active_primary_shards"],
              "relo": h["relocating_shards"],
              "init": h["initializing_shards"],
              "unassign": h["unassigned_shards"],
              "pending_tasks": h["number_of_pending_tasks"]}])
    c.register("GET", "/_cat/health", cat_health)

    def cat_indices(g, p, b):
        rows = []
        for n in sorted(node._resolve(g.get("index", "_all"))):
            svc = node.indices[n]
            size = sum(e.segment_stats()["memory_in_bytes"]
                       for e in svc.shards)
            deleted = sum(e.segment_stats()["deleted"] for e in svc.shards)
            rc = node.caches.request_cache.index_stats(n)
            rc_ops = svc.request_cache_hits + svc.request_cache_misses
            rows.append({
                "health": "green" if svc.n_replicas == 0 else "yellow",
                "status": "open", "index": n, "pri": svc.n_shards,
                "rep": svc.n_replicas, "docs.count": svc.doc_count(),
                "docs.deleted": deleted,
                "store.size": _cat.human_bytes(size),
                "pri.store.size": _cat.human_bytes(size),
                "search.rate": f"{svc.meters['search'].rate(60):.2f}",
                "indexing.rate":
                    f"{svc.meters['indexing'].rate(60):.2f}",
                "request_cache.memory": _cat.human_bytes(rc["bytes"]),
                "request_cache.hit_ratio":
                    f"{svc.request_cache_hits / rc_ops:.2f}"
                    if rc_ops else ""})
        for n in sorted(node.closed):
            rows.append({"health": "green", "status": "close", "index": n,
                         "pri": "", "rep": "", "docs.count": "",
                         "docs.deleted": "", "store.size": "",
                         "pri.store.size": "", "search.rate": "",
                         "indexing.rate": "", "request_cache.memory": "",
                         "request_cache.hit_ratio": ""})
        return 200, _cat.render(p, [
            ("health", "current health status"), ("status", "open/close"),
            ("index", "index name"), ("pri", "number of primary shards"),
            ("rep", "number of replica shards"),
            ("docs.count", "available docs"),
            ("docs.deleted", "deleted docs"),
            ("store.size", "store size of primaries & replicas"),
            ("pri.store.size", "store size of primaries"),
            ("search.rate", "1m EWMA searches per second"),
            ("indexing.rate", "1m EWMA indexing ops per second"),
            ("request_cache.memory", "request cache bytes for this index"),
            ("request_cache.hit_ratio",
             "request cache hits / lookups")], rows,
            aliases={"sr": "search.rate", "ir": "indexing.rate",
                     "rcm": "request_cache.memory",
                     "rchr": "request_cache.hit_ratio"})
    c.register("GET", "/_cat/indices", cat_indices)
    c.register("GET", "/_cat/indices/{index}", cat_indices)

    def cat_aliases(g, p, b):
        rows = []
        for n, svc in sorted(node.indices.items()):
            for a in sorted(svc.aliases):
                if g.get("name") and not any(
                        fnmatch.fnmatch(a, pat)
                        for pat in g["name"].split(",")):
                    continue
                props = svc.aliases[a]
                rows.append({"alias": a, "index": n,
                             "filter": "*" if props.get("filter") else "-",
                             "routing.index":
                                 props.get("index_routing", "-") or "-",
                             "routing.search":
                                 props.get("search_routing", "-") or "-"})
        return 200, _cat.render(p, [
            ("alias", "alias name"), ("index", "index the alias points to"),
            ("filter", "filter"), ("routing.index", "index routing"),
            ("routing.search", "search routing")], rows)
    c.register("GET", "/_cat/aliases", cat_aliases)
    c.register("GET", "/_cat/aliases/{name}", cat_aliases)

    def cat_shards(g, p, b):
        rows = []
        for n in sorted(node._resolve(g.get("index", "_all"))):
            svc = node.indices[n]
            for si, e in enumerate(svc.shards):
                size = e.segment_stats()["memory_in_bytes"]
                rows.append({"index": n, "shard": si, "prirep": "p",
                             "state": "STARTED", "docs": e.doc_count(),
                             "store": _cat.human_bytes(size),
                             "ip": "127.0.0.1", "node": "tpu-node-0"})
                shadow = str(svc.settings.get(
                    "shadow_replicas",
                    svc.settings.get("index.shadow_replicas",
                                     False))).lower() == "true"
                for _ in range(svc.n_replicas):
                    rows.append({"index": n, "shard": si,
                                 "prirep": "s" if shadow else "r",
                                 "state": "UNASSIGNED", "docs": "",
                                 "store": "", "ip": "", "node": ""})
        return 200, _cat.render(p, [
            ("index", "index name"), ("shard", "shard id"),
            ("prirep", "primary or replica"), ("state", "shard state"),
            ("docs", "number of docs"), ("store", "store size"),
            ("ip", "node ip"), ("node", "node name")], rows)
    c.register("GET", "/_cat/shards", cat_shards)
    c.register("GET", "/_cat/shards/{index}", cat_shards)

    # every pool name the reference's table shows (ThreadPool.Names); pools
    # this build doesn't run report zeros with their reference pool type
    _TP_ALL = ["bulk", "flush", "generic", "get", "index", "management",
               "optimize", "percolate", "refresh", "search", "snapshot",
               "suggest", "warmer"]
    _TP_TYPE = {"bulk": "fixed", "index": "fixed", "search": "fixed",
                "get": "fixed", "percolate": "fixed", "suggest": "fixed",
                "generic": "cached", "management": "scaling",
                "flush": "scaling", "optimize": "scaling",
                "refresh": "scaling", "snapshot": "scaling",
                "warmer": "scaling"}
    # short-form column aliases (ref RestThreadPoolAction's per-pool alias
    # scheme): <pool prefix> + a/q/r/s/l/c/t for active/queue/rejected/
    # size/largest/completed/type, e.g. h=sq,sr,sl selects the search
    # pool's live queue depth, rejections and high-water queue mark
    _TP_PFX = {"bulk": "b", "flush": "f", "generic": "ge", "get": "g",
               "index": "i", "management": "ma", "optimize": "o",
               "percolate": "p", "refresh": "r", "search": "s",
               "snapshot": "sn", "suggest": "su", "warmer": "w"}
    _TP_ALIAS = {"h": "host", "i": "ip", "po": "port", "p": "pid"}
    for _pool, _pfx in _TP_PFX.items():
        for _short, _col in (("a", "active"), ("q", "queue"),
                             ("r", "rejected"), ("s", "size"),
                             ("l", "largest"), ("c", "completed"),
                             ("t", "type"), ("qs", "queueSize")):
            _TP_ALIAS[f"{_pfx}{_short}"] = f"{_pool}.{_col}"

    def cat_thread_pool(g, p, b):
        # ref rest/action/cat/RestThreadPoolAction.java:108-150 — one row
        # per node; default columns host/ip + bulk/index/search gauges
        st = node.thread_pool.stats()
        full = p.get("full_id", ["false"])[0] == "true"
        row = {"id": "tpu-node-0" if full else "tpu0",
               "pid": os.getpid(), "host": "localhost",
               "ip": "127.0.0.1", "port": 9300}
        cols = [("id", "unique node id"), ("pid", "process id"),
                ("host", "host name"), ("ip", "ip address"),
                ("port", "bound transport port")]
        for name in _TP_ALL:
            s = st.get(name)
            typ = _TP_TYPE[name]
            row[f"{name}.type"] = typ
            row[f"{name}.active"] = s["active"] if s else 0
            row[f"{name}.size"] = s["threads"] if s else 0
            row[f"{name}.queue"] = s["queue"] if s else 0
            row[f"{name}.queueSize"] = (s["queue_size"] if s
                                        and s["queue_size"] > 0 else "")
            row[f"{name}.rejected"] = s["rejected"] if s else 0
            row[f"{name}.largest"] = s["largest"] if s else 0
            row[f"{name}.completed"] = s["completed"] if s else 0
            row[f"{name}.min"] = s["threads"] if s and typ == "fixed" else ""
            row[f"{name}.max"] = s["threads"] if s and typ == "fixed" else ""
            row[f"{name}.keepAlive"] = "" if typ == "fixed" else "5m"
            for col in ("type", "active", "size", "queue", "queueSize",
                        "rejected", "largest", "completed", "min", "max",
                        "keepAlive"):
                cols.append((f"{name}.{col}", f"{name} pool {col}"))
        defaults = ["host", "ip"] + [f"{n}.{c}"
                                     for n in ("bulk", "index", "search")
                                     for c in ("active", "queue", "rejected")]
        return 200, _cat.render(p, cols, [row], defaults=defaults,
                                aliases=_TP_ALIAS)
    c.register("GET", "/_cat/thread_pool", cat_thread_pool)

    def cat_plugins(g, p, b):
        # ref rest/action/cat/RestPluginsAction
        infos = node.plugins.infos() if getattr(node, "plugins", None) \
            else []
        rows = [{"name": "tpu-node-0", "component": i["name"],
                 "version": i["version"], "type": "j",
                 "description": i["description"]} for i in infos]
        return 200, _cat.render(p, [
            ("name", "node name"), ("component", "plugin name"),
            ("version", "plugin version"), ("type", "plugin type"),
            ("description", "plugin description")], rows)
    c.register("GET", "/_cat/plugins", cat_plugins)

    def cat_segments(g, p, b):
        rows = []
        for n in sorted(node._resolve(g.get("index", "_all"))):
            svc = node.indices[n]
            for si, e in enumerate(svc.shards):
                for seg in e.segments:
                    rows.append({
                        "index": n, "shard": si, "prirep": "p",
                        "ip": "127.0.0.1", "segment": f"_{seg.seg_id}",
                        "generation": seg.seg_id,
                        "docs.count": seg.live_count,
                        "docs.deleted": seg.n_docs - seg.live_count,
                        "size": _cat.human_bytes(seg.memory_bytes()),
                        "size.memory": seg.memory_bytes(),
                        "committed": str(
                            seg.seg_id in e.store.persisted).lower(),
                        "searchable": "true", "version": "2.0.0",
                        "compound": "false"})
        return 200, _cat.render(p, [
            ("index", "index name"), ("shard", "shard id"),
            ("prirep", "primary or replica"), ("ip", "node ip"),
            ("segment", "segment name"), ("generation", "generation"),
            ("docs.count", "number of docs in segment"),
            ("docs.deleted", "number of deleted docs in segment"),
            ("size", "segment size in bytes"),
            ("size.memory", "segment memory in bytes"),
            ("committed", "is segment committed"),
            ("searchable", "is segment searched"),
            ("version", "version"), ("compound", "is segment compound")],
            rows)
    c.register("GET", "/_cat/segments", cat_segments)
    c.register("GET", "/_cat/segments/{index}", cat_segments)

    def cat_nodes(g, p, b):
        import resource
        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        heap = rss_kb * 1024
        row = {"host": "localhost", "ip": "127.0.0.1",
               "heap.percent": 42, "ram.percent": 50, "load": "1.00",
               "node.role": "d", "master": "*", "name": "tpu-node-0",
               "heap.current": _cat.human_bytes(heap),
               "heap.max": _cat.human_bytes(4 << 30),
               "file_desc.current": 256, "file_desc.percent": 1,
               "file_desc.max": 65536}
        return 200, _cat.render(p, [
            ("host", "host name"), ("ip", "ip address"),
            ("heap.percent", "used heap ratio"),
            ("ram.percent", "used machine memory ratio"),
            ("load", "most recent load avg"),
            ("node.role", "d:data node, c:client node"),
            ("master", "*:current master, m:master eligible"),
            ("name", "node name"),
            ("heap.current", "used heap"), ("heap.max", "max heap"),
            ("file_desc.current", "used file descriptors"),
            ("file_desc.percent", "used file descriptor ratio"),
            ("file_desc.max", "max file descriptors")],
            [row],
            defaults=["host", "ip", "heap.percent", "ram.percent", "load",
                      "node.role", "master", "name"])
    c.register("GET", "/_cat/nodes", cat_nodes)

    def cat_tasks(g, p, b):
        infos = node.tasks.task_infos(
            actions=p.get("actions", [None])[0], detailed=True)
        rows = [{"action": i["action"], "task_id": tid,
                 "parent_task_id": i.get("parent_task_id", "-"),
                 "type": i["type"], "start_time": i["start_time_in_millis"],
                 "running_time": f"{i['running_time_in_nanos'] // 1000}micros",
                 "node": i["node"],
                 "description": i.get("description", "")}
                for tid, i in sorted(infos.items())]
        return 200, _cat.render(p, [
            ("action", "task action"), ("task_id", "task id"),
            ("parent_task_id", "parent task id"), ("type", "task type"),
            ("start_time", "start time in millis"),
            ("running_time", "running time"), ("node", "node name"),
            ("description", "task description")], rows,
            defaults=["action", "task_id", "parent_task_id", "type",
                      "start_time", "running_time", "node"])
    c.register("GET", "/_cat/tasks", cat_tasks)

    def cat_master(g, p, b):
        return 200, _cat.render(p, [
            ("id", "node id"), ("host", "host name"),
            ("ip", "ip address"), ("node", "node name")],
            [{"id": "tpu0", "host": "localhost", "ip": "127.0.0.1",
              "node": "tpu-node-0"}])
    c.register("GET", "/_cat/master", cat_master)

    def cat_pending_tasks(g, p, b):
        return 200, _cat.render(p, [
            ("insertOrder", "task insertion order"),
            ("timeInQueue", "how long task has been in queue"),
            ("priority", "task priority"),
            ("source", "task source")], [])
    c.register("GET", "/_cat/pending_tasks", cat_pending_tasks)

    def cat_allocation(g, p, b):
        nid = g.get("node_id")
        if nid and nid not in ("tpu-node-0", "tpu0", "_master", "*",
                               "_all", "_local"):
            return 200, _cat.render(p, [("shards", "")], [])
        total = sum(e.segment_stats()["memory_in_bytes"]
                    for svc in node.indices.values() for e in svc.shards)
        n_shards = sum(svc.n_shards for svc in node.indices.values())
        unit = p.get("bytes", [None])[0]
        scale = {"b": 1, "k": 1 << 10, "m": 1 << 20,
                 "g": 1 << 30, "t": 1 << 40}.get(unit)
        size = (lambda n: int(n // scale)) if scale             else _cat.human_bytes
        return 200, _cat.render(p, [
            ("shards", "number of shards on node"),
            ("disk.used", "disk used (total, not just ES)"),
            ("disk.avail", "disk available"),
            ("disk.total", "total capacity"),
            ("disk.percent", "percent disk used"),
            ("host", "host name"), ("ip", "ip address"),
            ("node", "node name")],
            [{"shards": n_shards, "disk.used": size(total),
              "disk.avail": size(100 << 30),
              "disk.total": size(100 << 30),
              "disk.percent": 1, "host": "localhost", "ip": "127.0.0.1",
              "node": "tpu-node-0"}])
    c.register("GET", "/_cat/allocation", cat_allocation)
    c.register("GET", "/_cat/allocation/{node_id}", cat_allocation)

    def cat_fielddata(g, p, b):
        # loaded per-field fielddata bytes across every segment (ref
        # rest/action/cat/RestFielddataAction.java — one column per field)
        per_field: dict[str, int] = {}
        for svc in node.indices.values():
            for e in svc.shards:
                for seg in e.segments:
                    for f, nb in seg.fielddata_bytes().items():
                        per_field[f] = per_field.get(f, 0) + nb
        fsel = g.get("fields") or ",".join(p.get("fields", []))
        if fsel:
            want = fsel.split(",")
            per_field = {f: nb for f, nb in per_field.items() if f in want}
        cols = [("id", "node id"), ("host", "host name"),
                ("ip", "ip address"), ("node", "node name"),
                ("total", "total field data usage")]
        row = {"id": "tpu0", "host": "localhost", "ip": "127.0.0.1",
               "node": "tpu-node-0",
               "total": _cat.human_bytes(sum(per_field.values()))}
        if p.get("help", ["false"])[0] in ("false", None):
            for f in sorted(per_field):
                cols.append((f, f"field data usage of [{f}]"))
                row[f] = _cat.human_bytes(per_field[f])
        return 200, _cat.render(p, cols, [row])
    c.register("GET", "/_cat/fielddata", cat_fielddata)
    c.register("GET", "/_cat/fielddata/{fields}", cat_fielddata)

    def cat_recovery(g, p, b):
        rows = []
        for n in sorted(node._resolve(g.get("index", "_all"))):
            svc = node.indices[n]
            for si in range(svc.n_shards):
                rows.append({"index": n, "shard": si, "time": 0,
                             "type": "gateway", "stage": "done",
                             "source_host": "localhost",
                             "target_host": "localhost",
                             "repository": "n/a", "snapshot": "n/a",
                             "files": 0, "files_percent": "100.0%",
                             "bytes": 0, "bytes_percent": "100.0%",
                             "total_files": 0, "total_bytes": 0,
                             "translog": 0, "translog_percent": "100.0%",
                             "total_translog": 0})
        return 200, _cat.render(p, [
            ("index", "index name"), ("shard", "shard id"),
            ("time", "recovery time"), ("type", "recovery type"),
            ("stage", "recovery stage"),
            ("source_host", "source host"), ("target_host", "target host"),
            ("repository", "repository"), ("snapshot", "snapshot"),
            ("files", "number of files"),
            ("files_percent", "percent of files recovered"),
            ("bytes", "number of bytes"),
            ("bytes_percent", "percent of bytes recovered"),
            ("total_files", "total number of files"),
            ("total_bytes", "total number of bytes"),
            ("translog", "translog operations recovered"),
            ("translog_percent", "percent of translog recovered"),
            ("total_translog", "total translog operations")], rows)
    c.register("GET", "/_cat/recovery", cat_recovery)
    c.register("GET", "/_cat/recovery/{index}", cat_recovery)


    # -- indices.stats (reference response shape: CommonStats sections,
    #    metric/level/fields/groups/types filtering; ref
    #    action/admin/indices/stats/CommonStats.java + RestIndicesStatsAction)
    _STATS_METRICS = {
        "docs", "store", "indexing", "get", "search", "merge", "refresh",
        "flush", "warmer", "filter_cache", "id_cache", "fielddata",
        "percolate", "completion", "segments", "translog", "suggest",
        "recovery", "query_cache", "request_cache",
    }

    def _csv_param(p, name):
        v = p.get(name)
        if not v:
            return None
        return [x.strip(" '\"[]") for x in ",".join(v).split(",")
                if x.strip(" '\"[]")]

    def index_stats_v2(g, p, b):
        names = node._resolve(g.get("index", "_all"))
        metric = g.get("metric") or ",".join(p.get("metric", [])) or "_all"
        want = set(x.strip() for x in metric.split(","))
        if "_all" in want:
            want = set(_STATS_METRICS)
        level = p.get("level", ["indices"])[0]
        fields_sel = _csv_param(p, "fields")
        fd_sel = fields_sel or _csv_param(p, "fielddata_fields")
        comp_sel = fields_sel or _csv_param(p, "completion_fields")
        groups_sel = _csv_param(p, "groups")
        types_sel = _csv_param(p, "types")

        def shard_stats(svc):
            seg = [e.segment_stats() for e in svc.shards]
            fd_fields: dict[str, int] = {}
            comp_fields: dict[str, int] = {}
            for e in svc.shards:
                for s in e.segments:
                    for f, nb in s.fielddata_bytes().items():
                        fd_fields[f] = fd_fields.get(f, 0) + nb
                    for f, kc in s.keywords.items():
                        ft_types = [dm.fields.get(f)
                                    for dm in svc.mappers._mappers.values()]
                        if any(ft is not None and ft.type == "completion"
                               for ft in ft_types):
                            comp_fields[f] = comp_fields.get(f, 0) \
                                + int(kc.ords.size) * 4 \
                                + sum(len(v) for v in kc.values)
            out = {}
            if "docs" in want:
                out["docs"] = {"count": svc.doc_count(),
                               "deleted": sum(s["deleted"] for s in seg)}
            if "store" in want:
                out["store"] = {"size_in_bytes": sum(
                    s["memory_in_bytes"] for s in seg),
                    "throttle_time_in_millis": 0}
            if "indexing" in want:
                ix = {"index_total": svc.indexing_stats["index_total"],
                      "index_time_in_millis": 0, "index_current": 0,
                      "index_rate_1m": svc.meters["indexing"].rate(60),
                      "index_rate_5m": svc.meters["indexing"].rate(300),
                      "index_rate_15m": svc.meters["indexing"].rate(900),
                      "delete_total": svc.indexing_stats["delete_total"],
                      "noop_update_total": 0, "is_throttled": False,
                      "throttle_time_in_millis": 0}
                if types_sel:
                    ix["types"] = {
                        t: {"index_total": c, "index_time_in_millis": 0,
                            "index_current": 0, "delete_total": 0}
                        for t, c in svc.indexing_stats["types"].items()
                        if any(fnmatch.fnmatch(t, x) for x in types_sel)}
                out["indexing"] = ix
            if "get" in want:
                out["get"] = {"total": svc.get_total, "exists_total": 0,
                              "missing_total": 0, "current": 0,
                              "time_in_millis": 0}
            if "search" in want:
                se = {"open_contexts": 0,
                      "query_total": svc.query_total,
                      "query_time_in_millis": 0, "query_current": 0,
                      "query_rate_1m": svc.meters["search"].rate(60),
                      "query_rate_5m": svc.meters["search"].rate(300),
                      "query_rate_15m": svc.meters["search"].rate(900),
                      "fetch_total": svc.query_total,
                      "fetch_time_in_millis": 0, "fetch_current": 0}
                if groups_sel:
                    se["groups"] = {
                        t: {"query_total": c, "query_time_in_millis": 0,
                            "query_current": 0, "fetch_total": c,
                            "fetch_time_in_millis": 0, "fetch_current": 0}
                        for t, c in svc.search_groups.items()
                        if any(fnmatch.fnmatch(t, x) for x in groups_sel)}
                # device-lane split: packed one-program serves + plan-shape
                # batched serves vs general per-segment path — the
                # "how much of the load rides one device program" gauge
                se["lanes"] = dict(svc.search_stats)
                out["search"] = se
            if "merge" in want:
                out["merges"] = {
                    "current": 0, "current_docs": 0, "current_size_in_bytes": 0,
                    "total": sum(e.merge_count for e in svc.shards),
                    "total_time_in_millis": 0, "total_docs": 0,
                    "total_size_in_bytes": 0}
            if "refresh" in want:
                out["refresh"] = {"total": sum(e.refresh_count
                                               for e in svc.shards),
                                  "total_time_in_millis": 0}
            if "flush" in want:
                out["flush"] = {"total": sum(
                    getattr(e, "flush_count", 0) for e in svc.shards),
                    "total_time_in_millis": 0}
            if "warmer" in want:
                out["warmer"] = {"current": 0, "total": 0,
                                 "total_time_in_millis": 0}
            rc = node.caches.request_cache.index_stats(svc.name)
            if "filter_cache" in want:
                # the query-plan cache is this engine's filter/query-cache
                # analog (compiled executables, not doc-id bitsets); its
                # per-index share keyed by the plan key's index component
                plan_bytes = plan_entries = 0
                for k, _v, w in node.caches.query_plan.entries_snapshot():
                    if k[0] == svc.name:
                        plan_bytes += w
                        plan_entries += 1
                out["filter_cache"] = {"memory_size_in_bytes": plan_bytes,
                                       "entries": plan_entries,
                                       "evictions":
                                           node.caches.query_plan.evictions}
            if "query_cache" in want:
                # wire-format parity: ES 2.0 clients read the request
                # cache's numbers under this section name too
                out["query_cache"] = {
                    "memory_size_in_bytes": rc["bytes"],
                    "hit_count": svc.request_cache_hits,
                    "miss_count": svc.request_cache_misses,
                    "evictions": rc["evictions"]}
            if "request_cache" in want:
                out["request_cache"] = {
                    "memory_size_in_bytes": rc["bytes"],
                    "entries": rc["count"],
                    "hit_count": svc.request_cache_hits,
                    "miss_count": svc.request_cache_misses,
                    "evictions": rc["evictions"]}
            if "id_cache" in want:
                # parent/child id maps ride the fielddata tier here: the
                # live bytes of _parent/_uid columns, usually 0
                out["id_cache"] = {"memory_size_in_bytes": sum(
                    nb for f, nb in fd_fields.items()
                    if f.startswith(("_parent", "_uid")))}
            if "fielddata" in want:
                fd = {"memory_size_in_bytes": sum(fd_fields.values()),
                      "evictions":
                          node.caches.fielddata.evictions_of(svc.name)}
                if fd_sel:
                    fd["fields"] = {
                        f: {"memory_size_in_bytes": nb}
                        for f, nb in fd_fields.items()
                        if any(fnmatch.fnmatch(f, x) for x in fd_sel)}
                out["fielddata"] = fd
            if "percolate" in want:
                out["percolate"] = {"total": 0, "time_in_millis": 0,
                                    "current": 0,
                                    "memory_size_in_bytes": -1,
                                    "memory_size": "-1b", "queries": 0}
            if "completion" in want:
                co = {"size_in_bytes": sum(comp_fields.values())}
                if comp_sel:
                    co["fields"] = {
                        f: {"size_in_bytes": nb}
                        for f, nb in comp_fields.items()
                        if any(fnmatch.fnmatch(f, x) for x in comp_sel)}
                out["completion"] = co
            if "segments" in want:
                out["segments"] = {
                    "count": sum(s["count"] for s in seg),
                    "memory_in_bytes": sum(s["memory_in_bytes"]
                                           for s in seg)}
            if "translog" in want:
                out["translog"] = {"operations": sum(
                    len(list(e.translog.snapshot())) for e in svc.shards),
                    "size_in_bytes": 0}
            if "suggest" in want:
                out["suggest"] = {"total": 0, "time_in_millis": 0,
                                  "current": 0}
            if "recovery" in want:
                out["recovery"] = {"current_as_source": 0,
                                   "current_as_target": 0,
                                   "throttle_time_in_millis": 0}
            return out

        def acc(dst, src):
            for k, v in src.items():
                d = dst.setdefault(k, {})
                for k2, v2 in v.items():
                    if isinstance(v2, dict):
                        d2 = d.setdefault(k2, {})
                        for k3, v3 in v2.items():
                            if isinstance(v3, (int, float)) \
                                    and not isinstance(v3, bool):
                                d3 = d2.setdefault(k3, 0)
                                d2[k3] = d3 + v3
                            else:
                                d2[k3] = v3
                    elif isinstance(v2, (int, float)) \
                            and not isinstance(v2, bool):
                        d[k2] = d.get(k2, 0) + v2
                    else:
                        d[k2] = v2

        indices = {}
        prim_all: dict = {}
        total_shards = 0
        total_copies = 0
        for n in names:
            svc = node.indices[n]
            prim = shard_stats(svc)
            acc(prim_all, prim)
            entry = {"primaries": prim, "total": prim}
            if level == "shards":
                entry["shards"] = {
                    str(i): [dict(prim, routing={
                        "state": "STARTED", "primary": True,
                        "node": "tpu-node-0"})]
                    for i in range(svc.n_shards)}
            indices[n] = entry
            total_shards += svc.n_shards
            total_copies += svc.n_shards * (1 + svc.n_replicas)
        out = {"_shards": {"total": total_copies,
                           "successful": total_shards, "failed": 0},
               "_all": {"primaries": prim_all, "total": prim_all}}
        if level != "cluster":
            out["indices"] = indices
        if not g.get("index") and "search" in want:
            # node-wide device timers + breaker hierarchy: the TPU
            # observability surface (ref AllCircuitBreakerStats)
            out["breakers"] = node.breakers.stats()
            out["search_phases"] = node.phase_timers.stats()
        return 200, out
    c.register("GET", "/_stats", index_stats_v2)
    c.register("GET", "/{index}/_stats", index_stats_v2)
    c.register("GET", "/_stats/{metric}", index_stats_v2)
    c.register("GET", "/{index}/_stats/{metric}", index_stats_v2)

    # -- nodes info / stats (ref rest/action/admin/cluster/node/) ----------
    def nodes_info(g, p, b):
        return 200, {"cluster_name": node.cluster_name, "nodes": {
            "tpu-node-0": {"name": "tpu-node-0", "version": "2.0.0-tpu",
                           "host": "localhost", "ip": "127.0.0.1",
                           "transport_address": "local[1]",
                           "http_address": "127.0.0.1:9200",
                           "build": "tensor-native",
                           "os": {}, "jvm": {},
                           "transport": {"profiles": {}},
                           "http": {},
                           "plugins": getattr(node, "plugins", None)
                           and node.plugins.infos() or []}}}
    c.register("GET", "/_nodes", nodes_info)
    c.register("GET", "/_nodes/{metric}", nodes_info)

    def nodes_stats(g, p, b):
        # per-phase device/host timers are the TPU hot_threads analog:
        # they say WHERE a slow search spent its time (parse vs device
        # program vs fetch/render; ref monitor/jvm/HotThreads.java:36 +
        # SearchStats — VERDICT r4 #10 observability floor). os/process/
        # fs/jvm sections come from common/monitor.py (ref monitor/*Service)
        from ..common import monitor
        return 200, {"cluster_name": node.cluster_name, "nodes": {
            "tpu-node-0": {"name": "tpu-node-0",
                           "indices": {"docs": {"count": sum(
                               s.doc_count()
                               for s in node.indices.values())}},
                           "os": monitor.os_stats(),
                           "process": monitor.process_stats(),
                           "jvm": monitor.runtime_stats(),
                           "fs": monitor.fs_stats([node.data_path]),
                           "breakers": node.breakers.stats(),
                           "thread_pool": node.thread_pool.stats(),
                           "search_phases": node.phase_timers.stats(),
                           "profiling": node.metrics.stats(),
                           "tasks": node.tasks.stats(),
                           "slowlog_tail": node.slowlog.snapshot(),
                           "search_batcher": node._batcher.stats(),
                           "caches": node.caches.stats(),
                           "rates": {name: m.stats()
                                     for name, m in node.meters.items()}}}}
    c.register("GET", "/_nodes/stats", nodes_stats)
    c.register("GET", "/_nodes/stats/{metric}", nodes_stats)

    # -- span tracing (common/tracing.py): the retained-trace ring ---------
    def list_traces(g, p, b):
        # newest-first summaries; GET /_traces/{id} has the full tree
        return 200, {"traces": node.tracer.list()}
    c.register("GET", "/_traces", list_traces)

    def get_trace(g, p, b):
        from ..common.tracing import chrome_trace, otlp_trace, span_tree
        t = node.tracer.get(g["trace_id"])
        if t is None:
            return 404, {"error": f"ResourceNotFoundException: trace "
                                  f"[{g['trace_id']}] not found "
                                  f"(expired from the ring or never "
                                  f"retained)", "status": 404}
        fmt = p.get("format", [None])[0]
        if fmt == "chrome":
            # Chrome trace-event JSON: load in chrome://tracing / Perfetto
            return 200, chrome_trace(t)
        if fmt == "otlp":
            return 200, otlp_trace(t)
        return 200, span_tree(t)
    c.register("GET", "/_traces/{trace_id}", get_trace)

    def nodes_slowlog(g, p, b):
        # the slowlog tails as a first-class endpoint: each entry carries
        # its trace_id, so a slow line links straight to GET /_traces/{id}
        import fnmatch as _fn
        want = p.get("index", [None])[0]

        def _filter(entries):
            if not want:
                return entries
            pats = [x for x in str(want).split(",") if x]
            return [e for e in entries
                    if any(_fn.fnmatch(e.get("index", ""), pat)
                           for pat in pats)]
        return 200, {"cluster_name": node.cluster_name, "nodes": {
            "tpu-node-0": {
                "search": _filter(node.slowlog.snapshot()),
                "indexing": _filter(node.indexing_slowlog.snapshot())}}}
    c.register("GET", "/_nodes/slowlog", nodes_slowlog)

    def nodes_device_stats(g, p, b):
        # device telemetry (ISSUE 16): the per-compiled-program registry
        # (top-N by cumulative dispatch time, with scrape-time XLA cost
        # analysis — None fields on backends that report nothing), per-
        # device HBM stats with the process high-water mark, and the
        # global lane-decision counters
        try:
            top_n = int(p.get("top_n", [50])[0])
        except (TypeError, ValueError):
            top_n = 50
        return 200, {"cluster_name": node.cluster_name, "nodes": {
            "tpu-node-0": node.device_stats_payload(top_n=top_n)}}
    c.register("GET", "/_nodes/device_stats", nodes_device_stats)

    def nodes_stats_history(g, p, b):
        # the StatsSampler ring (common/monitor.py): timestamped gauge
        # samples + min/max/avg rollups, so a spike BETWEEN two stats
        # calls is still inspectable without an external TSDB
        sel = _csv_param(p, "metric")
        return 200, {"cluster_name": node.cluster_name, "nodes": {
            "tpu-node-0": node.sampler.history(sel)}}
    c.register("GET", "/_nodes/stats/history", nodes_stats_history)

    def monitoring_overview(g, p, b):
        # self-monitoring overview (ISSUE 17): a REAL sorted + 2-level
        # sub-agg search over the `.monitoring-es-*` indices the
        # collector fills — the node observing itself through the
        # sorted/sub-agg device lanes this tier builds
        mon = getattr(node, "monitoring", None)
        if mon is None:
            return 404, {"error": "ResourceNotFoundException: monitoring "
                                  "is not enabled on this node (set "
                                  "node.monitoring.enable)", "status": 404}
        try:
            size = int(p.get("size", [10])[0])
        except (TypeError, ValueError):
            size = 10
        interval = p.get("interval", ["1m"])[0] or "1m"
        return 200, mon.overview(size=size, interval=interval)
    c.register("GET", "/_monitoring/overview", monitoring_overview)

    def metrics_exposition(g, p, b):
        # OpenMetrics text over every stats registry (common/metrics.py
        # render walk; `# TYPE`/`# HELP`, `_total`/`_bytes` conventions,
        # node/pool/breaker/index labels) — the standard scrape surface
        from ..common.metrics import render_openmetrics
        return 200, render_openmetrics(node.metric_sections(),
                                       node="tpu-node-0")
    c.register("GET", "/_metrics", metrics_exposition)
    c.register("GET", "/_prometheus/metrics", metrics_exposition)

    # -- watcher alerting tier (ISSUE 20): watch CRUD + stats + alerts -----
    def _watcher_service():
        ws = getattr(node, "watcher_service", None)
        if ws is None:
            raise RestError(400, "watcher is not enabled on this node "
                                 "(set watcher.enable)")
        return ws

    def put_watch(g, p, b):
        from ..watcher.watch import WatchParsingException
        ws = _watcher_service()
        try:
            out = ws.put_watch(g["watch_id"], _json_body(b))
        except WatchParsingException as e:
            return 400, {"error": f"WatchParsingException: {e}",
                         "status": 400}
        status = 201 if out["created"] else 200
        return status, out
    c.register("PUT", "/_watcher/watch/{watch_id}", put_watch)

    def get_watch(g, p, b):
        from ..watcher.service import WatchMissingException
        ws = _watcher_service()
        try:
            return 200, ws.get_watch(g["watch_id"])
        except WatchMissingException:
            return 404, {"found": False, "_id": g["watch_id"],
                         "status": 404}
    c.register("GET", "/_watcher/watch/{watch_id}", get_watch)

    def delete_watch(g, p, b):
        from ..watcher.service import WatchMissingException
        ws = _watcher_service()
        try:
            return 200, ws.delete_watch(g["watch_id"])
        except WatchMissingException:
            return 404, {"found": False, "_id": g["watch_id"],
                         "status": 404}
    c.register("DELETE", "/_watcher/watch/{watch_id}", delete_watch)

    def execute_watch(g, p, b):
        # manual evaluation outside the schedule (ref _execute): runs
        # the input search + condition now, fires/throttles for real
        from ..watcher.service import WatchMissingException
        ws = _watcher_service()
        try:
            return 200, ws.execute_watch(g["watch_id"])
        except WatchMissingException:
            return 404, {"found": False, "_id": g["watch_id"],
                         "status": 404}
    c.register("POST", "/_watcher/watch/{watch_id}/_execute", execute_watch)

    def ack_watch(g, p, b):
        # acked watches stay quiet until the condition goes false once
        from ..watcher.service import WatchMissingException
        ws = _watcher_service()
        try:
            return 200, ws.ack_watch(g["watch_id"])
        except WatchMissingException:
            return 404, {"found": False, "_id": g["watch_id"],
                         "status": 404}
    c.register("PUT", "/_watcher/watch/{watch_id}/_ack", ack_watch)

    def watcher_stats(g, p, b):
        return 200, _watcher_service().watcher_stats()
    c.register("GET", "/_watcher/stats", watcher_stats)

    def list_alerts(g, p, b):
        # the audit trail: newest firings across the rolling
        # `.alerts-es-*` indices, optionally filtered per watch
        ws = _watcher_service()
        try:
            size = int(p.get("size", [50])[0])
        except (TypeError, ValueError):
            size = 50
        return 200, ws.alerts(size=size,
                              watch_id=p.get("watch_id", [None])[0])
    c.register("GET", "/_alerts", list_alerts)

    # -- task management (ref tasks/TaskManager + ListTasksAction:
    #    GET /_tasks, GET /_tasks/{id}, GET /_cat/tasks) -------------------
    def list_tasks_api(g, p, b):
        out = node.tasks.list_tasks(
            actions=p.get("actions", [None])[0],
            detailed=_pbool(p, "detailed", False))
        if _pbool(p, "recent", False):
            # recently-completed ring: short-lived shard tasks stay
            # assertable after the request finishes (test seam)
            out["recent"] = node.tasks.recent_infos(
                actions=p.get("actions", [None])[0])
        return 200, out
    c.register("GET", "/_tasks", list_tasks_api)

    def get_task_api(g, p, b):
        t = node.tasks.get(g["task_id"])
        if t is None:
            return 404, {"error": f"ResourceNotFoundException: task "
                                  f"[{g['task_id']}] isn't running",
                         "status": 404}
        return 200, {"completed": False, "task": t.info(detailed=True)}
    c.register("GET", "/_tasks/{task_id}", get_task_api)

    def _duration_ms(v: str, default: float) -> float:
        s = str(v).strip().lower()
        for suffix, mult in (("micros", 0.001), ("ms", 1.0), ("s", 1000.0),
                             ("m", 60_000.0), ("h", 3_600_000.0)):
            if s.endswith(suffix):
                try:
                    return float(s[: -len(suffix)]) * mult
                except ValueError:
                    return default
        try:
            return float(s)
        except ValueError:
            return default

    def nodes_hot_threads(g, p, b):
        from ..common import monitor
        return 200, monitor.hot_threads(
            threads=int(p.get("threads", ["3"])[0]),
            snapshots=int(p.get("snapshots", ["10"])[0]),
            interval_ms=_duration_ms(p.get("interval", ["50ms"])[0], 50.0))
    c.register("GET", "/_nodes/hot_threads", nodes_hot_threads)
    c.register("GET", "/_nodes/{node_id}/hot_threads", nodes_hot_threads)
    c.register("GET", "/_cluster/nodes/hotthreads", nodes_hot_threads)

    def cluster_stats(g, p, b):
        # ref action/admin/cluster/stats/ClusterStatsNodes+Indices
        from ..common import monitor
        seg_count = mem = docs = deleted = 0
        shards = 0
        for svc in node.indices.values():
            shards += svc.n_shards
            docs += svc.doc_count()
            for e in svc.shards:
                st = e.segment_stats()
                seg_count += st["count"]
                mem += st["memory_in_bytes"]
                deleted += st["deleted"]
        return 200, {
            "timestamp": int(time.time() * 1000),
            "cluster_name": node.cluster_name,
            "status": node.cluster_health()["status"],
            "indices": {
                "count": len(node.indices),
                "shards": {"total": shards, "primaries": shards},
                "docs": {"count": docs, "deleted": deleted},
                "store": {"size_in_bytes": mem},
                "segments": {"count": seg_count,
                             "memory_in_bytes": mem},
            },
            "nodes": {
                "count": {"total": 1, "master_data": 1},
                "versions": ["2.0.0-tpu"],
                "os": monitor.os_stats(),
                "process": monitor.process_stats(),
                "jvm": monitor.runtime_stats(),
                "fs": monitor.fs_stats([node.data_path]),
            },
        }
    c.register("GET", "/_cluster/stats", cluster_stats)

    # -- warmers (registry parity; packed-view warmup is the real warmer) --
    def put_warmer(g, p, b):
        body = _json_body(b)
        for n in node._resolve(g.get("index", "_all")):
            svc = node.indices[n]
            if not hasattr(svc, "warmers"):
                svc.warmers = {}
            svc.warmers[g["name"]] = {
                "types": [g["type"]] if g.get("type") else [],
                "source": body}
        return 200, {"acknowledged": True}
    c.register("PUT", "/{index}/_warmer/{name}", put_warmer)
    c.register("PUT", "/{index}/{type}/_warmer/{name}", put_warmer)
    c.register("PUT", "/_warmer/{name}", put_warmer)

    def get_warmer(g, p, b):
        name = g.get("name")
        out = {}
        for n in node._resolve(g.get("index", "_all")):
            svc = node.indices[n]
            wm = getattr(svc, "warmers", {})
            if name:
                pats = ["*" if x == "_all" else x for x in name.split(",")]
                wm = {w: s for w, s in wm.items()
                      if any(fnmatch.fnmatch(w, pat) for pat in pats)}
                if wm:
                    out[n] = {"warmers": wm}
            else:
                # unfiltered listing shows every index, empty map included
                out[n] = {"warmers": wm}
        return 200, out
    for pat in ("/_warmer", "/_warmer/{name}", "/{index}/_warmer",
                "/{index}/_warmer/{name}"):
        c.register("GET", pat, get_warmer)

    def delete_warmer(g, p, b):
        name = g.get("name")
        if not name:
            raise RestError(400, "ActionRequestValidationException: "
                                 "warmer name is missing")
        removed = False
        for n in node._resolve(g["index"]):
            svc = node.indices[n]
            wm = getattr(svc, "warmers", {})
            match = list(wm) if name in ("_all", "*") else \
                [w for w in wm if any(fnmatch.fnmatch(w, pat)
                                      for pat in name.split(","))]
            for w in match:
                del wm[w]
                removed = True
        if not removed:
            return 404, {"error": f"IndexWarmerMissingException: "
                                  f"index_warmer [{name}] missing",
                         "status": 404}
        return 200, {"acknowledged": True}
    c.register("DELETE", "/{index}/_warmer/{name}", delete_warmer)
    c.register("DELETE", "/{index}/_warmer", delete_warmer)


def _parse_bulk(body: bytes, default_index: str | None) -> list:
    """NDJSON bulk format (ref rest/action/bulk/RestBulkAction).

    All lines parse as ONE json array (a single C-level loads instead of
    one per line — measurable at 100k-doc ingests); the python walk only
    pairs action lines with their sources. Ops carry the raw source
    line's byte length as a 4th element so the engine's buffered-bytes
    estimate skips re-walking each source dict (node.bulk accepts both
    3- and 4-tuples)."""
    lines = [ln for ln in body.split(b"\n") if ln and not ln.isspace()]
    if not lines:
        return []
    docs = json.loads(b"[" + b",".join(lines) + b"]")
    ops = []
    i = 0
    n = len(docs)
    while i < n:
        action_line = docs[i]
        (action, meta), = action_line.items()
        if default_index and "_index" not in meta:
            meta["_index"] = default_index
        i += 1
        source = None
        raw_len = 0
        if action != "delete" and i < n:
            source = docs[i]
            raw_len = len(lines[i])
            i += 1
        ops.append((action, meta, source, raw_len))
    return ops




# ---------------------------------------------------------------------------

# which QoS traffic class admits each pool-routed request class (the
# reference's five connection types, NettyTransport.java:180-184 — REST
# traffic is read (search-class) or write (bulk-class); state/ping are
# transport-internal and never shed). Pool None (management) skips
# admission entirely: control-plane reads must work DURING an overload.
_TRAFFIC_CLASS_OF = {"search": "search", "get": "search",
                     "bulk": "bulk", "index": "bulk"}


def _pool_of(method: str, path: str) -> str | None:
    """Which named thread pool serves this request class (ref
    ThreadPool.Names mapping in each TransportAction's executor()); None =
    run inline on the connection thread (management/admin)."""
    seg = [s for s in path.split("/") if s]
    _SEARCH = {"_search", "_msearch", "_count", "_suggest", "_percolate",
               "_mpercolate", "_count_percolate", "_explain", "_validate",
               "_mlt", "_knn", "_termvectors", "_termvector",
               "_mtermvectors", "_search_shards"}
    if any(s in _SEARCH for s in seg):
        return "search"
    if "_bulk" in seg:
        return "bulk"
    if "_mget" in seg:
        return "get"
    if (len(seg) == 3 and not any(s.startswith("_") for s in seg[:2])):
        if method in ("GET", "HEAD"):
            return "get"
        if method in ("PUT", "POST", "DELETE"):
            return "index"
    if len(seg) == 4 and seg[3] == "_update":
        return "index"
    return None


class HttpServer:
    """Threaded HTTP front-end (ref http/HttpServer.java + netty transport)."""

    def __init__(self, node: NodeService, host: str = "127.0.0.1",
                 port: int = 9200, registrar: Callable | None = None):
        self.controller = RestController(node, registrar=registrar)
        if getattr(node, "plugins", None) is not None:
            # plugins may contribute REST endpoints (ref PluginsService +
            # RestModule handler registration)
            node.plugins.register_routes(self.controller, node)
        controller = self.controller

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):   # silence per-request logs
                pass

            def _handle(self, method: str):
                parsed = urlparse(self.path)
                params = parse_qs(parsed.query)
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                # XContent seam (common/xcontent.py; ref XContentFactory):
                # YAML/CBOR request bodies normalize to JSON at the edge so
                # every handler stays single-format
                ctype_in = self.headers.get("Content-Type") or ""
                if body and ("yaml" in ctype_in or "cbor" in ctype_in
                             or "smile" in ctype_in):
                    from ..common import xcontent
                    try:
                        body = json.dumps(
                            xcontent.decode(body, ctype_in)).encode()
                    except Exception as e:  # noqa: BLE001 — yaml/cbor
                        # parsers raise their own types; ALL malformed
                        # bodies must 406, never drop the connection
                        self._reply(406, json.dumps(
                            {"error": f"{type(e).__name__}: {e}",
                             "status": 406}).encode(),
                            "application/json; charset=UTF-8", method)
                        return
                req_headers = {k.lower(): v for k, v in self.headers.items()}
                extra_headers: dict = {}
                try:
                    # admission control (serving/qos.py, ISSUE 9): the QoS
                    # controller sheds excess load per traffic class as
                    # 429 + Retry-After BEFORE the pool, then each request
                    # class runs on its named bounded pool; queue overflow
                    # -> 429 before any engine/device work (ref
                    # ThreadPool.java:116 + EsRejectedExecutionException)
                    pool = _pool_of(method, parsed.path)
                    tp = getattr(node, "thread_pool", None)
                    qos = getattr(node, "qos", None)
                    tclass = _TRAFFIC_CLASS_OF.get(pool)
                    admission = qos.admit(tclass) \
                        if qos is not None and tclass is not None \
                        else contextlib.nullcontext()
                    with admission:
                        if pool is None or tp is None:
                            status, payload = controller.dispatch(
                                method, parsed.path, params, body,
                                req_headers)
                        else:
                            status, payload = tp.submit(
                                pool, controller.dispatch,
                                method, parsed.path, params, body,
                                req_headers).result()
                except Exception as e:  # noqa: BLE001 — REST error contract
                    status = _status_of(e)
                    payload = {"error": f"{type(e).__name__}: {e}",
                               "status": status}
                    if status == 429:
                        # backpressure contract: every shed/rejection
                        # carries a client backoff hint (never a 5xx)
                        retry = getattr(e, "retry_after_s", None)
                        if retry is None and getattr(node, "qos", None) \
                                is not None:
                            retry = node.qos.retry_after_s()
                        import math as _math
                        extra_headers["Retry-After"] = \
                            str(int(_math.ceil(retry or 1.0)))
                fmt = params.get("format", [None])[0]
                if isinstance(payload, bytes):
                    data = payload           # pre-serialized JSON fast lane
                    ctype = "application/json; charset=UTF-8"
                elif isinstance(payload, str):
                    data = payload.encode("utf-8")
                    ctype = "text/plain; charset=UTF-8"
                elif fmt in ("yaml", "cbor"):
                    from ..common import xcontent
                    try:
                        data, ctype = xcontent.encode(payload, fmt)
                    except Exception:  # noqa: BLE001 — unencodable value:
                        data = json.dumps(payload).encode()  # JSON fallback
                        ctype = "application/json; charset=UTF-8"
                else:
                    data = json.dumps(payload).encode("utf-8")
                    ctype = "application/json; charset=UTF-8"
                self._reply(status, data, ctype, method,
                            opaque_id=req_headers.get("x-opaque-id"),
                            extra=extra_headers)

            def _reply(self, status, data, ctype, method, opaque_id=None,
                       extra=None):
                if method == "HEAD":
                    data = b""
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                if opaque_id:
                    # the reference echoes X-Opaque-Id on every response
                    self.send_header("X-Opaque-Id", opaque_id)
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_DELETE(self):
                self._handle("DELETE")

            def do_HEAD(self):
                self._handle("HEAD")

        class Server(ThreadingHTTPServer):
            # stdlib default backlog is 5: a burst of concurrent clients
            # (the dynamic batcher's whole point) gets connection resets
            request_queue_size = 128
            daemon_threads = True

        self.server = Server((host, port), Handler)
        self.port = self.server.server_port
        self._thread: threading.Thread | None = None

    def start(self) -> "HttpServer":
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
