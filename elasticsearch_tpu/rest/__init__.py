"""REST API surface (ref rest/, http/, SURVEY.md §2.8)."""

from .http_server import HttpServer, RestController, RestError

__all__ = ["HttpServer", "RestController", "RestError"]
