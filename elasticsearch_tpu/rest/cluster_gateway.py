"""REST gateway fronting a multi-node cluster.

In the reference EVERY node serves HTTP and coordinates distributed
execution (http/HttpServer.java feeding the action layer). This registrar
plugs a ClusterNode coordinator into the same threaded HttpServer /
RestController plumbing the single-node product uses, so REST requests hit
a real cluster: metadata ops become master tasks, document ops route to
primaries with replication, search runs the full 2-phase scatter-gather
(cluster/node.py).

    node = cluster.client()
    HttpServer(node, port=9200, registrar=register_cluster_routes).start()
"""

from __future__ import annotations

import json
import time

from ..cluster.node import ClusterNode
from .http_server import RestError, _json_body, _parse_bulk


def register_cluster_routes(c, node: ClusterNode) -> None:
    # -- banner / health ---------------------------------------------------
    def banner(g, p, b):
        return 200, {"status": 200, "name": node.node_id,
                     "cluster_name": "elasticsearch-tpu",
                     "version": {"number": "2.0.0-tpu",
                                 "lucene_version": "tensor-native"},
                     "tagline": "You Know, for Search"}
    c.register("GET", "/", banner)
    c.register("HEAD", "/", banner)

    def health(g, p, b):
        h = node.health()
        want = p.get("wait_for_status", [None])[0]
        deadline = time.monotonic() + 30.0
        rank = {"red": 0, "yellow": 1, "green": 2}
        while want and rank[h["status"]] < rank.get(want, 0) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
            h = node.health()
        return 200, {"cluster_name": h["cluster_name"],
                     "status": h["status"], "timed_out": False,
                     "number_of_nodes": h["number_of_nodes"],
                     "number_of_data_nodes": h["number_of_data_nodes"],
                     "active_primary_shards": h["active_primary_shards"],
                     "active_shards": h["active_shards"],
                     "relocating_shards": h.get("relocating_shards", 0),
                     "initializing_shards": h["initializing_shards"],
                     "unassigned_shards": h["unassigned_shards"]}
    c.register("GET", "/_cluster/health", health)
    c.register("GET", "/_cluster/health/{index}", health)

    def cluster_state(g, p, b):
        st = node.cluster.current()
        return 200, {"cluster_name": st.data.get("cluster_name"),
                     "master_node": st.master_node, "version": st.version,
                     "nodes": st.nodes,
                     "metadata": {"indices": st.indices},
                     "routing_table": {"indices": {
                         i: {"shards": {str(s): copies
                                        for s, copies in enumerate(shards)}}
                         for i, shards in st.routing.items()}}}
    c.register("GET", "/_cluster/state", cluster_state)

    def nodes_stats(g, p, b):
        # the nodes template over the REAL transport: every live node
        # answers for itself; handler errors on live nodes surface as
        # per-node failures (ref TransportNodesStatsAction +
        # FailedNodeException)
        res = node.nodes_stats()
        out = {"cluster_name": "elasticsearch-tpu", "nodes": res["nodes"]}
        if res["failures"]:
            out["failures"] = res["failures"]
        return 200, out
    c.register("GET", "/_nodes/stats", nodes_stats)
    c.register("GET", "/_nodes/stats/{metric}", nodes_stats)

    def metrics_local(g, p, b):
        # the coordinator's OWN exposition (same contract as the
        # single-node /_metrics)
        from ..common.metrics import render_openmetrics
        return 200, render_openmetrics(node.metric_sections(),
                                       node=node.node_id)
    c.register("GET", "/_metrics", metrics_local)
    c.register("GET", "/_prometheus/metrics", metrics_local)

    def cluster_metrics(g, p, b):
        # cluster-wide exposition: per-node sections fan out over the
        # transport and merge into ONE valid document (same family, one
        # sample per node via the `node` label); live nodes whose handler
        # errored surface as comment entries, never a dropped scrape
        from ..common.metrics import openmetrics_families, render_families
        res = node.nodes_metric_sections()
        fams: dict = {}
        for node_id, sections in sorted(res["sections_by_node"].items()):
            openmetrics_families(sections, node_id, fams)
        comments = [
            f"node-failure node={f['node']} reason="
            + str(f["reason"])[:200].replace("\n", " ")
            for f in res["failures"]]
        return 200, render_families(fams, comments=comments)
    c.register("GET", "/_cluster/_metrics", cluster_metrics)

    def list_tasks(g, p, b):
        # tasks running on THIS coordinator (shard tasks live on the
        # copy-holders' own managers, parent-linked over the transport)
        detailed = p.get("detailed", ["false"])[0] not in ("false", None)
        out = node.tasks.list_tasks(actions=p.get("actions", [None])[0],
                                    detailed=detailed)
        if p.get("recent", ["false"])[0] not in ("false", None):
            out["recent"] = node.tasks.recent_infos(
                actions=p.get("actions", [None])[0])
        return 200, out
    c.register("GET", "/_tasks", list_tasks)

    def nodes_info(g, p, b):
        # node INFO shape (addresses/version — what client sniffers read;
        # ref RestNodesInfoAction), distinct from the stats body
        st = node.cluster.current()
        infos = {}
        for node_id in sorted(st.nodes):
            addr = None
            net = getattr(node.transport, "network", None)
            if net is not None and hasattr(net, "address_of"):
                addr = net.address_of(node_id)
            infos[node_id] = {
                "name": node_id, "version": "2.0.0-tpu",
                "build": "tensor-native",
                "transport_address": f"{addr[0]}:{addr[1]}" if addr
                else f"local[{node_id}]",
                "http_address": None, "host": "localhost",
                "ip": "127.0.0.1", "os": {}, "jvm": {},
                "transport": {"profiles": {}}, "http": {}, "plugins": []}
        return 200, {"cluster_name": "elasticsearch-tpu", "nodes": infos}
    c.register("GET", "/_nodes", nodes_info)

    def indices_stats(g, p, b):
        # the broadcast template over the transport: shard stats from
        # every holder, coordinator-aggregated (ref
        # TransportIndicesStatsAction over TransportBroadcastOperation)
        return 200, node.indices_stats(g.get("index", "_all"))
    c.register("GET", "/_stats", indices_stats)
    c.register("GET", "/{index}/_stats", indices_stats)

    # -- index admin (master template) ------------------------------------
    def create_index(g, p, b):
        body = _json_body(b)
        node.create_index(g["index"], settings=body.get("settings") or {},
                          mappings=body.get("mappings") or {})
        return 200, {"acknowledged": True}
    c.register("PUT", "/{index}", create_index)
    c.register("POST", "/{index}", create_index)

    def delete_index(g, p, b):
        node.delete_index(g["index"])
        return 200, {"acknowledged": True}
    c.register("DELETE", "/{index}", delete_index)

    def index_exists(g, p, b):
        st = node.cluster.current()
        return (200 if g["index"] in st.indices else 404), ""
    c.register("HEAD", "/{index}", index_exists)

    def put_mapping(g, p, b):
        node.put_mapping(g["index"], g.get("type", "_doc"), _json_body(b))
        return 200, {"acknowledged": True}
    c.register("PUT", "/{index}/_mapping/{type}", put_mapping)
    c.register("PUT", "/{index}/_mapping", put_mapping)
    c.register("POST", "/{index}/_mapping/{type}", put_mapping)

    def get_mapping(g, p, b):
        st = node.cluster.current()
        names = st.resolve_index(g.get("index", "_all"))
        out = {}
        for n in names:
            meta = st.index_meta(n) or {}
            out[n] = {"mappings": meta.get("mappings") or {}}
        return 200, out
    c.register("GET", "/{index}/_mapping", get_mapping)
    c.register("GET", "/_mapping", get_mapping)

    # -- documents (replicated writes / routed reads) ----------------------
    def _maybe_refresh(g, p):
        if p.get("refresh", ["false"])[0] != "false":
            node.refresh(g.get("index", "_all"))

    def put_doc(g, p, b):
        kw = {}
        if p.get("op_type", [None])[0] == "create":
            kw["op_type"] = "create"
        if "version" in p:
            kw["version"] = int(p["version"][0])
            kw["version_type"] = p.get("version_type", ["internal"])[0]
        r = node.index_doc(g["index"], g.get("id"), _json_body(b),
                           type_name=g.get("type", "_doc"),
                           routing=p.get("routing", [None])[0], **kw)
        _maybe_refresh(g, p)
        return (201 if r.get("created") else 200), {
            "_index": g["index"], "_type": g.get("type", "_doc"),
            "_id": r["_id"], "_version": r["_version"],
            "created": r.get("created", False)}
    c.register("PUT", "/{index}/{type}/{id}", put_doc)
    c.register("POST", "/{index}/{type}/{id}", put_doc)
    c.register("POST", "/{index}/{type}", put_doc)

    def get_doc(g, p, b):
        r = node.get_doc(g["index"], g["id"],
                         routing=p.get("routing", [None])[0])
        if not r["found"]:
            return 404, {"_index": g["index"], "_type": g.get("type"),
                         "_id": g["id"], "found": False}
        return 200, {"_index": g["index"], "_type": g.get("type", "_doc"),
                     "_id": g["id"], "_version": r["_version"],
                     "found": True, "_source": r["_source"]}
    c.register("GET", "/{index}/{type}/{id}", get_doc)
    c.register("HEAD", "/{index}/{type}/{id}", get_doc)

    def delete_doc(g, p, b):
        r = node.delete_doc(g["index"], g["id"],
                            routing=p.get("routing", [None])[0])
        _maybe_refresh(g, p)
        found = r.get("found", True)
        return (200 if found else 404), {
            "found": found, "_index": g["index"],
            "_type": g.get("type", "_doc"), "_id": g["id"],
            "_version": r["_version"]}
    c.register("DELETE", "/{index}/{type}/{id}", delete_doc)

    def bulk(g, p, b):
        ops = _parse_bulk(b, g.get("index"))
        items = node.bulk(ops)
        _maybe_refresh(g, p)
        errors = any(next(iter(i.values())).get("status", 200) >= 300
                     for i in items)
        return 200, {"took": 0, "errors": errors, "items": items}
    c.register("POST", "/_bulk", bulk)
    c.register("PUT", "/_bulk", bulk)
    c.register("POST", "/{index}/_bulk", bulk)
    c.register("POST", "/{index}/{type}/_bulk", bulk)

    # -- search (2-phase scatter-gather) -----------------------------------
    def search(g, p, b):
        body = _json_body(b) if b else {}
        if "size" in p:
            body["size"] = int(p["size"][0])
        if "from" in p:
            body["from"] = int(p["from"][0])
        if "q" in p:
            body["query"] = {"query_string": {"query": p["q"][0]}}
        scroll = p.get("scroll", [None])[0]
        out = node.search(g.get("index", "_all"), body,
                          preference=p.get("preference", [None])[0],
                          scroll=scroll)
        return 200, out
    c.register("GET", "/{index}/_search", search)
    c.register("POST", "/{index}/_search", search)
    c.register("GET", "/_search", search)
    c.register("POST", "/_search", search)

    def scroll_next(g, p, b):
        body = {}
        sid = p.get("scroll_id", [None])[0]
        if b and b.strip().startswith(b"{"):
            body = _json_body(b)
            sid = body.get("scroll_id") or sid
        elif b and sid is None:
            sid = b.decode("utf-8").strip()   # bare-id body (pre-2.0 form)
        if not sid:
            raise RestError(400, "scroll_id is missing")
        keep = body.get("scroll") or p.get("scroll", [None])[0]
        from ..cluster.node import SearchContextMissingException
        try:
            return 200, node.scroll(sid, keep_alive=keep)
        except SearchContextMissingException as e:
            raise RestError(404, f"SearchContextMissingException: {e}")
    c.register("GET", "/_search/scroll", scroll_next)
    c.register("POST", "/_search/scroll", scroll_next)

    def clear_scroll(g, p, b):
        body = _json_body(b) if b else {}
        sids = body.get("scroll_id") or []
        if isinstance(sids, str):
            sids = [sids]
        found = any([node.clear_scroll(s) for s in sids])  # clear ALL ids
        return 200, {"succeeded": True, "found": found}
    c.register("DELETE", "/_search/scroll", clear_scroll)

    def msearch(g, p, b):
        lines = [json.loads(ln) for ln in b.decode("utf-8").split("\n")
                 if ln.strip()]
        items = []
        for i in range(0, len(lines) - 1, 2):
            header = lines[i] or {}
            if "index" not in header and g.get("index"):
                header["index"] = g["index"]
            items.append((header, lines[i + 1]))
        return 200, node.msearch(items)
    c.register("POST", "/_msearch", msearch)
    c.register("GET", "/_msearch", msearch)
    c.register("POST", "/{index}/_msearch", msearch)

    def count(g, p, b):
        body = _json_body(b) if b else {}
        if "q" in p:
            body["query"] = {"query_string": {"query": p["q"][0]}}
        return 200, node.count(g.get("index", "_all"), body)
    c.register("GET", "/{index}/_count", count)
    c.register("POST", "/{index}/_count", count)
    c.register("GET", "/_count", count)

    # -- broadcast admin ---------------------------------------------------
    def refresh(g, p, b):
        node.refresh(g.get("index", "_all"))
        return 200, {"_shards": {"failed": 0}}
    c.register("POST", "/{index}/_refresh", refresh)
    c.register("GET", "/{index}/_refresh", refresh)
    c.register("POST", "/_refresh", refresh)

    def flush(g, p, b):
        node.flush(g.get("index", "_all"))
        return 200, {"_shards": {"failed": 0}}
    c.register("POST", "/{index}/_flush", flush)
    c.register("POST", "/_flush", flush)

    # -- _cat --------------------------------------------------------------
    def cat_shards(g, p, b):
        st = node.cluster.current()
        rows = []
        for index, shards in sorted(st.routing.items()):
            for sid, copies in enumerate(shards):
                for cp in copies:
                    rows.append(" ".join([
                        index, str(sid),
                        "p" if cp["primary"] else "r",
                        cp["state"], str(cp.get("node") or "-")]))
        return 200, "\n".join(rows) + ("\n" if rows else "")
    c.register("GET", "/_cat/shards", cat_shards)

    def cat_nodes(g, p, b):
        st = node.cluster.current()
        rows = [" ".join([nid,
                          "*" if nid == st.master_node else "-"])
                for nid in sorted(st.nodes)]
        return 200, "\n".join(rows) + "\n"
    c.register("GET", "/_cat/nodes", cat_nodes)

    def cat_recovery(g, p, b):
        # index shard source target stage files_total files_reused
        # bytes_total bytes_recovered throttle_waits retries elapsed_ms
        rows = []
        for r in node.cat_recovery():
            if g.get("index") and r["index"] != g["index"]:
                continue
            rows.append(" ".join([
                r["index"], str(r["shard"]), str(r["source"]),
                str(r["target"]), r["stage"], str(r["files_total"]),
                str(r["files_reused"]), str(r["bytes_total"]),
                str(r["bytes_recovered"]), str(r["throttle_waits"]),
                str(r["retries"]), f"{r['elapsed_ms']:.1f}"]))
        return 200, "\n".join(rows) + ("\n" if rows else "")
    c.register("GET", "/_cat/recovery", cat_recovery)
    c.register("GET", "/_cat/recovery/{index}", cat_recovery)

    # -- allocation / settings (ISSUE 15) ----------------------------------
    def allocation_explain(g, p, b):
        body = _json_body(b) if b else {}
        try:
            out = node.allocation_explain(
                index=body.get("index"),
                shard=body.get("shard"),
                primary=body.get("primary"))
        except ValueError as e:
            raise RestError(400, str(e))
        except KeyError as e:
            raise RestError(404, str(e))
        return 200, out
    c.register("POST", "/_cluster/allocation/explain", allocation_explain)
    c.register("GET", "/_cluster/allocation/explain", allocation_explain)

    def put_cluster_settings(g, p, b):
        body = _json_body(b) if b else {}
        # accept both the flat form and the transient/persistent wrappers
        upd: dict = {}
        for section in ("persistent", "transient"):
            sec = body.get(section)
            if isinstance(sec, dict):
                upd.update(sec)
        if not upd:
            upd = {k: v for k, v in body.items()
                   if k not in ("persistent", "transient")}
        if not upd:
            raise RestError(400, "no settings to update")
        return 200, node.update_cluster_settings(upd)
    c.register("PUT", "/_cluster/settings", put_cluster_settings)

    def get_cluster_settings(g, p, b):
        st = node.cluster.current()
        return 200, {"persistent": {},
                     "transient": dict(st.data.get("settings") or {})}
    c.register("GET", "/_cluster/settings", get_cluster_settings)
