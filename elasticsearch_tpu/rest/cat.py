"""_cat API: aligned-column text tables with v/h/help semantics.

Analog of /root/reference/src/main/java/org/elasticsearch/rest/action/cat/
(RestTable.java renders; each endpoint declares its columns). Contract per
the cat.* YAML suites: default output is rows only, `v=true` prepends the
header row, `h=a,b` selects columns (including non-default ones), and
`help=true` lists every column as "name | alias | description" lines.
"""

from __future__ import annotations

import re
import time

_NUMERIC = re.compile(r"^-?\d+(\.\d+)?([ptgmk]?b|%)?$")


def render(p: dict, columns: list[tuple[str, str]], rows: list[dict],
           defaults: list[str] | None = None,
           aliases: dict | None = None) -> str:
    """columns: [(name, help_text)]; rows: dicts keyed by column name.
    aliases: short-form column names (h=a,b may use them; the header echoes
    the requested token, values resolve through the canonical name)."""
    if p.get("help", ["false"])[0] not in ("false", None):
        return "".join(f"{name:<14} | - | {hlp}\n" for name, hlp in columns)
    sel = p.get("h", [None])
    sel = ",".join(sel) if isinstance(sel, list) and sel != [None] else \
        (sel[0] if isinstance(sel, list) else sel)
    amap = aliases or {}
    known = {name for name, _ in columns}
    if sel:
        requested = [c.strip(" '\"") for c in str(sel).strip("[]").split(",")
                     if c.strip(" '\"")]
        # unknown columns are silently dropped (RestTable behavior)
        names = [n for n in requested if amap.get(n, n) in known]
    else:
        names = defaults or [name for name, _ in columns]
    data = [[str(r.get(amap.get(n, n), r.get(n, ""))) for n in names]
            for r in rows]
    header = p.get("v", ["false"])[0] == "true"
    if not data and not header:
        return ""
    # header width only counts when the header prints; numeric columns
    # right-align (RestTable's alignment rules)
    widths = [max(([len(n)] if header else [0])
                  + [len(row[i]) for row in data] + [1])
              for i, n in enumerate(names)]
    num = [all(_NUMERIC.match(row[i]) for row in data if row[i])
           and any(row[i] for row in data)
           for i in range(len(names))]
    out = []
    if header:
        # headers are always left-aligned (the suites anchor ^ on the
        # first header token); only VALUES right-align in numeric columns
        out.append(" ".join(n.ljust(w) for n, w in zip(names, widths))
                   .rstrip() + " \n")
    for row in data:
        # pad through the LAST column too (RestTable pads trailing cells, and
        # the suites' regexes require `\s+` separators around empty values)
        line = " ".join((v.rjust(w) if num[i] else v.ljust(w))
                        for i, (v, w) in enumerate(zip(row, widths)))
        out.append((line.rstrip() if row and row[-1] else line) + " \n")
    return "".join(out)


def human_bytes(n: int) -> str:
    """520 -> "520b", 2048 -> "2kb" (RestTable's ByteSizeValue rendering)."""
    for unit, div in (("pb", 1 << 50), ("tb", 1 << 40), ("gb", 1 << 30),
                      ("mb", 1 << 20), ("kb", 1 << 10)):
        if n >= div:
            v = n / div
            return f"{v:.1f}{unit}" if v < 10 and v != int(v) \
                else f"{int(v)}{unit}"
    return f"{int(n)}b"


def now_cols() -> dict:
    t = int(time.time())
    return {"epoch": t, "timestamp": time.strftime("%H:%M:%S",
                                                   time.gmtime(t))}
