"""Language analysis: stopword lists + light suffix stemmers.

The breadth analog of the reference's ~40 language analyzers
(/root/reference/src/main/java/org/elasticsearch/index/analysis/ — e.g.
FrenchAnalyzerProvider, GermanAnalyzerProvider; Lucene's language packs).
Design choice: LIGHT stemmers (suffix-strip tables in the spirit of the
published "light" stemmer family used by Lucene's *LightStemmer classes)
rather than full Snowball ports — they normalize the common inflectional
morphology that drives recall, in ~10 lines per language, and stay
deterministic across nodes. Stemming is host-side string work; its output
feeds the tensor segment builder like any other analysis chain.
"""

from __future__ import annotations

# -- stopwords (compact high-frequency function-word sets per language) ----

# Lucene's default English stopword set (StandardAnalyzer.STOP_WORDS_SET);
# analyzers.py re-exports this as ENGLISH_STOPWORDS — single source.
_ENGLISH = frozenset(
    "a an and are as at be but by for if in into is it no not of on or "
    "such that the their then there these they this to was will with"
    .split())

STOPWORDS: dict[str, frozenset] = {
    "english": _ENGLISH,
    "french": frozenset(
        "au aux avec ce ces dans de des du elle en et eux il ils je la le "
        "les leur lui ma mais me même mes moi mon ne nos notre nous on ou "
        "par pas pour qu que qui sa se ses son sur ta te tes toi ton tu un "
        "une vos votre vous c d j l à m n s t y été étée étées étés étant "
        "suis es est sommes êtes sont sera serai au".split()),
    "german": frozenset(
        "aber alle als also am an auch auf aus bei bin bis bist da damit "
        "dann das dass dein der den des dem die dies dir doch dort du er es "
        "ein eine einem einen einer eines für hab habe haben hat hatte ich "
        "ihr im in ist ja kann kein können mein mich mir mit muss nach "
        "nicht noch nun nur ob oder ohne sehr sein sich sie sind so über um "
        "und uns unser vom von vor war was wenn werden wie wieder wir wird "
        "zu zum zur".split()),
    "spanish": frozenset(
        "a al algo como con de del desde donde dos el ella ellas ellos en "
        "entre era es esa ese eso esta este esto fue ha hay la las le les "
        "lo los mas me mi mis mucho muy nada ni no nos nosotros o os otra "
        "otro para pero poco por porque que quien se ser si sin sobre son "
        "su sus también te tiene todo tu tus un una uno unos y ya yo"
        .split()),
    "italian": frozenset(
        "a ad al alla alle agli ai anche che chi ci come con cui da dal "
        "dalla de degli dei del della delle di dove e ed era fra gli ha "
        "hanno il in io la le lei lo loro lui ma mi mia mio ne nei nel "
        "nella noi non nostro o per perché più quale quando questa queste "
        "questi questo se sei si sia sono su sua sue sui suo tra tu tua "
        "tuo un una uno vi voi".split()),
    "portuguese": frozenset(
        "a ao aos as às com como da das de do dos e em entre era és foi "
        "há isso isto já la lhe lo mais mas me mesmo meu minha muito na "
        "não nas nem no nos nós o os ou para pela pelo por qual quando que "
        "quem se sem ser seu sua são só também te tem teu tua tudo um uma "
        "você vos".split()),
    "dutch": frozenset(
        "aan al alles als altijd andere ben bij daar dan dat de der deze "
        "die dit doch doen door dus een en er ge geen geweest haar had heb "
        "hebben heeft hem het hier hij hoe hun iemand iets ik in is ja je "
        "kan kon kunnen maar me meer men met mij mijn moet na naar niet "
        "niets nog nu of om omdat onder ons ook op over reeds te tegen toch "
        "toen tot u uit uw van veel voor want waren was wat werd wezen wie "
        "wil worden wordt zal ze zelf zich zij zijn zo zonder zou".split()),
    "russian": frozenset(
        "а без более бы был была были было быть в вам вас весь во вот все "
        "всего всех вы где да даже для до его ее если есть еще же за здесь "
        "и из или им их к как ко когда кто ли либо мне может мы на надо "
        "наш не него нее нет ни них но ну о об однако он она они оно от "
        "очень по под при с со так также такой там те тем то того тоже той "
        "только том ты у уже хотя чего чей чем что чтобы чье чья эта эти "
        "это я".split()),
    "swedish": frozenset(
        "alla allt att av blev bli blir blivit de dem den denna deras dess "
        "det detta dig din dina du där då efter ej eller en er era ett "
        "från för ha hade han hans har henne hennes hon honom hur här i "
        "icke ingen inom inte jag ju kan kunde man med mellan men mig min "
        "mina mot mycket ni nu när någon något några och om oss på samma "
        "sedan sig sin sina sitta skulle som så sådan till under upp ut "
        "utan vad var vara varför varit varje vars vem vi vid vilken än är "
        "åt över".split()),
    "danish": frozenset(
        "af alle andet andre at begge da de den denne der deres det dette "
        "dig din dog du ej eller en end ene eneste enhver et få for fordi "
        "fra ham han hans har hendes her hun hvad hvem hver hvilken hvis "
        "hvor hvordan hvorfor hvornår i ikke ind ingen intet jeg jeres kan "
        "kom kunne man mange med meget men mig mine mit mod ned når nogen "
        "noget nogle nu ny og også om op os over på se sig skal skulle som "
        "sådan thi til ud under var vi vil ville vor være været".split()),
    "norwegian": frozenset(
        "alle at av både båe da de deg dei deim deira dem den denne der "
        "dere deres det dette di din disse ditt du dykk eg ein eit eitt "
        "eller elles en er et ett etter for fordi fra før ha hadde han "
        "hans har hennar henne hennes her hjå ho hun hva hvem hver hvilke "
        "hvis hvor hvordan hvorfor i ikke ingen ja jeg kan kom korleis "
        "kva kvar kvi man mange me med meg men mi min mitt mot mykje nå "
        "når noen noko nokon nokor nokre og også om opp oss over på så "
        "sidan sin sine sitt sjøl skal skulle slik som somme somt til um "
        "upp ut uten var vart varte ved vere verte vi vil ville vore vors "
        "vort være vært".split()),
    "finnish": frozenset(
        "ei eivät emme en et ette että he heidän heidät heihin heille "
        "heillä heiltä heissä heistä heitä hän häneen hänelle hänellä "
        "häneltä hänen hänessä hänestä hänet häntä ja jos joka jotka kuin "
        "kun me meidän meidät meihin meille meillä meiltä meissä meistä "
        "meitä minkä minua minulla minulle minulta minun minussa minusta "
        "minut minuun minä mitä mukaan mutta ne niiden niihin niille "
        "niillä niiltä niin niissä niistä niitä nuo nyt näiden näihin "
        "näille näillä näiltä näissä näistä näitä nämä ole olemme olen "
        "olet olette oli olimme olin olisi olisimme olisin olisit olisitte "
        "olisivat olit olitte olivat olla olleet ollut on ovat se sekä sen "
        "siihen siinä siitä sille sillä siltä sinua sinulla sinulle "
        "sinulta sinun sinussa sinusta sinut sinuun sinä sitä tai te "
        "teidän teidät teihin teille teillä teiltä teissä teistä teitä tuo "
        "tähän tälle tällä tältä tämä tämän tässä tästä tätä vaan vai "
        "vaikka ja".split()),
}

# -- light suffix stemmers --------------------------------------------------
# Longest-match suffix stripping with a minimum-stem guard; tables follow
# the inflectional morphology each language's "light" stemmer targets.

_SUFFIXES: dict[str, list[str]] = {
    "french": ["issements", "issement", "atrices", "atrice", "ateurs",
               "ations", "ateur", "ation", "euses", "ments", "ement",
               "euse", "ence", "esse", "asse", "ant", "ent", "eux", "aux",
               "ier", "ive", "ifs", "es", "er", "ez", "s", "e"],
    "german": ["erinnen", "erin", "heiten", "heit", "keiten", "keit",
               "ungen", "ung", "isch", "ern", "em", "er", "en", "es",
               "e", "s", "n"],
    "spanish": ["amientos", "imientos", "amiento", "imiento", "aciones",
                "adoras", "adores", "ancias", "acion", "ación", "adora",
                "ador", "ancia", "mente", "ible", "able", "istas", "ista",
                "osos", "osas", "oso", "osa", "idad", "iva", "ivo", "es",
                "as", "os", "s", "a", "o", "e"],
    "italian": ["amenti", "imenti", "amento", "imento", "azioni", "azione",
                "atrice", "atore", "mente", "anza", "enza", "ichi", "iche",
                "abili", "ibili", "ista", "iste", "isti", "oso", "osa",
                "osi", "ose", "i", "e", "a", "o"],
    "portuguese": ["amentos", "imentos", "amento", "imento", "adoras",
                   "adores", "aço~es", "ações", "ância", "mente", "idades",
                   "idade", "ismos", "ismo", "istas", "ista", "osos",
                   "osas", "oso", "osa", "es", "as", "os", "s", "a", "o",
                   "e"],
    "dutch": ["heden", "heid", "ingen", "ing", "eren", "en", "e", "s"],
    "russian": ["иями", "иях", "ями", "ами", "ием", "иям", "ием", "ого",
                "ому", "ыми", "его", "ему", "ими", "ов", "ев", "ей", "ий",
                "ый", "ой", "ая", "яя", "ое", "ее", "ие", "ые", "ом", "ем",
                "ам", "ям", "ах", "ях", "ую", "юю", "а", "я", "о", "е",
                "и", "ы", "у", "ю", "й", "ь"],
    "swedish": ["heterna", "heten", "heter", "arna", "erna", "orna", "ande",
                "ende", "aste", "ast", "are", "en", "ar", "er", "or", "et",
                "a", "e", "t", "s"],
    "danish": ["erende", "hederne", "heden", "heder", "erne", "erer",
               "ende", "erne", "ede", "er", "en", "et", "e", "s"],
    "norwegian": ["hetene", "heten", "heter", "ende", "ande", "else",
                  "ene", "ane", "ede", "er", "en", "et", "ar", "a", "e"],
    "finnish": ["isuuksien", "isuuden", "isuus", "uksen", "ukset", "inen",
                "isen", "iset", "ista", "istä", "ssa", "ssä", "sta", "stä",
                "lla", "llä", "lta", "ltä", "lle", "ksi", "in", "en", "an",
                "än", "at", "ät", "a", "ä", "n", "t"],
    # the remaining members of the reference's language-analyzer roster,
    # each a published-light-stemmer-style suffix table (Lucene's
    # *LightStemmer family): common inflectional morphology only
    "arabic": ["ها", "ان", "ات", "ون", "ين", "يه", "ية", "ه", "ة", "ي"],
    "bulgarian": ["ията", "ият", "ите", "ето", "ата", "ото", "та", "то",
                  "ят", "ия", "а", "я", "о", "е"],
    "catalan": ["aments", "ament", "ques", "es", "os", "or", "a", "e", "o",
                "s"],
    "czech": ["atech", "atům", "ých", "ami", "emi", "ého", "ému", "ích",
              "ími", "ách", "ata", "aty", "ové", "ovi", "ými", "em", "es",
              "ém", "ím", "ám", "os", "us", "ým", "mi", "ou", "ů", "e",
              "i", "í", "ě", "u", "y", "a", "o", "á", "é", "ý"],
    "greek": ["ματος", "ματα", "οντας", "ωντας", "ες", "ος", "ης", "ου",
              "ων", "ας", "ής", "ού", "ών", "α", "η", "ι", "ο", "ς"],
    "hindi": ["ियों", "ियाँ", "ियां", "ाओं", "ाएँ", "ुओं", "ुएँ", "ओं", "एँ",
              "ें", "ों", "ीं", "ाँ", "ां", "ो", "े", "ू", "ु", "ी", "ि", "ा"],
    "hungarian": ["okkal", "ekkel", "akkal", "nak", "nek", "val", "vel",
                  "ban", "ben", "ból", "ből", "hoz", "hez", "nál", "nél",
                  "ról", "ről", "tól", "től", "ok", "ek", "ak", "ai", "ei",
                  "át", "et", "ot", "a", "e", "i", "o", "ó", "ő", "t", "k"],
    "indonesian": ["kannya", "kanlah", "annya", "kan", "an", "nya", "lah",
                   "kah", "i"],
    "irish": ["acha", "anna", "ach", "aí", "í"],
    "latvian": ["ajiem", "ajām", "iem", "ajā", "ām", "ās", "am", "as",
                "ies", "em", "es", "is", "us", "ai", "ei", "u", "s", "a",
                "e", "i"],
    "persian": ["هایی", "های", "ترین", "ها", "ات", "ان", "تر", "ی"],
    "romanian": ["urile", "ilor", "ului", "elor", "uri", "ul", "ile", "ea",
                 "le", "lor", "ii", "iei", "ie", "ei", "a", "i"],
    "turkish": ["larının", "lerinin", "ların", "lerin", "ları", "leri",
                "lar", "ler", "dan", "den", "tan", "ten", "da", "de", "ta",
                "te", "ın", "in", "un", "ün", "ı", "i", "u", "ü", "a", "e"],
    "armenian": ["ները", "ներին", "ների", "երի", "ներ", "եր", "ում", "ը",
                 "ի", "ն"],
    "basque": ["etako", "etan", "ak", "ek", "en", "ra", "an", "a", "k"],
    "sorani": ["ەکان", "ەکە", "یان", "مان", "تان", "ان", "ەی", "ی", "ە"],
    "galician": ["amentos", "amento", "cións", "ción", "eiras", "eiros",
                 "eira", "eiro", "anza", "ois", "áns", "es", "ns", "s",
                 "a", "o", "e"],
    "brazilian": ["amentos", "amento", "adores", "ações", "ância", "agem",
                  "mente", "idade", "ção", "ções", "ista", "ismo", "oso",
                  "osa", "eza", "es", "os", "as", "a", "o", "e", "s"],
}

_MIN_STEM = {"russian": 3, "finnish": 3, "arabic": 3,
             "hindi": 2, "persian": 3, "sorani": 3,
             "greek": 3, "armenian": 3, "hungarian": 3,
             "czech": 3, "turkish": 3, "latvian": 3,
             "bulgarian": 3}


def light_stem(lang: str, word: str) -> str:
    """Strip matching suffixes to a FIXPOINT, keeping a minimum stem.
    Fixpoint matters for index/query symmetry: a single pass maps
    "kapıları"->"kapı" but the query "kapı"->"kap" — different terms for
    the same lemma and recall silently drops to zero. Iterating until no
    suffix applies makes stemming idempotent, so both sides of the match
    land on the same term."""
    min_stem = _MIN_STEM.get(lang, 4)
    sufs = _SUFFIXES.get(lang, ())
    while True:
        for suf in sufs:
            if word.endswith(suf) and len(word) - len(suf) >= min_stem:
                word = word[: -len(suf)]
                break
        else:
            return word


def make_light_stemmer(lang: str):
    def f(tokens):
        return [light_stem(lang, t) for t in tokens]
    f.__name__ = f"{lang}_light_stem"
    # per-token map (no cross-token state): the batched ingest lane may
    # apply it over a bulk's unique vocabulary (analyzers.per_token contract)
    f.per_token = True
    return f


# -- CJK bigrams ------------------------------------------------------------

def cjk_bigram(tokens):
    """Han/Hiragana/Katakana/Hangul runs re-emitted as overlapping bigrams
    (ref Lucene CJKAnalyzer): the standard unigram-ambiguity workaround
    for unsegmented scripts."""
    out = []
    for t in tokens:
        if len(t) >= 2 and any("⺀" <= c <= "鿿"
                               or "぀" <= c <= "ヿ"
                               or "가" <= c <= "힯" for c in t):
            out.extend(t[i:i + 2] for i in range(len(t) - 1))
        else:
            out.append(t)
    return out


# each token expands independently into its bigrams — per-token contract
cjk_bigram.per_token = True

LANGUAGES = sorted(set(STOPWORDS) | set(_SUFFIXES))
