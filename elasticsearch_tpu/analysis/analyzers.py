"""Text analysis: tokenizers → token filters → analyzers.

CPU-side analog of the reference analysis registry
(/root/reference/src/main/java/org/elasticsearch/index/analysis/AnalysisModule.java,
AnalysisService.java; SURVEY.md §2.4 "Analysis"): a registry of named
tokenizers/filters/analyzers plus per-index custom chains built from settings.
Analysis runs on host (it is string processing, not tensor work); its output
feeds the tensor segment builder in index/segment.py.
"""

from __future__ import annotations

import re
import unicodedata
from dataclasses import dataclass, field
from typing import Callable, Iterable

Token = str
Tokenizer = Callable[[str], list[Token]]
TokenFilter = Callable[[list[Token]], list[Token]]


def per_token(f):
    """Mark a token filter as PER-TOKEN: its output is the concatenation of
    `f([t])` over the input tokens — no cross-token state (order, adjacency,
    dedup). The batched ingest lane (index/bulk_ingest.py) applies chains of
    per-token filters over a bulk request's *unique* vocabulary once instead
    of per occurrence; unmarked filters (shingle, synonym, decompounder,
    unique) force the per-doc fallback so semantics never change."""
    f.per_token = True
    return f


# per-doc Analyzer.analyze invocations — the batched ingest lane's tripwire
# counter (tests assert a ZERO delta across a vectorized _bulk; the whole
# point of the batch lane is that this stays off the per-doc path)
_ANALYZE_CALLS = [0]


def analyze_call_count() -> int:
    return _ANALYZE_CALLS[0]

# ---------------------------------------------------------------------------
# Tokenizers (ref: index/analysis/StandardTokenizerFactory.java etc.)
# ---------------------------------------------------------------------------

_WORD_RE = re.compile(r"[\w][\w'’]*", re.UNICODE)
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)


def standard_tokenizer(text: str) -> list[Token]:
    """Unicode word-boundary tokenizer (approximation of Lucene's
    StandardTokenizer / UAX#29): splits on non-word chars, keeps interior
    apostrophes, strips possessive 's."""
    toks = []
    for m in _WORD_RE.finditer(text):
        t = m.group(0).replace("’", "'")
        if t.endswith("'s") or t.endswith("'S"):
            t = t[:-2]
        t = t.strip("'")
        if t:
            toks.append(t)
    return toks


def whitespace_tokenizer(text: str) -> list[Token]:
    return text.split()


def letter_tokenizer(text: str) -> list[Token]:
    return _LETTER_RE.findall(text)


def keyword_tokenizer(text: str) -> list[Token]:
    return [text] if text else []


def _ngram(text: str, lo: int, hi: int, edge: bool) -> list[Token]:
    out = []
    n = len(text)
    if edge:
        for g in range(lo, min(hi, n) + 1):
            out.append(text[:g])
    else:
        for g in range(lo, hi + 1):
            for i in range(0, n - g + 1):
                out.append(text[i:i + g])
    return out


def ngram_tokenizer(text: str, min_gram: int = 1, max_gram: int = 2) -> list[Token]:
    return _ngram(text, min_gram, max_gram, edge=False)


def edge_ngram_tokenizer(text: str, min_gram: int = 1, max_gram: int = 8) -> list[Token]:
    return _ngram(text, min_gram, max_gram, edge=True)


# ---------------------------------------------------------------------------
# Token filters
# ---------------------------------------------------------------------------

# Lucene's default English stopword set — one source (languages.py).
from .languages import _ENGLISH as ENGLISH_STOPWORDS  # noqa: E402


@per_token
def lowercase_filter(tokens: list[Token]) -> list[Token]:
    return [t.lower() for t in tokens]


@per_token
def uppercase_filter(tokens: list[Token]) -> list[Token]:
    return [t.upper() for t in tokens]


@per_token
def stop_filter(tokens: list[Token], stopwords: frozenset[str] = ENGLISH_STOPWORDS) -> list[Token]:
    return [t for t in tokens if t not in stopwords]


@per_token
def asciifolding_filter(tokens: list[Token]) -> list[Token]:
    out = []
    for t in tokens:
        folded = unicodedata.normalize("NFKD", t).encode("ascii", "ignore").decode("ascii")
        out.append(folded if folded else t)
    return out


@per_token
def trim_filter(tokens: list[Token]) -> list[Token]:
    return [t.strip() for t in tokens]


def unique_filter(tokens: list[Token]) -> list[Token]:
    seen, out = set(), []
    for t in tokens:
        if t not in seen:
            seen.add(t)
            out.append(t)
    return out


@per_token
def length_filter(tokens: list[Token], min_len: int = 0, max_len: int = 1 << 30) -> list[Token]:
    return [t for t in tokens if min_len <= len(t) <= max_len]


def shingle_filter(tokens: list[Token], min_size: int = 2, max_size: int = 2,
                   output_unigrams: bool = True, sep: str = " ") -> list[Token]:
    out = list(tokens) if output_unigrams else []
    for size in range(min_size, max_size + 1):
        for i in range(len(tokens) - size + 1):
            out.append(sep.join(tokens[i:i + size]))
    return out


# --- Porter stemmer (english analyzer; ref index/analysis/StemmerTokenFilterFactory.java)

_VOWELS = "aeiou"


def _is_cons(word: str, i: int) -> bool:
    c = word[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    m, prev_c = 0, True
    started = False
    for i in range(len(stem)):
        c = _is_cons(stem, i)
        if not c:
            started = True
        elif started and not prev_c:
            m += 1
        prev_c = c
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(w: str) -> bool:
    return len(w) >= 2 and w[-1] == w[-2] and _is_cons(w, len(w) - 1)


def _cvc(w: str) -> bool:
    if len(w) < 3:
        return False
    return (_is_cons(w, len(w) - 3) and not _is_cons(w, len(w) - 2)
            and _is_cons(w, len(w) - 1) and w[-1] not in "wxy")


def porter_stem(w: str) -> str:
    """Porter stemming algorithm (Porter, 1980) — classic 5-step rules."""
    if len(w) <= 2:
        return w
    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]
    # step 1b
    flag = False
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed") and _has_vowel(w[:-2]):
        w, flag = w[:-2], True
    elif w.endswith("ing") and _has_vowel(w[:-3]):
        w, flag = w[:-3], True
    if flag:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif _ends_double_cons(w) and w[-1] not in "lsz":
            w = w[:-1]
        elif _measure(w) == 1 and _cvc(w):
            w += "e"
    # step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # step 2
    for suf, rep in (("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
                     ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
                     ("alli", "al"), ("entli", "ent"), ("eli", "e"),
                     ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
                     ("ator", "ate"), ("alism", "al"), ("iveness", "ive"),
                     ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"),
                     ("iviti", "ive"), ("biliti", "ble")):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break
    # step 3
    for suf, rep in (("icate", "ic"), ("ative", ""), ("alize", "al"),
                     ("iciti", "ic"), ("ical", "ic"), ("ful", ""), ("ness", "")):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break
    # step 4
    for suf in ("al", "ance", "ence", "er", "ic", "able", "ible", "ant",
                "ement", "ment", "ent", "ou", "ism", "ate", "iti", "ous",
                "ive", "ize"):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 1:
                w = w[:-len(suf)]
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st" and _measure(w[:-3]) > 1:
            w = w[:-3]
    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        if _measure(stem) > 1 or (_measure(stem) == 1 and not _cvc(stem)):
            w = stem
    # step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w


@per_token
def porter_stem_filter(tokens: list[Token]) -> list[Token]:
    return [porter_stem(t) for t in tokens]


# --- synonyms (ref index/analysis/SynonymTokenFilterFactory.java) ----------

class SynonymFilter:
    """Solr-format synonym rules:
         "a, b => c"   mapping: a or b rewrite to c
         "x, y, z"     equivalence class: each emits the whole class
    Multi-token synonyms are matched on SINGLE input tokens only (phrase
    synonyms are out of scope; the reference supports them via its token
    graph, a tokenizer-level feature)."""

    def __init__(self, rules: list[str], expand: bool = True):
        self.map: dict[str, list[str]] = {}
        for rule in rules or []:
            rule = str(rule).strip()
            if not rule or rule.startswith("#"):
                continue
            if "=>" in rule:
                lhs, rhs = rule.split("=>", 1)
                targets = [t.strip() for t in rhs.split(",") if t.strip()]
                for src in (s.strip() for s in lhs.split(",")):
                    if src:
                        self.map.setdefault(src, []).extend(
                            t for t in targets
                            if t not in self.map.get(src, []))
            else:
                cls = [t.strip() for t in rule.split(",") if t.strip()]
                for src in cls:
                    outs = cls if expand else cls[:1]
                    self.map.setdefault(src, []).extend(
                        t for t in outs if t not in self.map.get(src, []))

    def __call__(self, tokens: list[Token]) -> list[Token]:
        # mapping rules REPLACE the source (targets exclude it);
        # equivalence classes EXPAND it (targets include it)
        out: list[Token] = []
        for t in tokens:
            out.extend(self.map.get(t, (t,)))
        return out


# --- compound words (ref DictionaryCompoundWordTokenFilterFactory) ---------

class DictionaryDecompounder:
    """Emits the original token plus any dictionary subwords found inside
    it (greedy substring scan; min/max subword lengths per the reference
    factory's defaults)."""

    def __init__(self, word_list: list[str], min_subword_size: int = 2,
                 max_subword_size: int = 15, only_longest_match: bool = False):
        self.words = {w.lower() for w in word_list or []}
        self.min_sub = min_subword_size
        self.max_sub = max_subword_size
        self.only_longest = only_longest_match

    def __call__(self, tokens: list[Token]) -> list[Token]:
        out = []
        for t in tokens:
            out.append(t)
            low = t.lower()
            found = []
            for i in range(len(low)):
                for j in range(i + self.min_sub,
                               min(len(low), i + self.max_sub) + 1):
                    if low[i:j] in self.words and low[i:j] != low:
                        found.append(low[i:j])
            if found and self.only_longest:
                found = [max(found, key=len)]
            out.extend(found)
        return out


# --- elision (l'avion -> avion; ref ElisionTokenFilterFactory) -------------

_DEFAULT_ELISION = ("l", "m", "t", "qu", "n", "s", "j", "d", "c",
                    "jusqu", "quoiqu", "lorsqu", "puisqu")


def make_elision_filter(articles=None):
    arts = tuple(articles) if articles else _DEFAULT_ELISION

    def f(tokens):
        out = []
        for t in tokens:
            for a in arts:
                if t.lower().startswith(a + "'"):
                    t = t[len(a) + 1:]
                    break
            if t:
                out.append(t)
        return out
    return per_token(f)


# ---------------------------------------------------------------------------
# Analyzers and the registry
# ---------------------------------------------------------------------------

@dataclass
class Analyzer:
    name: str
    tokenizer: Tokenizer
    filters: list[TokenFilter] = field(default_factory=list)

    def analyze(self, text: str) -> list[Token]:
        _ANALYZE_CALLS[0] += 1
        if text is None:
            return []
        tokens = self.tokenizer(str(text))
        for f in self.filters:
            tokens = f(tokens)
        return tokens

    __call__ = analyze


def _std(name: str, *filters: TokenFilter) -> Analyzer:
    return Analyzer(name, standard_tokenizer, list(filters))


BUILTIN_ANALYZERS: dict[str, Analyzer] = {
    "standard": _std("standard", lowercase_filter),
    "simple": Analyzer("simple", letter_tokenizer, [lowercase_filter]),
    "whitespace": Analyzer("whitespace", whitespace_tokenizer),
    "keyword": Analyzer("keyword", keyword_tokenizer),
    "stop": Analyzer("stop", letter_tokenizer, [lowercase_filter, stop_filter]),
    "english": _std("english", lowercase_filter, stop_filter, porter_stem_filter),
}


def _register_language_analyzers() -> None:
    """Language analyzers (ref the per-language *AnalyzerProvider classes):
    lowercase -> language stopwords -> light stemmer (+ elision for
    french/italian; cjk uses bigrams)."""
    from .languages import (STOPWORDS, cjk_bigram, make_light_stemmer)

    def stop_for(lang):
        sw = STOPWORDS.get(lang)
        if sw is None:
            return None
        return per_token(lambda toks: [t for t in toks if t not in sw])

    from .languages import LANGUAGES
    for lang in LANGUAGES:
        if lang == "english":
            continue                 # "english" is the default chain
        filters = [lowercase_filter]
        if lang in ("french", "italian"):
            filters.append(make_elision_filter())
        elif lang == "catalan":       # Lucene CatalanAnalyzer elision set
            filters.append(make_elision_filter(("d", "l", "m", "n", "s",
                                                "t")))
        elif lang == "irish":         # Lucene IrishAnalyzer elision set
            filters.append(make_elision_filter(("d", "m", "b")))
        sf = stop_for(lang)
        if sf is not None:
            filters.append(sf)
        filters.append(make_light_stemmer(lang))
        BUILTIN_ANALYZERS[lang] = Analyzer(lang, standard_tokenizer, filters)
    BUILTIN_ANALYZERS["cjk"] = Analyzer("cjk", standard_tokenizer,
                                        [lowercase_filter, cjk_bigram])
    # the reference's ChineseAnalyzerProvider delegates to the standard
    # chain (Lucene deprecated ChineseAnalyzer); CJK bigrams serve better
    BUILTIN_ANALYZERS["chinese"] = BUILTIN_ANALYZERS["cjk"]


_register_language_analyzers()

_TOKENIZERS: dict[str, Tokenizer] = {
    "standard": standard_tokenizer,
    "whitespace": whitespace_tokenizer,
    "letter": letter_tokenizer,
    "keyword": keyword_tokenizer,
    "ngram": ngram_tokenizer,
    "nGram": ngram_tokenizer,
    "edge_ngram": edge_ngram_tokenizer,
    "edgeNGram": edge_ngram_tokenizer,
}

_FILTERS: dict[str, TokenFilter] = {
    "lowercase": lowercase_filter,
    "uppercase": uppercase_filter,
    "stop": stop_filter,
    "asciifolding": asciifolding_filter,
    "trim": trim_filter,
    "unique": unique_filter,
    "porter_stem": porter_stem_filter,
    "stemmer": porter_stem_filter,
    "snowball": porter_stem_filter,
    "shingle": shingle_filter,
    "elision": make_elision_filter(),
}


def _filter_factory(ftype: str, params: dict) -> TokenFilter:
    """Build a PARAMETERIZED token filter from its settings definition
    (ref index.analysis.filter.<name>.{type, ...} — AnalysisModule's
    TokenFilterFactory registry)."""
    from .languages import STOPWORDS, cjk_bigram, make_light_stemmer

    if ftype == "synonym":
        rules = params.get("synonyms") or []
        if isinstance(rules, str):
            rules = [rules]
        return SynonymFilter(rules, expand=params.get("expand", True)
                             not in (False, "false"))
    if ftype in ("dictionary_decompounder", "hyphenation_decompounder"):
        return DictionaryDecompounder(
            params.get("word_list") or [],
            min_subword_size=int(params.get("min_subword_size", 2)),
            max_subword_size=int(params.get("max_subword_size", 15)),
            only_longest_match=params.get("only_longest_match")
            in (True, "true"))
    if ftype in ("stemmer", "snowball", "light_stemmer"):
        lang = str(params.get("language", params.get("name",
                                                     "english"))).lower()
        if lang in ("english", "porter", "porter2", "minimal_english",
                    "light_english"):
            return porter_stem_filter
        base = lang.replace("light_", "").replace("minimal_", "")
        return make_light_stemmer(base)
    if ftype == "stop":
        sw = params.get("stopwords", "_english_")
        if isinstance(sw, str):
            lang = sw.strip("_")
            if lang == "none":
                sw = frozenset()      # explicit "keep everything"
            else:
                sw = STOPWORDS.get(lang, ENGLISH_STOPWORDS)
        sw = frozenset(str(x) for x in sw)
        return per_token(lambda toks: [t for t in toks if t not in sw])
    if ftype == "shingle":
        return lambda toks: shingle_filter(
            toks, min_size=int(params.get("min_shingle_size", 2)),
            max_size=int(params.get("max_shingle_size", 2)),
            output_unigrams=params.get("output_unigrams", True)
            not in (False, "false"))
    if ftype == "length":
        lo = int(params.get("min", 0))
        hi = int(params.get("max", 1 << 30))
        return per_token(lambda toks: length_filter(toks, lo, hi))
    if ftype in ("ngram", "nGram"):
        lo = int(params.get("min_gram", 1))
        hi = int(params.get("max_gram", 2))
        return per_token(lambda toks: [g for t in toks
                                       for g in _ngram(t, lo, hi,
                                                       edge=False)])
    if ftype in ("edge_ngram", "edgeNGram"):
        lo = int(params.get("min_gram", 1))
        hi = int(params.get("max_gram", 8))
        return per_token(lambda toks: [g for t in toks
                                       for g in _ngram(t, lo, hi,
                                                       edge=True)])
    if ftype == "elision":
        return make_elision_filter(params.get("articles"))
    if ftype == "cjk_bigram":
        return cjk_bigram
    f = _FILTERS.get(ftype)
    if f is not None:
        return f
    raise ValueError(f"unknown token filter type [{ftype}]")


class AnalysisService:
    """Per-index analyzer registry: builtins + custom chains from settings.

    Custom analyzers follow the reference settings schema
    (index.analysis.analyzer.<name>.{type,tokenizer,filter}), see
    /root/reference/src/main/java/org/elasticsearch/index/analysis/AnalysisService.java.
    """

    def __init__(self, index_settings=None):
        self._analyzers = dict(BUILTIN_ANALYZERS)
        if index_settings is not None:
            self._build_custom(index_settings)

    def _build_custom(self, settings) -> None:
        from ..common.settings import Settings

        if not isinstance(settings, Settings):
            settings = Settings(settings)

        # 1. named CUSTOM FILTER definitions with parameters
        #    (index.analysis.filter.<name>.{type, synonyms, language, ...})
        # Build errors are RECORDED, not raised: an unsupported filter type
        # must not brick node recovery of an existing index — create_index
        # checks build_errors and rejects new indices loudly instead.
        self.build_errors: list[str] = []
        self._custom_filters: dict[str, TokenFilter] = {}
        fdefs = settings.by_prefix("index.analysis.filter.")
        for name in {k.split(".")[0] for k in fdefs}:
            sub = fdefs.by_prefix(name + ".")
            params = {k: sub.get(k) for k in sub
                      if not k.split(".")[-1].isdigit()}
            for lp in ("synonyms", "word_list", "articles", "stopwords"):
                raw = sub.get(lp)
                if isinstance(raw, (list, tuple)):
                    params[lp] = list(raw)
                elif raw is None:       # flat numbered keys (syn.0, syn.1)
                    lv = sub.get_list(lp)
                    if lv is not None:
                        params[lp] = lv
                # scalar strings pass through UNSPLIT ("a, b => c" is one
                # synonym rule; "_french_" is one language marker)
            ftype = str(params.pop("type", name))
            try:
                self._custom_filters[name] = _filter_factory(ftype, params)
            except Exception as e:  # noqa: BLE001 — recovery must not die
                self.build_errors.append(
                    f"filter [{name}]: {type(e).__name__}: {e}")

        # 2. named custom TOKENIZER definitions (ngram params etc.)
        self._custom_tokenizers: dict[str, Tokenizer] = {}
        tdefs = settings.by_prefix("index.analysis.tokenizer.")
        for name in {k.split(".")[0] for k in tdefs}:
            sub = tdefs.by_prefix(name + ".")
            ttype = sub.get_str("type", name)
            if ttype in ("ngram", "nGram", "edge_ngram", "edgeNGram"):
                lo = int(sub.get("min_gram", 1))
                hi = int(sub.get("max_gram",
                                 2 if "edge" not in ttype.lower()
                                 and "Edge" not in ttype else 8))
                edge = "edge" in ttype.lower() or ttype == "edgeNGram"
                self._custom_tokenizers[name] = \
                    (lambda lo=lo, hi=hi, edge=edge:
                     lambda text: _ngram(text, lo, hi, edge))()
            elif ttype in _TOKENIZERS:
                self._custom_tokenizers[name] = _TOKENIZERS[ttype]

        # 3. analyzer chains referencing builtins or the custom components
        custom = settings.by_prefix("index.analysis.analyzer.")
        names = {k.split(".")[0] for k in custom}
        for name in names:
            sub = custom.by_prefix(name + ".")
            atype = sub.get_str("type", "custom")
            if atype != "custom" and atype in BUILTIN_ANALYZERS:
                self._analyzers[name] = BUILTIN_ANALYZERS[atype]
                continue
            tok_name = sub.get_str("tokenizer", "standard")
            tokenizer = self._custom_tokenizers.get(tok_name) \
                or _TOKENIZERS.get(tok_name)
            if tokenizer is None:
                self.build_errors.append(
                    f"analyzer [{name}]: unknown tokenizer [{tok_name}]")
                continue
            filters = []
            broken = None
            for fname in sub.get_list("filter", []) or []:
                f = self._custom_filters.get(fname) or _FILTERS.get(fname)
                if f is None:
                    try:
                        f = _filter_factory(fname, {})
                    except ValueError:
                        broken = fname
                        break
                filters.append(f)
            if broken is not None:
                self.build_errors.append(
                    f"analyzer [{name}]: unknown token filter [{broken}]")
                continue
            self._analyzers[name] = Analyzer(name, tokenizer, filters)

    def analyzer(self, name: str) -> Analyzer:
        a = self._analyzers.get(name)
        if a is None:
            raise ValueError(f"unknown analyzer [{name}]")
        return a

    def custom(self, tokenizer: str, filters: list[str]) -> Analyzer:
        """Ad-hoc chain for the _analyze API's tokenizer/filters params
        (ref rest/action/admin/indices/analyze/RestAnalyzeAction)."""
        tok = _TOKENIZERS.get(tokenizer)
        if tok is None:
            raise ValueError(f"unknown tokenizer [{tokenizer}]")
        fs = []
        for fname in filters or []:
            f = _FILTERS.get(fname)
            if f is None:
                raise ValueError(f"unknown token filter [{fname}]")
            fs.append(f)
        return Analyzer("_custom", tok, fs)

    def default_analyzer(self) -> Analyzer:
        return self._analyzers.get("default", self._analyzers["standard"])

    def names(self) -> Iterable[str]:
        return self._analyzers.keys()
