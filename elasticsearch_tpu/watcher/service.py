"""WatcherService: registry + scheduler + alert writer (ISSUE 20
tentpole; ref Watcher's WatchStore/.watches index, ExecutionService,
TriggeredWatchStore, SURVEY §7).

Persistence: watches live in an internal single-shard `.watches` index
(one doc per watch: the body plus its runtime status) and are re-parsed
from it at node construction — a watch registered before a restart is
armed after it, exactly like the reference's WatchStore recovery scan.

Document watches are compiled into the PR-18 percolator registry of the
CURRENT `.monitoring-es-*` index as `.percolator` docs with reserved
`_watch_<id>` ids: the monitoring collector calls
`percolate_collector_batch` with the SAME docs list it just bulked, so
the whole tick is percolated as ONE dense doc×query matrix program —
one extra query column per watch, one device fetch per batch, zero
extra fetches. Registrations are re-applied when the rolling index name
changes (daily rollover), so the ride survives ILM.

Aggregation watches are evaluated by a scheduler thread (`interval_s <=
0` skips the thread — tests drive `run_due()` directly, the same
convention as MonitoringCollector): the stored search request runs
through `node.search` (composite + pipeline aggs now being first-class
there) under a `watch` tracer root, the condition is applied to the
response, and a firing appends an alert document to the rolling
`.alerts-es-YYYY.MM.DD` index via the vectorized bulk lane with the
same ILM-lite rollover/retention discipline as monitoring.

Throttling/ack (ref Watcher's ack/throttle): a fired watch stays quiet
for `throttle_period` (per-watch, default `watcher.throttle_period`);
an acked watch never fires until its condition goes false once, which
auto-unacks it.
"""

from __future__ import annotations

import threading
import time

from .watch import Watch, WatchParsingException, parse_watch, condition_met

WATCHES_INDEX = ".watches"
ALERTS_PREFIX = ".alerts-es-"
ENABLE_SETTING = "watcher.enable"
INTERVAL_SETTING = "watcher.interval"
THROTTLE_SETTING = "watcher.throttle_period"
RETENTION_SETTING = "watcher.alerts.retention_days"

_WATCH_DOC_PREFIX = "_watch_"       # reserved percolator-registry ids

WATCHES_SETTINGS = {"number_of_shards": 1, "number_of_replicas": 0}
ALERTS_SETTINGS = {"number_of_shards": 1, "number_of_replicas": 0}
ALERTS_MAPPING = {"_doc": {"properties": {
    "@timestamp": {"type": "date"},
    "watch_id": {"type": "string", "index": "not_analyzed"},
    "kind": {"type": "string", "index": "not_analyzed"},
    "state": {"type": "string", "index": "not_analyzed"},
}}}


class WatchMissingException(Exception):
    pass


def _enabled(settings) -> bool:
    v = settings.get(ENABLE_SETTING, True)
    if isinstance(v, str):
        return v.strip().lower() in ("true", "1", "yes", "on")
    return bool(v)


class WatcherService:

    def __init__(self, node, interval_s: float = 1.0,
                 default_throttle_s: float = 10.0,
                 retention_days: int = 3, clock=None):
        self.node = node
        self.interval_s = float(interval_s)
        self.default_throttle_s = float(default_throttle_s)
        self.retention_days = int(retention_days)
        self._clock = clock or time.time
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.watches: dict[str, Watch] = {}
        self.stats = {"evaluations_total": 0, "fires_total": 0,
                      "throttled_total": 0, "errors_total": 0,
                      "percolate_rides_total": 0, "alerts_indexed_total": 0,
                      "retention_deletes_total": 0}
        # monitoring-index name -> watch ids whose percolator query is
        # registered there (rollover re-registers into the new index)
        self._registered: dict[str, set[str]] = {}
        self._recover()

    @classmethod
    def from_settings(cls, node):
        """None when `watcher.enable: false` — otherwise always built
        (the scheduler thread only starts once an aggregation watch
        exists, so idle nodes pay nothing)."""
        if not _enabled(node.settings):
            return None

        def _num(key, default, cast):
            try:
                return cast(node.settings.get(key, default))
            except (TypeError, ValueError):
                return cast(default)

        from .watch import duration_secs
        interval = duration_secs(node.settings.get(INTERVAL_SETTING), 1.0)
        throttle = duration_secs(node.settings.get(THROTTLE_SETTING), 10.0)
        retention = _num(RETENTION_SETTING, 3, int)
        return cls(node, interval_s=interval, default_throttle_s=throttle,
                   retention_days=retention)

    # -- registry persistence / recovery ------------------------------------

    def _recover(self) -> None:
        """Re-arm watches from the `.watches` registry index (ref
        WatchStore.start scan-and-parse)."""
        node = self.node
        if WATCHES_INDEX not in node.indices:
            return
        try:
            node.indices[WATCHES_INDEX].refresh()
            resp = node.search(WATCHES_INDEX,
                               {"size": 10000,
                                "query": {"match_all": {}}})
        except Exception:  # noqa: BLE001 — recovery must not kill boot
            self.stats["errors_total"] += 1
            return
        for hit in resp.get("hits", {}).get("hits", []):
            src = hit.get("_source") or {}
            body = src.get("watch")
            if not isinstance(body, dict):
                continue
            try:
                w = parse_watch(hit["_id"], body, self.default_throttle_s)
            except WatchParsingException:
                self.stats["errors_total"] += 1
                continue
            st = src.get("state") or {}
            w.acked = bool(st.get("acked", False))
            w.last_fire_ms = int(st.get("last_fire_ms", 0) or 0)
            w.fires_total = int(st.get("fires_total", 0) or 0)
            self.watches[w.watch_id] = w
        if self.watches:
            self._maybe_start()

    def _persist(self, w: Watch) -> None:
        node = self.node
        if WATCHES_INDEX not in node.indices:
            node.create_index(WATCHES_INDEX, dict(WATCHES_SETTINGS))
        node.index_doc(WATCHES_INDEX, w.watch_id,
                       {"watch": w.body,
                        "state": {"acked": w.acked,
                                  "last_fire_ms": w.last_fire_ms,
                                  "fires_total": w.fires_total}})

    # -- CRUD ---------------------------------------------------------------

    def put_watch(self, watch_id: str, body: dict) -> dict:
        w = parse_watch(watch_id, body, self.default_throttle_s)
        with self._lock:
            created = watch_id not in self.watches
            old = self.watches.get(watch_id)
            self.watches[watch_id] = w
            if old is not None and old.kind == "document":
                # a replaced query must not keep matching under its old form
                self._unregister(watch_id)
        self._persist(w)
        if w.kind == "document":
            mon = getattr(self.node, "monitoring", None)
            if mon is not None and mon.current_index:
                self.ensure_percolator_registrations(mon.current_index)
        self._maybe_start()
        return {"_id": watch_id, "created": created}

    def get_watch(self, watch_id: str) -> dict:
        with self._lock:
            w = self.watches.get(watch_id)
        if w is None:
            raise WatchMissingException(watch_id)
        return {"found": True, "_id": watch_id, "watch": w.body,
                "status": w.status()}

    def delete_watch(self, watch_id: str) -> dict:
        with self._lock:
            w = self.watches.pop(watch_id, None)
        if w is None:
            raise WatchMissingException(watch_id)
        self._unregister(watch_id)
        if WATCHES_INDEX in self.node.indices:
            try:
                self.node.delete_doc(WATCHES_INDEX, watch_id)
            except Exception:  # noqa: BLE001
                self.stats["errors_total"] += 1
        return {"found": True, "_id": watch_id}

    def ack_watch(self, watch_id: str) -> dict:
        with self._lock:
            w = self.watches.get(watch_id)
            if w is None:
                raise WatchMissingException(watch_id)
            w.acked = True
        self._persist(w)
        return {"_id": watch_id, "status": w.status()}

    # -- document watches: the percolator ride ------------------------------

    def _document_watches(self) -> list[Watch]:
        with self._lock:
            return [w for w in self.watches.values()
                    if w.kind == "document"]

    def ensure_percolator_registrations(self, index_name: str) -> int:
        """Idempotently register every document watch's query as a
        `.percolator` doc in `index_name`; called by the collector each
        tick so daily rollover re-arms the dense matrix columns."""
        node = self.node
        if index_name not in node.indices:
            return 0
        reg = self._registered.setdefault(index_name, set())
        # prune state for rolled/retired indices
        for stale in [n for n in self._registered if n not in node.indices]:
            self._registered.pop(stale, None)
        added = 0
        for w in self._document_watches():
            if w.watch_id in reg:
                continue
            node.index_doc(index_name,
                           _WATCH_DOC_PREFIX + w.watch_id,
                           {"query": w.percolate_query},
                           type_name=".percolator")
            reg.add(w.watch_id)
            added += 1
        return added

    def _unregister(self, watch_id: str) -> None:
        node = self.node
        for name, reg in list(self._registered.items()):
            if watch_id not in reg:
                continue
            reg.discard(watch_id)
            if name in node.indices:
                try:
                    node.delete_doc(name, _WATCH_DOC_PREFIX + watch_id)
                except Exception:  # noqa: BLE001
                    self.stats["errors_total"] += 1

    def percolate_collector_batch(self, index_name: str,
                                  docs: list[dict]) -> int:
        """Percolate one collector bulk against every document watch in
        ONE dense matrix program (the PR-18 lane the monitoring index
        already rides) and fire matching watches. Returns matched-doc
        count across watches."""
        if not docs or not self._document_watches():
            return 0
        node = self.node
        self.ensure_percolator_registrations(index_name)
        svc = node.indices.get(index_name)
        if svc is None:
            return 0
        from ..search.percolate_exec import percolate_batch
        from ..common import tracing
        with tracing.span("watch", kind="document", index=index_name,
                          docs=len(docs)):
            try:
                outs = percolate_batch(
                    svc, index_name, [(d, "_doc") for d in docs],
                    caches=node.caches,
                    devices=node.device_pool.devices
                    if node.device_pool else None)
            except Exception as e:  # noqa: BLE001 — never break the tick
                self.stats["errors_total"] += 1
                for w in self._document_watches():
                    w.last_error = str(e)
                return 0
        self.stats["percolate_rides_total"] += 1
        per_watch: dict[str, int] = {}
        for out in outs:
            for m in out["matches"]:
                mid = m["_id"]
                if mid.startswith(_WATCH_DOC_PREFIX):
                    wid = mid[len(_WATCH_DOC_PREFIX):]
                    per_watch[wid] = per_watch.get(wid, 0) + 1
        now_ms = int(self._clock() * 1000)
        matched = 0
        for wid in sorted(per_watch):
            with self._lock:
                w = self.watches.get(wid)
            if w is None:
                continue
            self.stats["evaluations_total"] += 1
            w.evaluations_total += 1
            w.last_eval_ms = now_ms
            matched += per_watch[wid]
            self._fire(w, now_ms, {"matched_docs": per_watch[wid],
                                   "index": index_name})
        return matched

    # -- aggregation watches: scheduled evaluation --------------------------

    def run_due(self, now_ms: int | None = None) -> int:
        """Evaluate every aggregation watch whose interval has elapsed;
        the scheduler tick (tests call it directly)."""
        if now_ms is None:
            now_ms = int(self._clock() * 1000)
        with self._lock:
            due = [w for w in self.watches.values()
                   if w.kind == "aggregation"
                   and now_ms - w.last_eval_ms >= w.interval_s * 1000.0]
        for w in due:
            w.last_eval_ms = now_ms
            self.execute_watch(w.watch_id, now_ms=now_ms)
        self._apply_retention()
        return len(due)

    def execute_watch(self, watch_id: str,
                      now_ms: int | None = None) -> dict:
        """One evaluation of an aggregation watch: run the stored search
        under a `watch` tracer root, apply the condition, maybe fire."""
        with self._lock:
            w = self.watches.get(watch_id)
        if w is None:
            raise WatchMissingException(watch_id)
        if now_ms is None:
            now_ms = int(self._clock() * 1000)
        if w.kind == "document":
            return {"_id": watch_id, "kind": "document",
                    "note": "document watches fire from the collector's "
                            "percolate ride, not the scheduler"}
        node = self.node
        self.stats["evaluations_total"] += 1
        w.evaluations_total += 1
        req = w.search_request
        out = {"_id": watch_id, "kind": "aggregation",
               "condition_met": False, "fired": False, "throttled": False}
        with node.tracer.request("watch",
                                 attrs={"watch_id": watch_id,
                                        "index": str(req.get("index"))}):
            try:
                resp = node.search(req["index"], req.get("body") or {})
            except Exception as e:  # noqa: BLE001
                from ..node import IndexMissingException
                if isinstance(e, IndexMissingException):
                    # monitoring hasn't produced its first index yet:
                    # 'no data', not an error
                    out["note"] = "input index missing"
                    return out
                self.stats["errors_total"] += 1
                w.last_error = str(e)
                out["error"] = str(e)
                return out
            w.last_error = None
            try:
                met = condition_met(w, resp)
            except WatchParsingException as e:
                self.stats["errors_total"] += 1
                w.last_error = str(e)
                out["error"] = str(e)
                return out
            out["condition_met"] = bool(met)
            if not met:
                if w.acked:
                    # condition went false: auto-unack (ref ackable
                    # actions reset on AWAITS_SUCCESSFUL_EXECUTION)
                    w.acked = False
                    self._persist(w)
                return out
            fired = self._fire(w, now_ms, {"index": str(req.get("index"))})
            out["fired"] = fired
            out["throttled"] = not fired
        return out

    # -- firing / throttle / alerts ILM -------------------------------------

    def _fire(self, w: Watch, now_ms: int, details: dict) -> bool:
        if w.acked:
            self.stats["throttled_total"] += 1
            return False
        if w.last_fire_ms and now_ms - w.last_fire_ms \
                < w.throttle_s * 1000.0:
            self.stats["throttled_total"] += 1
            return False
        self._write_alert(w, now_ms, details)
        w.last_fire_ms = now_ms
        w.fires_total += 1
        self.stats["fires_total"] += 1
        try:
            self._persist(w)
        except Exception:  # noqa: BLE001
            self.stats["errors_total"] += 1
        return True

    def alert_index_for(self, ts_ms: int) -> str:
        day = time.gmtime(ts_ms / 1000.0)
        return f"{ALERTS_PREFIX}{day.tm_year:04d}." \
               f"{day.tm_mon:02d}.{day.tm_mday:02d}"

    def _day_of(self, name: str):
        try:
            y, m, d = name[len(ALERTS_PREFIX):].split(".")
            return (int(y), int(m), int(d))
        except (ValueError, IndexError):
            return None

    def _write_alert(self, w: Watch, now_ms: int, details: dict) -> None:
        """Append the firing to today's rolling alert index via the
        vectorized bulk lane (same write path as monitoring)."""
        node = self.node
        name = self.alert_index_for(now_ms)
        if name not in node.indices:
            from ..node import IndexAlreadyExistsException
            try:
                node.create_index(name, dict(ALERTS_SETTINGS),
                                  {k: dict(v) for k, v in
                                   ALERTS_MAPPING.items()})
            except IndexAlreadyExistsException:
                pass
        doc = {"@timestamp": now_ms, "watch_id": w.watch_id,
               "kind": w.kind, "state": "fired"}
        doc.update({k: v for k, v in details.items() if k not in doc})
        if isinstance(w.body.get("actions"), dict):
            doc["actions"] = sorted(w.body["actions"])
        node.bulk([("index",
                    {"_index": name,
                     "_id": f"{w.watch_id}-{now_ms}"}, doc)])
        node.indices[name].refresh()
        self.stats["alerts_indexed_total"] += 1

    def _apply_retention(self) -> None:
        import datetime
        today = datetime.datetime.utcfromtimestamp(self._clock()).date()
        cutoff = today - datetime.timedelta(days=self.retention_days)
        for name in sorted(self.node.indices):
            if not name.startswith(ALERTS_PREFIX):
                continue
            day = self._day_of(name)
            if day is None:
                continue
            try:
                when = datetime.date(*day)
            except ValueError:
                continue
            if when < cutoff:
                self.node.delete_index(name)
                self.stats["retention_deletes_total"] += 1

    # -- GET /_alerts -------------------------------------------------------

    def alerts(self, size: int = 50, watch_id: str | None = None) -> dict:
        node = self.node
        names = sorted(n for n in node.indices
                       if n.startswith(ALERTS_PREFIX)
                       and self._day_of(n) is not None)
        if not names:
            return {"total": 0, "indices": [], "alerts": []}
        body = {"size": size, "sort": [{"@timestamp": "desc"}],
                "query": ({"term": {"watch_id": watch_id}} if watch_id
                          else {"match_all": {}})}
        resp = node.search(ALERTS_PREFIX + "*", body)
        alerts = [dict(h.get("_source") or {},
                       _id=h["_id"], _index=h["_index"])
                  for h in resp["hits"]["hits"]]
        return {"total": resp["hits"]["total"], "indices": names,
                "alerts": alerts}

    # -- stats / metrics ----------------------------------------------------

    def watcher_stats(self) -> dict:
        with self._lock:
            watches = {wid: w.status()
                       for wid, w in sorted(self.watches.items())}
        return {"watcher_state": ("started" if self._thread is not None
                                  else "stopped"),
                "watch_count": len(watches),
                "execution": dict(self.stats),
                "watches": watches}

    def metric_totals(self) -> dict:
        """The `es_watcher_*` family payload for /_metrics."""
        with self._lock:
            n = len(self.watches)
        out = dict(self.stats)
        out["watches"] = n
        return out

    def metric_per_watch(self) -> dict:
        """Per-watch last-fire gauges (`es_watcher_watch_*`, one series
        per watch id)."""
        with self._lock:
            return {wid: {"fires_total": w.fires_total,
                          "last_fire_epoch_millis": w.last_fire_ms}
                    for wid, w in sorted(self.watches.items())}

    # -- thread lifecycle ---------------------------------------------------

    def _maybe_start(self) -> None:
        if self._thread is not None or self.interval_s <= 0:
            return
        with self._lock:
            if not any(w.kind == "aggregation"
                       for w in self.watches.values()):
                return
            if self._thread is not None:
                return

            def loop():
                while not self._stop.wait(self.interval_s):
                    try:
                        self.run_due()
                    except Exception:  # noqa: BLE001 — never break serving
                        self.stats["errors_total"] += 1
            self._thread = threading.Thread(target=loop, daemon=True,
                                            name="es[watcher]")
            self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
