"""Watch model + condition evaluation (ref Watcher's Watch.java /
CompareCondition / ScriptCondition, SURVEY §7).

A watch body is JSON:

    {"trigger":   {"schedule": {"interval": "10s"}},          # optional
     "input":     {"search": {"request": {"index": ..., "body": ...}}}
                | {"percolate": {"query": {...}}},
     "condition": {"always": {}} | {"never": {}}
                | {"compare": {"ctx.payload.<path>": {"gte": 10}}}
                | {"script": {"inline"|"source": ..., "params": {...}}},
     "actions":   {...},                                      # opaque
     "throttle_period": "10s"}                                # optional

Two flavors fall out of the input clause: a ``percolate`` input makes a
*document watch* (the query is compiled into the PR-18 percolator
registry and rides the monitoring collector's dense doc×query matrix —
no scheduler involvement), a ``search`` input makes an *aggregation
watch* (the scheduler runs the request and applies the condition to the
response payload — ``ctx.payload`` paths walk the search response, so
pipeline-agg values like a derivative are first-class condition inputs).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field


class WatchParsingException(Exception):
    pass


_COMPARE_OPS = {
    "gte": lambda a, b: a >= b,
    "gt": lambda a, b: a > b,
    "lte": lambda a, b: a <= b,
    "lt": lambda a, b: a < b,
    "eq": lambda a, b: a == b,
    "not_eq": lambda a, b: a != b,
}

_DURATION = re.compile(r"^(\d+(?:\.\d+)?)\s*(ms|s|m|h|d)?$")
_UNIT_S = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0,
           None: 1.0}


def duration_secs(value, default: float) -> float:
    """'500ms' / '10s' / '5m' / bare number -> seconds (ref TimeValue)."""
    if value is None:
        return default
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    m = _DURATION.match(str(value).strip())
    if not m:
        return default
    return float(m.group(1)) * _UNIT_S[m.group(2)]


@dataclass
class Watch:
    watch_id: str
    body: dict
    kind: str                      # "document" | "aggregation"
    interval_s: float
    throttle_s: float
    condition: dict
    # runtime status (persisted back into the registry index on change)
    acked: bool = False
    last_eval_ms: int = 0
    last_fire_ms: int = 0
    fires_total: int = 0
    evaluations_total: int = 0
    last_error: str | None = dc_field(default=None)

    @property
    def percolate_query(self) -> dict:
        return self.body["input"]["percolate"]["query"]

    @property
    def search_request(self) -> dict:
        return self.body["input"]["search"]["request"]

    def status(self) -> dict:
        return {"kind": self.kind, "acked": self.acked,
                "interval_s": self.interval_s,
                "throttle_period_s": self.throttle_s,
                "evaluations_total": self.evaluations_total,
                "fires_total": self.fires_total,
                "last_fire_epoch_millis": self.last_fire_ms,
                "last_error": self.last_error}


def parse_watch(watch_id: str, body, default_throttle_s: float = 10.0,
                default_interval_s: float = 10.0) -> Watch:
    if not watch_id or not isinstance(watch_id, str):
        raise WatchParsingException("watch id is required")
    if not isinstance(body, dict):
        raise WatchParsingException("watch body must be an object")
    inp = body.get("input")
    if not isinstance(inp, dict) or len(inp) != 1:
        raise WatchParsingException(
            "watch requires exactly one input: [search] or [percolate]")
    (itype, ival), = inp.items()
    if itype == "percolate":
        if not isinstance(ival, dict) \
                or not isinstance(ival.get("query"), dict):
            raise WatchParsingException(
                "[percolate] input requires a [query] object")
        kind = "document"
    elif itype == "search":
        req = (ival or {}).get("request") if isinstance(ival, dict) else None
        if not isinstance(req, dict) or not req.get("index"):
            raise WatchParsingException(
                "[search] input requires [request.index]")
        if not isinstance(req.get("body", {}), dict):
            raise WatchParsingException("[search] request body must be "
                                        "an object")
        kind = "aggregation"
    else:
        raise WatchParsingException(f"unknown watch input [{itype}]")

    condition = body.get("condition", {"always": {}})
    _validate_condition(condition)
    if kind == "document" and "condition" in body \
            and "always" not in condition:
        raise WatchParsingException(
            "document (percolate) watches fire on any match; only the "
            "[always] condition is supported")

    trigger = body.get("trigger") or {}
    sched = trigger.get("schedule") or {} if isinstance(trigger, dict) else {}
    interval_s = duration_secs(sched.get("interval"), default_interval_s)
    if interval_s <= 0:
        raise WatchParsingException("trigger interval must be positive")
    throttle_s = duration_secs(body.get("throttle_period"),
                               default_throttle_s)
    if "actions" in body and not isinstance(body["actions"], dict):
        raise WatchParsingException("[actions] must be an object")
    return Watch(watch_id=watch_id, body=body, kind=kind,
                 interval_s=interval_s, throttle_s=throttle_s,
                 condition=condition)


def _validate_condition(cond) -> None:
    if not isinstance(cond, dict) or len(cond) != 1:
        raise WatchParsingException(
            "condition requires exactly one of "
            "[always|never|compare|script]")
    (ctype, cval), = cond.items()
    if ctype in ("always", "never"):
        return
    if ctype == "compare":
        if not isinstance(cval, dict) or len(cval) != 1:
            raise WatchParsingException(
                "[compare] condition requires exactly one path")
        (_, clause), = cval.items()
        if not isinstance(clause, dict) or len(clause) != 1:
            raise WatchParsingException(
                "[compare] clause requires exactly one operator")
        (op, _), = clause.items()
        if op not in _COMPARE_OPS:
            raise WatchParsingException(f"unknown compare operator [{op}]")
        return
    if ctype == "script":
        if not isinstance(cval, (str, dict)):
            raise WatchParsingException("[script] condition requires a "
                                        "script")
        return
    raise WatchParsingException(f"unknown condition [{ctype}]")


def resolve_payload_path(payload, path: str):
    """Walk a `ctx.payload.`-style dotted path through the search
    response; integer tokens (incl. negative) index lists. None on any
    miss — a missing bucket is 'no data', not an error."""
    for prefix in ("ctx.payload.", "payload."):
        if path.startswith(prefix):
            path = path[len(prefix):]
            break
    cur = payload
    for tok in path.split("."):
        if isinstance(cur, list):
            try:
                cur = cur[int(tok)]
            except (ValueError, IndexError):
                return None
        elif isinstance(cur, dict):
            if tok not in cur:
                return None
            cur = cur[tok]
        else:
            return None
    return cur


def condition_met(watch: Watch, payload: dict) -> bool:
    """Apply the watch's condition to the input search response."""
    (ctype, cval), = watch.condition.items()
    if ctype == "always":
        return True
    if ctype == "never":
        return False
    if ctype == "compare":
        (path, clause), = cval.items()
        (op, expected), = clause.items()
        actual = resolve_payload_path(payload, path)
        if actual is None:
            return False
        try:
            return bool(_COMPARE_OPS[op](actual, expected))
        except TypeError:
            return False
    # script condition: truthy return fires; `ctx.payload` binds the
    # search response (the script's own params clause still applies)
    from ..script.engine import run_search_script, ScriptException
    ctx = {"payload": payload}
    try:
        return bool(run_search_script(cval, {}, extra_names={"ctx": ctx}))
    except ScriptException as e:
        raise WatchParsingException(f"watch condition script failed: {e}")
