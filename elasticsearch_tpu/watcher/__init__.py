"""Watcher alerting tier (ISSUE 20, SURVEY §7): stored watches evaluated
continuously against the monitoring stream."""

from .watch import Watch, WatchParsingException, parse_watch, condition_met
from .service import WatcherService, WATCHES_INDEX, ALERTS_PREFIX

__all__ = [
    "Watch", "WatchParsingException", "parse_watch", "condition_met",
    "WatcherService", "WATCHES_INDEX", "ALERTS_PREFIX",
]
