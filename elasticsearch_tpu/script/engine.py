"""Restricted update/script engine.

The reference sandboxes Groovy for update scripts
(/root/reference/src/main/java/org/elasticsearch/script/groovy/
GroovySandboxExpressionChecker.java; update flow action/update/
UpdateHelper.java:61). Groovy-on-JVM has no place here; instead a tiny
AST-whitelisted expression language covers the overwhelmingly common update
patterns (counter increments, field set/remove, list append) with NO access
to anything outside `ctx` and `params` — the same capability boundary the
reference's sandbox enforces.

Supported: assignments and augmented assignments to ctx._source paths,
arithmetic/comparison/boolean expressions, literals, list/dict displays,
`del ctx._source.field` / ctx.op = "delete"-style deletes via `remove`,
method calls append/extend/remove on lists, `if` statements.
"""

from __future__ import annotations

import ast
from typing import Any


class ScriptException(Exception):
    pass


_ALLOWED_BINOPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv,
                   ast.Mod, ast.Pow)
_ALLOWED_CMPOPS = (ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
                   ast.In, ast.NotIn)
_ALLOWED_METHODS = {"append", "extend", "remove", "pop", "get", "keys",
                    "values", "items", "upper", "lower", "strip", "split"}

# Math.* roster shared with the compiled script lane (script/jax_compile.py)
# — same names, same f64 results for the exact-IEEE subset, so the host
# evaluator is the bitwise reference the compiled lane declines to.
import math as _math  # noqa: E402


def _math_floor(a):
    return float(_math.floor(a))


def _math_ceil(a):
    return float(_math.ceil(a))


_MATH_METHODS = {
    "abs": abs, "sqrt": _math.sqrt, "log": _math.log,
    "log10": _math.log10, "exp": _math.exp, "pow": lambda a, b: a ** b,
    "min": min, "max": max, "floor": _math_floor, "ceil": _math_ceil,
}


class _Env:
    def __init__(self, ctx: dict, params: dict):
        self.names = {"ctx": ctx, "params": params, "true": True,
                      "false": False, "null": None}


def run_update_script(script, source: dict,
                      params: dict | None = None) -> tuple[dict, str]:
    """Execute an update script against a doc source; returns
    (new_source, op) where op is "index" (default), "delete" or "none" —
    the ctx.op contract the reference's UpdateHelper honors
    (ref action/update/UpdateHelper.java:61).
    Accepts the ES shapes: "inline string", {"inline": "..."} or
    {"source"/"script": "..."} with optional {"params": {...}}."""
    if isinstance(script, dict):
        code = script.get("inline") or script.get("source") or \
            script.get("script") or ""
        params = params or script.get("params") or {}
    else:
        code = str(script)
    params = params or {}
    ctx = {"_source": source, "op": "index"}
    try:
        tree = ast.parse(code, mode="exec")
    except SyntaxError as e:
        raise ScriptException(f"script parse error: {e}") from e
    env = _Env(ctx, params)
    for stmt in tree.body:
        _exec_stmt(stmt, env)
    op = ctx.get("op", "index")
    if op not in ("index", "create", "delete", "none", "noop"):
        raise ScriptException(f"illegal ctx.op [{op}]")
    return ctx["_source"], "none" if op == "noop" else op


def _exec_stmt(node: ast.stmt, env: _Env) -> None:
    if isinstance(node, ast.Expr):
        _eval(node.value, env)
    elif isinstance(node, ast.Assign):
        val = _eval(node.value, env)
        for t in node.targets:
            _assign(t, val, env)
    elif isinstance(node, ast.AugAssign):
        if not isinstance(node.op, _ALLOWED_BINOPS):
            raise ScriptException("operator not allowed")
        cur = _eval(node.target, env)
        val = _apply_binop(node.op, cur, _eval(node.value, env))
        _assign(node.target, val, env)
    elif isinstance(node, ast.If):
        branch = node.body if _eval(node.test, env) else node.orelse
        for s in branch:
            _exec_stmt(s, env)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            _delete(t, env)
    else:
        raise ScriptException(f"statement not allowed: {type(node).__name__}")


def _assign(target: ast.expr, val: Any, env: _Env) -> None:
    obj, key = _resolve_container(target, env)
    obj[key] = val


def _delete(target: ast.expr, env: _Env) -> None:
    obj, key = _resolve_container(target, env)
    obj.pop(key, None)


def _resolve_container(target: ast.expr, env: _Env):
    if isinstance(target, ast.Name):
        # script-local variable (scripted_metric combine/reduce temps)
        return env.names, target.id
    if isinstance(target, ast.Attribute):
        obj = _eval(target.value, env)
        if not isinstance(obj, dict):
            raise ScriptException("can only assign into object fields")
        return obj, target.attr
    if isinstance(target, ast.Subscript):
        obj = _eval(target.value, env)
        key = _eval(target.slice, env)
        return obj, key
    raise ScriptException("invalid assignment target")


def _apply_binop(op, a, b):
    import operator
    table = {ast.Add: operator.add, ast.Sub: operator.sub,
             ast.Mult: operator.mul, ast.Div: operator.truediv,
             ast.FloorDiv: operator.floordiv, ast.Mod: operator.mod,
             ast.Pow: operator.pow}
    return table[type(op)](a, b)


def _eval(node: ast.expr, env: _Env) -> Any:
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        if node.id not in env.names:
            raise ScriptException(f"unknown name [{node.id}]")
        return env.names[node.id]
    if isinstance(node, ast.Attribute):
        obj = _eval(node.value, env)
        if isinstance(obj, dict):
            return obj.get(node.attr)
        raise ScriptException(f"attribute access on non-object [{node.attr}]")
    if isinstance(node, ast.Subscript):
        obj = _eval(node.value, env)
        key = _eval(node.slice, env)
        if isinstance(obj, dict):
            return obj.get(key)
        return obj[key]
    if isinstance(node, ast.BinOp):
        if not isinstance(node.op, _ALLOWED_BINOPS):
            raise ScriptException("operator not allowed")
        return _apply_binop(node.op, _eval(node.left, env),
                            _eval(node.right, env))
    if isinstance(node, ast.UnaryOp):
        v = _eval(node.operand, env)
        if isinstance(node.op, ast.USub):
            return -v
        if isinstance(node.op, ast.Not):
            return not v
        raise ScriptException("unary operator not allowed")
    if isinstance(node, ast.Compare):
        left = _eval(node.left, env)
        import operator
        table = {ast.Eq: operator.eq, ast.NotEq: operator.ne,
                 ast.Lt: operator.lt, ast.LtE: operator.le,
                 ast.Gt: operator.gt, ast.GtE: operator.ge,
                 ast.In: lambda a, b: a in b,
                 ast.NotIn: lambda a, b: a not in b}
        for op, comp in zip(node.ops, node.comparators):
            if not isinstance(op, _ALLOWED_CMPOPS):
                raise ScriptException("comparison not allowed")
            right = _eval(comp, env)
            if not table[type(op)](left, right):
                return False
            left = right
        return True
    if isinstance(node, ast.BoolOp):
        vals = [_eval(v, env) for v in node.values]
        return all(vals) if isinstance(node.op, ast.And) else any(vals)
    if isinstance(node, ast.IfExp):
        return _eval(node.body, env) if _eval(node.test, env) \
            else _eval(node.orelse, env)
    if isinstance(node, ast.List):
        return [_eval(e, env) for e in node.elts]
    if isinstance(node, ast.Dict):
        return {_eval(k, env): _eval(v, env)
                for k, v in zip(node.keys, node.values)}
    if isinstance(node, ast.Call):
        if not isinstance(node.func, ast.Attribute):
            raise ScriptException("only method calls are allowed")
        if (isinstance(node.func.value, ast.Name)
                and node.func.value.id == "Math"
                and node.func.attr in _MATH_METHODS):
            args = [_eval(a, env) for a in node.args]
            try:
                return _MATH_METHODS[node.func.attr](*args)
            except (TypeError, ValueError, OverflowError) as e:
                raise ScriptException(f"Math.{node.func.attr}: {e}") from e
        if node.func.attr not in _ALLOWED_METHODS:
            raise ScriptException(f"method [{node.func.attr}] not allowed")
        obj = _eval(node.func.value, env)
        args = [_eval(a, env) for a in node.args]
        return getattr(obj, node.func.attr)(*args)
    raise ScriptException(f"expression not allowed: {type(node).__name__}")


def doc_values_view(source: dict) -> dict:
    """`doc['field'].value` accessor view over a stored source — flattened
    dotted paths, each with value/values/empty (the lang-expression doc
    contract; shared by script queries, script_fields and scripted_metric
    so every script dialect sees the same shape)."""
    def flatten(obj, prefix=""):
        out = {}
        for k, v in (obj or {}).items():
            path = f"{prefix}{k}"
            if isinstance(v, dict):
                out.update(flatten(v, path + "."))
            else:
                out[path] = v if isinstance(v, list) else [v]
        return out

    return {f: {"value": (vs[0] if vs else None), "values": vs,
                "empty": not vs}
            for f, vs in flatten(source).items()}


def run_search_script(script, source: dict, params: dict | None = None,
                      extra_names: dict | None = None):
    """Evaluate a SEARCH-time expression over one doc (script_fields /
    script query; ref script/expression/ExpressionScriptEngineService —
    `doc['field'].value` accessors over doc values). Returns the value;
    numeric results coerce to float like Lucene expressions (always
    doubles). `extra_names` binds additional read-only names (e.g.
    `_score` for function_score script_score)."""
    if isinstance(script, dict):
        code = script.get("inline") or script.get("source") or \
            script.get("script") or ""
        params = params or script.get("params") or {}
    else:
        code = str(script)
    params = params or {}

    doc = doc_values_view(source)
    env = _Env({"_source": source}, params)
    env.names["doc"] = doc
    env.names["_source"] = source
    if extra_names:
        env.names.update(extra_names)
    try:
        tree = ast.parse(code, mode="eval")
    except SyntaxError as e:
        raise ScriptException(f"script parse error: {e}") from e
    out = _eval(tree.body, env)
    if isinstance(out, int) and not isinstance(out, bool):
        return float(out)
    return out


def run_agg_script(script, names: dict, params: dict | None = None) -> None:
    """Execute statements against caller-provided names (scripted_metric's
    _agg / doc / _aggs environment; ref metrics/scripted/
    ScriptedMetricAggregator). Mutates the passed objects in place; returns
    the value of a trailing bare expression, if any."""
    if isinstance(script, dict):
        code = script.get("inline") or script.get("source") or ""
        params = params or script.get("params") or {}
    else:
        code = str(script)
    try:
        tree = ast.parse(code, mode="exec")
    except SyntaxError as e:
        raise ScriptException(f"script parse error: {e}") from e
    env = _Env({}, params or {})
    env.names.update(names)
    result = None
    for i, stmt in enumerate(tree.body):
        if i == len(tree.body) - 1 and isinstance(stmt, ast.Expr):
            result = _eval(stmt.value, env)
        else:
            _exec_stmt(stmt, env)
    return result
