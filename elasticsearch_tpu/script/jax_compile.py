"""Restricted expression → JAX compiler (ISSUE 18 tentpole, part 2).

`script_score` bodies written in the engine's expression subset compile
to fused device ops instead of declining every dense lane (SURVEY §7 M6:
"restricted expression→XLA compiler instead of Groovy sandbox"). The
grammar is deliberately the intersection of what the host evaluator
(script/engine.py) accepts and what XLA can fuse:

    literals        int / float constants
    arithmetic      + - * / // % **  and unary -
    doc values      doc['field'].value — reads the segment's uninverted
                    numeric column (long/integer/short/byte/double/float
                    fields only; other types read differently from
                    _source than from columns and decline)
    score           _score — the inner query's score matrix
    params          params.x / params['x'] — bound as TRACED f64 scalars,
                    so re-running a template with different values reuses
                    the compiled program (the no-retrace contract)
    Math roster     Math.abs/sqrt/log/log10/exp/pow/min/max/floor/ceil

Everything else (comparisons, conditionals, loops, _source reads, string
ops) raises ScriptCompileError with a stable `script:*` reason; the
caller declines to the host evaluator through the lane recorder — a
decline, never an error.

Numeric contract vs the host evaluator (the chaos parity pair): both
lanes evaluate in f64 and a doc with ANY referenced field missing scores
0.0 (the host raises on `None` arithmetic and maps ScriptException→0.0;
the compiled lane masks on the missing column). + - * / min / max / abs
/ floor / ceil are bitwise-identical IEEE ops on both sides. Documented
carve-outs, excluded from the oracle's replay pair: ** and the
transcendentals (libm vs XLA ulp), % on negative operands, division by
zero (host exception→0.0, device ±inf), NaN propagation through min/max
(Python min vs jnp.minimum), and integers beyond 2^53.

Compile cache: keyed on (canonical AST dump, param-name tuple, target) —
the expression's TEXT doesn't key (whitespace variants share a program),
and `es_script_compiles_total{target=}` counts only true builds.
"""

from __future__ import annotations

import ast
import threading

import jax
import jax.numpy as jnp

from ..common.device_stats import instrument

# numeric column types whose _source values and uninverted columns agree
# bit-for-bit in f64 (date/bool/ip columns encode differently than their
# source form, so they decline)
_NUMERIC_OK = ("long", "integer", "short", "byte", "double", "float")

_MATH_FNS = {
    "abs": (jnp.abs, 1), "sqrt": (jnp.sqrt, 1), "log": (jnp.log, 1),
    "log10": (jnp.log10, 1), "exp": (jnp.exp, 1), "pow": (jnp.power, 2),
    "min": (jnp.minimum, 2), "max": (jnp.maximum, 2),
    "floor": (jnp.floor, 1), "ceil": (jnp.ceil, 1),
}

_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.Div: lambda a, b: a / b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.Mod: lambda a, b: a % b,
    ast.Pow: lambda a, b: a ** b,
}


class ScriptCompileError(Exception):
    """Expression outside the compilable subset; `.reason` is the stable
    lane-decline label."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class _Analysis:
    __slots__ = ("fields", "params", "uses_score")

    def __init__(self):
        self.fields: list[str] = []      # first-reference order
        self.params: list[str] = []
        self.uses_score = False


def _doc_field(node: ast.AST) -> str | None:
    """doc['field'].value -> 'field' (the only doc accessor shape)."""
    if (isinstance(node, ast.Attribute) and node.attr == "value"
            and isinstance(node.value, ast.Subscript)
            and isinstance(node.value.value, ast.Name)
            and node.value.value.id == "doc"):
        sl = node.value.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return sl.value
    return None


def _param_name(node: ast.AST) -> str | None:
    """params.x or params['x'] -> 'x'."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "params"):
        return node.attr
    if (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id == "params"):
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return sl.value
    return None


def _walk(node: ast.AST, an: _Analysis) -> None:
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bool) or not isinstance(
                node.value, (int, float)):
            raise ScriptCompileError("script:literal-type")
        return
    if isinstance(node, ast.BinOp):
        if type(node.op) not in _BINOPS:
            raise ScriptCompileError(
                f"script:unsupported-{type(node.op).__name__}")
        _walk(node.left, an)
        _walk(node.right, an)
        return
    if isinstance(node, ast.UnaryOp):
        if not isinstance(node.op, (ast.USub, ast.UAdd)):
            raise ScriptCompileError(
                f"script:unsupported-{type(node.op).__name__}")
        _walk(node.operand, an)
        return
    if isinstance(node, ast.Name):
        if node.id == "_score":
            an.uses_score = True
            return
        raise ScriptCompileError("script:unknown-name")
    f = _doc_field(node)
    if f is not None:
        if f not in an.fields:
            an.fields.append(f)
        return
    p = _param_name(node)
    if p is not None:
        if p not in an.params:
            an.params.append(p)
        return
    if isinstance(node, ast.Call):
        if (not isinstance(node.func, ast.Attribute)
                or not isinstance(node.func.value, ast.Name)
                or node.func.value.id != "Math"
                or node.func.attr not in _MATH_FNS):
            raise ScriptCompileError("script:unsupported-call")
        _, arity = _MATH_FNS[node.func.attr]
        if len(node.args) != arity or node.keywords:
            raise ScriptCompileError("script:math-arity")
        for a in node.args:
            _walk(a, an)
        return
    raise ScriptCompileError(f"script:unsupported-{type(node).__name__}")


def analyze(source: str) -> _Analysis:
    """Parse + validate; -> referenced fields/params/score usage.
    Raises ScriptCompileError with a stable reason."""
    try:
        tree = ast.parse(source, mode="eval")
    except SyntaxError:
        raise ScriptCompileError("script:parse-error") from None
    an = _Analysis()
    _walk(tree.body, an)
    return an


# ---------------------------------------------------------------------------
# Compilation
# ---------------------------------------------------------------------------

class CompiledScript:
    """A jitted (vals [F,N] f64, miss [F,N] bool, score [Q,N] f64,
    params [P] f64) -> [Q,N] f64 program plus its binding metadata."""

    __slots__ = ("fields", "param_names", "uses_score", "fn", "key")

    def __init__(self, fields, param_names, uses_score, fn, key):
        self.fields = fields
        self.param_names = param_names
        self.uses_score = uses_score
        self.fn = fn
        self.key = key


_CACHE_LOCK = threading.Lock()
_COMPILED: dict[tuple, CompiledScript] = {}
_COMPILES_BY_TARGET: dict[str, int] = {}


def script_compiles_snapshot() -> dict[str, int]:
    """target -> true-build count (`es_script_compiles_total{target=}`)."""
    with _CACHE_LOCK:
        return dict(_COMPILES_BY_TARGET)


def _emit(node: ast.AST, env: dict):
    if isinstance(node, ast.Constant):
        return jnp.float64(node.value)
    if isinstance(node, ast.BinOp):
        return _BINOPS[type(node.op)](_emit(node.left, env),
                                      _emit(node.right, env))
    if isinstance(node, ast.UnaryOp):
        v = _emit(node.operand, env)
        return -v if isinstance(node.op, ast.USub) else v
    if isinstance(node, ast.Name):                 # _score (validated)
        return env["score"]
    f = _doc_field(node)
    if f is not None:
        return env["doc"][f]
    p = _param_name(node)
    if p is not None:
        return env["params"][p]
    fn, _ = _MATH_FNS[node.func.attr]              # Call (validated)
    return fn(*[_emit(a, env) for a in node.args])


def compile_expression(source: str, target: str) -> CompiledScript:
    """source text -> cached CompiledScript. The cache key is the
    canonical AST (whitespace/formatting variants share one program) +
    the referenced param-name tuple; only a true build bumps the
    per-target compile counter."""
    an = analyze(source)
    tree = ast.parse(source, mode="eval")
    key = (ast.dump(tree), tuple(an.params), target)
    with _CACHE_LOCK:
        hit = _COMPILED.get(key)
    if hit is not None:
        return hit

    fields = tuple(an.fields)
    param_names = tuple(an.params)
    body = tree.body

    def raw(vals, miss, score, params):
        env = {
            "doc": {f: vals[i][None, :] for i, f in enumerate(fields)},
            "params": {p: params[i] for i, p in enumerate(param_names)},
            "score": score,
        }
        out = _emit(body, env) + jnp.zeros_like(score)   # -> [Q, N] f64
        if fields:
            anymiss = miss[0]
            for i in range(1, len(fields)):
                anymiss = anymiss | miss[i]
            out = jnp.where(anymiss[None, :], 0.0, out)
        return out

    compiled = CompiledScript(
        fields, param_names, an.uses_score,
        instrument("script:compiled", jax.jit(raw), key=key[0][:64]),
        key)
    with _CACHE_LOCK:
        if key in _COMPILED:               # racing build: keep the first
            return _COMPILED[key]
        _COMPILED[key] = compiled
        _COMPILES_BY_TARGET[target] = _COMPILES_BY_TARGET.get(target, 0) + 1
    from ..common import tracing
    tracing.add_event("script_compile", target=target,
                      fields=len(fields), params=len(param_names))
    return compiled


def script_source(spec: dict) -> tuple[str | None, dict]:
    """Extract (source, params) from the ES wire shapes: a bare string,
    {"script": "..."} / {"inline": "..."} / {"source": "..."} or the
    nested {"script": {"inline"/"source": ..., "params": {...}}}."""
    if isinstance(spec, str):
        return spec, {}
    if not isinstance(spec, dict):
        return None, {}
    params = spec.get("params") or {}
    s = spec.get("script")
    if isinstance(s, dict):
        inner, p2 = script_source(s)
        return inner, {**params, **p2}
    for k in ("script", "inline", "source"):
        v = spec.get(k)
        if isinstance(v, str):
            return v, params
    return None, params


def validate_binding(compiled: CompiledScript, params: dict,
                     field_types: dict) -> None:
    """Wire-time checks the pure compiler can't do: every referenced doc
    field must be a plain numeric column and every referenced param a
    number. Raises ScriptCompileError (-> lane decline, host fallback)."""
    for f in compiled.fields:
        ft = field_types.get(f)
        if ft is None:
            raise ScriptCompileError("script:unmapped-field")
        if ft not in _NUMERIC_OK:
            raise ScriptCompileError("script:doc-field-type")
    for p in compiled.param_names:
        v = params.get(p)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ScriptCompileError("script:param-type")
