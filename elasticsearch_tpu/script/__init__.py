"""Scripting (ref script/, SURVEY.md §2.9): restricted update scripts."""

from .engine import run_update_script, ScriptException

__all__ = ["run_update_script", "ScriptException"]
