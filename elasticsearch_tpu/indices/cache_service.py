"""Node-level cache subsystem: request / query-plan / fielddata tiers.

The reference engine's hot-path economics rest on three caches the TPU
repro now has too, all instances of the one `common.cache.Cache` core:

  * `IndicesRequestCache` (ref indices/cache/request/IndicesRequestCache
    in ES 2.0): whole size-0 response bodies shared across indices, keyed
    by (index expression, canonical body, per-index engine generation) so
    any refresh/delete/merge invalidates naturally. Entries charge the
    `request` circuit breaker — under memory pressure the cache evicts its
    LRU tail and, at worst, refuses the insert; searches keep returning
    uncached results instead of 5xx-ing. Budget:
    `indices.requests.cache.size` (default 1% of the breaker-total "heap"),
    optional TTL `indices.requests.cache.expire`.

  * `QueryPlanCache` (the Lucene LRUQueryCache analog for this engine):
    normalized DSL body -> parsed executable Node tree, keyed by (index,
    incarnation, mapping version, canonical query JSON). Parsed trees are
    stateless w.r.t. execution (all per-segment work flows through
    SegmentContext), so repeated query templates skip host-side re-parse —
    and because the tree's plan_key() feeds the jit compile cache, a
    stable tree also means zero jit-key churn. Bodies containing date math
    ("now"), stored-template references or indexed-shape lookups are never
    cached (their parse output depends on wall clock / external state).

  * `FielddataCache` (ref indices/fielddata/cache/IndicesFieldDataCache):
    per-(segment, field) uninverted sort columns. Builds go through
    `make_room` admission — under `fielddata` breaker pressure the cache
    evicts least-recently-sorted columns (actually freeing their memory
    and breaker charge) before giving up with a clean 429.

One `stats()` walk feeds `_nodes/stats`, the `/_metrics` OpenMetrics
scrape and the stats-history sampler; `clear()` is the real engine under
`POST /_cache/clear?query=&request=&fielddata=`.
"""

from __future__ import annotations

import copy
import itertools
import json
import threading
import weakref
from typing import Any

from ..common import tracing
from ..common.cache import Cache, RemovalReason, parse_size

# tokens identify segments inside the fielddata cache without pinning the
# segment objects themselves (id() reuse after gc would alias entries)
_SEG_TOKENS = itertools.count(1)


def response_weight(resp: dict) -> int:
    """Bytes a cached response is accounted at: its JSON wire size (the
    response IS a JSON document; `default=str` covers stray numpy
    scalars)."""
    try:
        return len(json.dumps(resp, default=str).encode())
    except (TypeError, ValueError):
        return 1024


class _RequestEntry:
    __slots__ = ("resp", "names", "nbytes")

    def __init__(self, resp: dict, names: tuple, nbytes: int):
        self.resp = resp
        self.names = names
        self.nbytes = nbytes


class IndicesRequestCache:
    """Shared request cache with per-index byte/eviction attribution (the
    `{index}/_stats` request_cache section needs per-index numbers out of
    one node-wide cache; multi-index entries attribute to every index they
    cover)."""

    def __init__(self, max_bytes: int, ttl_s: float | None = None,
                 breaker=None, clock=None):
        self._lock = threading.Lock()
        self._by_index: dict[str, dict] = {}
        self.cache = Cache("request", max_bytes=max_bytes, ttl_s=ttl_s,
                           weigher=lambda e: e.nbytes, clock=clock,
                           removal_listener=self._on_removal,
                           breaker=breaker)

    def _slot(self, name: str) -> dict:
        return self._by_index.setdefault(
            name, {"bytes": 0, "count": 0, "evictions": 0})

    def _on_removal(self, key, entry: _RequestEntry, reason: str) -> None:
        if reason in (RemovalReason.EVICTED, RemovalReason.EXPIRED):
            tracing.add_event("cache.evict", tier="request", reason=reason,
                              bytes=entry.nbytes)
        with self._lock:
            for n in entry.names:
                s = self._slot(n)
                s["bytes"] -= entry.nbytes
                s["count"] -= 1
                if reason in (RemovalReason.EVICTED, RemovalReason.EXPIRED):
                    s["evictions"] += 1

    def get(self, key) -> dict | None:
        with tracing.span("cache.get", tier="request") as sp:
            ent = self.cache.get(key)
            if sp is not None:
                sp.attrs["hit"] = ent is not None
        if ent is None:
            return None
        return copy.deepcopy(ent.resp)

    def put(self, key, names, resp: dict) -> bool:
        entry = _RequestEntry(copy.deepcopy(resp), tuple(names),
                              response_weight(resp))
        with tracing.span("cache.put", tier="request",
                          bytes=entry.nbytes) as sp:
            ok = self.cache.put(key, entry)
            if sp is not None:
                sp.attrs["accepted"] = ok
        if ok:
            with self._lock:
                for n in entry.names:
                    s = self._slot(n)
                    s["bytes"] += entry.nbytes
                    s["count"] += 1
        return ok

    def clear(self, indices: list[str] | None = None) -> int:
        if indices is None:
            return self.cache.clear()
        want = set(indices)
        return self.cache.invalidate_where(
            lambda _k, e: bool(want & set(e.names)))

    def index_stats(self, name: str) -> dict:
        with self._lock:
            s = self._by_index.get(name)
            return {"bytes": max(s["bytes"], 0), "count": max(s["count"], 0),
                    "evictions": s["evictions"]} if s \
                else {"bytes": 0, "count": 0, "evictions": 0}

    def stats(self) -> dict:
        return self.cache.stats()


class _FdEntry:
    __slots__ = ("fd", "nbytes", "breaker", "index_name", "field", "token")

    def __init__(self, fd, nbytes, breaker, index_name, field, token):
        self.fd = fd
        self.nbytes = nbytes
        self.breaker = breaker
        self.index_name = index_name
        self.field = field
        self.token = token


class FielddataCache:
    """Node-level fielddata tier: owns the built (segment, field) columns,
    releases their breaker charge on any exit, and evicts LRU columns
    under breaker pressure so a hot sort workload on a full device sheds
    cold columns instead of 429-ing forever."""

    def __init__(self, max_bytes: int = 0):
        self._lock = threading.Lock()
        self._by_seg: dict[int, set[str]] = {}
        self._evictions_by_index: dict[str, int] = {}
        self.cache = Cache("fielddata", max_bytes=max_bytes,
                           weigher=lambda e: e.nbytes,
                           removal_listener=self._on_removal)

    def _on_removal(self, key, entry: _FdEntry, reason: str) -> None:
        if reason == RemovalReason.EVICTED:
            tracing.add_event("cache.evict", tier="fielddata",
                              reason=reason, field=entry.field,
                              bytes=entry.nbytes)
        if entry.breaker is not None:
            entry.breaker.release(entry.nbytes)
        with self._lock:
            fields = self._by_seg.get(entry.token)
            if fields is not None:
                fields.discard(entry.field)
                if not fields:
                    self._by_seg.pop(entry.token, None)
            if reason == RemovalReason.EVICTED and entry.index_name:
                self._evictions_by_index[entry.index_name] = \
                    self._evictions_by_index.get(entry.index_name, 0) + 1

    @staticmethod
    def token_of(seg) -> int:
        tok = getattr(seg, "_fd_token", None)
        if tok is None:
            tok = seg._fd_token = next(_SEG_TOKENS)
        return tok

    def get_or_build(self, seg, field: str, build):
        """The segment's fielddata entry, building (and charging the
        segment's breaker, with eviction-under-pressure) on first use.
        Raises CircuitBreakingException only when evicting every other
        column still can't fit the new one. `build()` returns the
        (mn, mx, miss, vocab, nbytes) tuple segment sorts consume."""
        token = self.token_of(seg)
        key = (token, field)
        with tracing.span("cache.get", tier="fielddata",
                          field=field) as sp:
            ent = self.cache.get(key)
            if sp is not None:
                sp.attrs["hit"] = ent is not None
        if ent is not None:
            return ent.fd
        breaker = getattr(seg, "breaker", None)
        charge = seg.n_pad * 17        # mirrors the built column's nbytes
        if breaker is not None:
            self.cache.make_room(breaker, charge)
        try:
            fd = build()
        except BaseException:
            if breaker is not None:
                breaker.release(charge)
            raise
        if fd is None:
            if breaker is not None:
                breaker.release(charge)
            return None
        nbytes = fd[4]
        if breaker is not None and nbytes != charge:
            # true up estimate drift without re-tripping
            if nbytes > charge:
                breaker.add_estimate(nbytes - charge, check=False)
            else:
                breaker.release(charge - nbytes)
        entry = _FdEntry(fd, nbytes, breaker,
                         getattr(seg, "index_name", None), field, token)
        if self.cache.put(key, entry):
            with self._lock:
                self._by_seg.setdefault(token, set()).add(field)
        elif breaker is not None:
            breaker.release(nbytes)   # refused by budget: nothing retained
        return fd

    def bytes_for(self, seg) -> dict[str, int]:
        """field -> bytes loaded for this segment (the `_cat/fielddata` /
        `_stats` fielddata walk)."""
        token = getattr(seg, "_fd_token", None)
        if token is None:
            return {}
        with self._lock:
            fields = list(self._by_seg.get(token, ()))
        out = {}
        for f in fields:
            ent = self.cache.peek((token, f))
            if ent is not None:
                out[f] = ent.nbytes
        return out

    def drop_segment(self, seg) -> int:
        """Invalidate every column of a dead segment (merge/close path) —
        the removal listener releases the breaker charge."""
        token = getattr(seg, "_fd_token", None)
        if token is None:
            return 0
        return self.cache.invalidate_where(lambda k, _e: k[0] == token)

    def clear(self, indices: list[str] | None = None) -> int:
        if indices is None:
            return self.cache.clear()
        want = set(indices)
        return self.cache.invalidate_where(
            lambda _k, e: e.index_name in want)

    def evictions_of(self, name: str) -> int:
        with self._lock:
            return self._evictions_by_index.get(name, 0)

    def stats(self) -> dict:
        return self.cache.stats()


class _AnnEntry:
    __slots__ = ("ivf", "nbytes", "breaker", "index_name", "field", "token")

    def __init__(self, ivf, nbytes, breaker, index_name, field, token):
        self.ivf = ivf
        self.nbytes = nbytes
        self.breaker = breaker
        self.index_name = index_name
        self.field = field
        self.token = token


class _QuantEntry:
    __slots__ = ("quant", "nbytes", "breaker", "index_name", "field",
                 "token", "mode", "kind")

    def __init__(self, quant, nbytes, breaker, index_name, field, token,
                 mode, kind):
        self.quant = quant
        self.nbytes = nbytes
        self.breaker = breaker
        self.index_name = index_name
        self.field = field
        self.token = token
        self.mode = mode              # "int8" | "pq"
        self.kind = kind              # "codes" | "books"


class AnnIndexCache:
    """Per-(segment, vector field, nlist) IVF cluster indexes for the ANN
    kNN lane (ops/ann.py + index/segment.IvfData): k-means centroids + the
    cluster->doc CSR, breaker-charged at build through `make_room`
    admission (LRU IVF structures shed under `fielddata` pressure before
    anything 429s), released on any removal. Entries die with their source
    segment (Engine merge/close calls `drop_segment` — the same hook that
    drops fielddata columns) and with `_cache/clear?query=`; vectors are
    immutable per segment, so tombstones never invalidate an entry (the
    query-time liveness mask handles them)."""

    def __init__(self, max_bytes: int = 0):
        self.declined = 0                # breaker refused the build charge
        self.cache = Cache("ann_index", max_bytes=max_bytes,
                           weigher=lambda e: e.nbytes,
                           removal_listener=self._on_removal)
        # quantized storage tier (ISSUE 12): int8 / PQ codes charged at
        # their TRUE 1/4-1/32 bytes, codebooks as a SEPARATE accounted
        # entry (key tail "codes" / "books") so the exposition and the
        # sampler ring show both residencies; same lifecycle as the IVF
        # tier — dies with the segment, rides `_cache/clear?query=`
        self.quant_declined = 0
        self._qlock = threading.Lock()
        self.quant_code_bytes = 0
        self.quant_book_bytes = 0
        self.quant = Cache("ann_quant", max_bytes=max_bytes,
                           weigher=lambda e: e.nbytes,
                           removal_listener=self._on_quant_removal)

    def _on_removal(self, key, entry: _AnnEntry, reason: str) -> None:
        if reason == RemovalReason.EVICTED:
            tracing.add_event("cache.evict", tier="ann_index",
                              reason=reason, field=entry.field,
                              bytes=entry.nbytes)
        if entry.breaker is not None:
            entry.breaker.release(entry.nbytes)

    def _on_quant_removal(self, key, entry: _QuantEntry,
                          reason: str) -> None:
        if reason == RemovalReason.EVICTED:
            tracing.add_event("cache.evict", tier="ann_quant",
                              reason=reason, field=entry.field,
                              bytes=entry.nbytes)
        if entry.breaker is not None:
            entry.breaker.release(entry.nbytes)
        with self._qlock:
            if entry.kind == "codes":
                self.quant_code_bytes -= entry.nbytes
            else:
                self.quant_book_bytes -= entry.nbytes

    def get_or_build(self, seg, field: str, nlist: int, build):
        """The segment's IVF index for `field`, building (and charging the
        segment's `fielddata` breaker) on first use. None when declined —
        undersized column, build failure, or breaker pressure even after
        shedding other entries (callers fall back to exact kNN)."""
        token = FielddataCache.token_of(seg)
        key = (token, field, int(nlist))
        with tracing.span("cache.get", tier="ann_index",
                          field=field) as sp:
            ent = self.cache.get(key)
            if sp is not None:
                sp.attrs["hit"] = ent is not None
        if ent is not None:
            return ent.ivf
        from ..ops.ann import ivf_nbytes
        vc = seg.vectors.get(field)
        if vc is None:
            return None
        breaker = getattr(seg, "breaker", None)
        est = ivf_nbytes(int(vc.vecs.shape[0]), int(nlist), vc.dims)
        if breaker is not None:
            try:
                self.cache.make_room(breaker, est)
            except Exception:  # noqa: BLE001 — degrade, never 429 a search
                self.declined += 1
                return None
        try:
            with tracing.span("ann_ivf_build", field=field, nlist=nlist):
                ivf = build()
        except BaseException:
            if breaker is not None:
                breaker.release(est)
            raise
        if ivf is None:
            if breaker is not None:
                breaker.release(est)
            return None
        nbytes = ivf.nbytes
        if breaker is not None and nbytes != est:
            if nbytes > est:      # true up estimate drift without re-tripping
                breaker.add_estimate(nbytes - est, check=False)
            else:
                breaker.release(est - nbytes)
        entry = _AnnEntry(ivf, nbytes, breaker,
                          getattr(seg, "index_name", None), field, token)
        if not self.cache.put(key, entry) and breaker is not None:
            breaker.release(nbytes)   # refused by budget: nothing retained
        return ivf

    def get_or_build_quant(self, seg, field: str, nlist: int, mode: str,
                           m: int, build):
        """The segment's quantized codes for `field` against the `nlist`
        IVF layout, building (and charging the `fielddata` breaker at the
        true quantized bytes) on first use. None when declined — shape
        can't quantize, build failure, or breaker pressure even after
        shedding (callers fall back to the f32 IVF scan)."""
        token = FielddataCache.token_of(seg)
        base = (token, field, int(nlist), mode, int(m))
        with tracing.span("cache.get", tier="ann_quant",
                          field=field) as sp:
            ent = self.quant.get(base + ("codes",))
            if sp is not None:
                sp.attrs["hit"] = ent is not None
        if ent is not None:
            return ent.quant
        from ..ops.ann import quant_nbytes
        vc = seg.vectors.get(field)
        if vc is None:
            return None
        breaker = getattr(seg, "breaker", None)
        cb_est, bb_est = quant_nbytes(int(vc.vecs.shape[0]), vc.dims,
                                      mode, m)
        est = cb_est + bb_est
        if breaker is not None:
            try:
                self.quant.make_room(breaker, est)
            except Exception:  # noqa: BLE001 — degrade, never 429 a search
                self.quant_declined += 1
                return None
        try:
            with tracing.span("ann_quant_build", field=field, mode=mode,
                              m=m):
                quant = build()
        except BaseException:
            if breaker is not None:
                breaker.release(est)
            raise
        if quant is None:
            if breaker is not None:
                breaker.release(est)
            return None
        if breaker is not None and quant.nbytes != est:
            if quant.nbytes > est:   # true up drift without re-tripping
                breaker.add_estimate(quant.nbytes - est, check=False)
            else:
                breaker.release(est - quant.nbytes)
        index_name = getattr(seg, "index_name", None)
        for kind, nbytes in (("codes", quant.codes_nbytes),
                             ("books", quant.books_nbytes)):
            entry = _QuantEntry(quant, nbytes, breaker, index_name, field,
                                token, mode, kind)
            if self.quant.put(base + (kind,), entry):
                with self._qlock:
                    if kind == "codes":
                        self.quant_code_bytes += nbytes
                    else:
                        self.quant_book_bytes += nbytes
            elif breaker is not None:
                breaker.release(nbytes)  # refused by budget: not retained
        return quant                     # the built tensors still serve

    def drop_segment(self, seg) -> int:
        """Invalidate every IVF index + quantized code set of a dead
        segment (merge/close) — the removal listeners release the
        breaker charges."""
        token = getattr(seg, "_fd_token", None)
        if token is None:
            return 0
        n = self.cache.invalidate_where(lambda k, _e: k[0] == token)
        n += self.quant.invalidate_where(lambda k, _e: k[0] == token)
        return n

    def clear(self, indices: list[str] | None = None) -> int:
        if indices is None:
            return self.cache.clear() + self.quant.clear()
        want = set(indices)
        n = self.cache.invalidate_where(
            lambda _k, e: e.index_name in want)
        n += self.quant.invalidate_where(
            lambda _k, e: e.index_name in want)
        return n

    def stats(self) -> dict:
        out = self.cache.stats()
        out["declined"] = self.declined
        return out

    def quant_stats(self) -> dict:
        out = self.quant.stats()
        out["declined"] = self.quant_declined
        with self._qlock:
            out["code_bytes"] = max(self.quant_code_bytes, 0)
            out["codebook_bytes"] = max(self.quant_book_bytes, 0)
        return out


class _StackEntry:
    __slots__ = ("stack", "nbytes", "breaker", "index_name")

    def __init__(self, stack, nbytes, breaker, index_name):
        self.stack = stack
        self.nbytes = nbytes
        self.breaker = breaker
        self.index_name = index_name


class SegmentStackCache:
    """Per-(index, shard) packed segment stacks for the stacked dense lane
    (search/stacked.py). Entries charge the `fielddata` breaker at build
    (make_room admission: LRU stacks shed under pressure before anything
    429s), release on any removal, and are keyed by the shard's exact
    segment-id set — refresh/merge produce a new key, and the stale
    sibling is invalidated on the next put (plus eagerly via drop_stale).
    Oversized stacks (estimate beyond the byte budget) are declined up
    front: callers fall back to the per-segment loop, never raise."""

    def __init__(self, max_bytes: int = 0):
        self.oversized = 0
        self.declined = 0                # breaker refused the build charge
        self.cache = Cache("segment_stack", max_bytes=max_bytes,
                           weigher=lambda e: e.nbytes,
                           removal_listener=self._on_removal)

    def _on_removal(self, key, entry: _StackEntry, reason: str) -> None:
        if reason == RemovalReason.EVICTED:
            tracing.add_event("cache.evict", tier="segment_stack",
                              reason=reason, bytes=entry.nbytes)
        if entry.breaker is not None:
            entry.breaker.release(entry.nbytes)

    def get_or_build(self, index_name, shard_id, incarnation, segments,
                     breaker=None):
        """The shard's SegmentStack, building (and breaker-charging) on
        first use. Returns None when declined — empty shard, oversized
        stack, or breaker pressure even after shedding other stacks."""
        from ..search import stacked as stacked_mod
        live = [s for s in segments if s.n_docs > 0]
        if not live:
            return None
        key = (index_name, shard_id, incarnation,
               tuple(s.seg_id for s in live))
        with tracing.span("cache.get", tier="segment_stack",
                          shard=shard_id) as sp:
            ent = self.cache.get(key)
            if sp is not None:
                sp.attrs["hit"] = ent is not None
        if ent is not None:
            return ent.stack
        est = stacked_mod.estimate_stack_bytes(live)
        if self.cache.max_bytes > 0 and est > self.cache.max_bytes:
            self.oversized += 1
            return None
        if breaker is not None:
            try:
                self.cache.make_room(breaker, est)
            except Exception:  # noqa: BLE001 — degrade, never 429 a search
                self.declined += 1
                return None
        try:
            stack = stacked_mod.build_stack(live)
        except BaseException:
            if breaker is not None:
                breaker.release(est)
            raise
        if stack is None:
            if breaker is not None:
                breaker.release(est)
            return None
        nbytes = stack.nbytes
        if breaker is not None and nbytes != est:
            if nbytes > est:      # true up estimate drift without re-tripping
                breaker.add_estimate(nbytes - est, check=False)
            else:
                breaker.release(est - nbytes)
        entry = _StackEntry(stack, nbytes, breaker, index_name)
        if self.cache.put(key, entry):
            # a refresh/merge changed the segment set: the predecessor
            # entry for this shard frees its device bytes NOW
            self.cache.invalidate_where(
                lambda k, _e: k[:3] == key[:3] and k != key)
        elif breaker is not None:
            breaker.release(nbytes)   # refused by budget: nothing retained
        return stack

    def drop_stale(self, index_name: str, valid: set) -> int:
        """Invalidate entries whose (shard, segment-id set) is no longer
        the live one — the refresh/merge hook (IndexService)."""
        return self.cache.invalidate_where(
            lambda k, _e: k[0] == index_name and (k[1], k[3]) not in valid)

    def clear(self, indices: list[str] | None = None) -> int:
        if indices is None:
            return self.cache.clear()
        want = set(indices)
        return self.cache.invalidate_where(lambda k, _e: k[0] in want)

    def stats(self) -> dict:
        out = self.cache.stats()
        out["oversized"] = self.oversized
        out["declined"] = self.declined
        return out


class MeshStackCache:
    """Per-index packed MESH stacks for the mesh-sharded query lane
    (parallel/mesh_exec.py): all S shards' segment stacks one level up,
    sharded over the device mesh's "shard" axis. Same lifecycle contract
    as SegmentStackCache — fielddata-breaker-charged at build through
    make_room admission, released on any removal, keyed by the index's
    FULL per-shard segment-id sets so any refresh/flush/merge produces a
    new key (stale siblings die on the next put and eagerly via
    drop_stale). Oversized estimates are declined up front: callers fall
    back to the concurrent fan-out, never raise."""

    def __init__(self, max_bytes: int = 0):
        self.oversized = 0
        self.declined = 0
        self.cache = Cache("mesh_stack", max_bytes=max_bytes,
                           weigher=lambda e: e.nbytes,
                           removal_listener=self._on_removal)

    def _on_removal(self, key, entry: _StackEntry, reason: str) -> None:
        if reason == RemovalReason.EVICTED:
            tracing.add_event("cache.evict", tier="mesh_stack",
                              reason=reason, bytes=entry.nbytes)
        if entry.breaker is not None:
            entry.breaker.release(entry.nbytes)

    def get_or_build(self, index_name, incarnation, per_shard_segments,
                     breaker=None, pool=None):
        """The index's MeshStack, building (and breaker-charging) on first
        use. None when declined — no live docs, no mesh topology on this
        pool (fewer devices than shards), oversized, or breaker pressure
        even after shedding other stacks. `pool` is the owning node's
        DevicePool (None = legacy shared pool)."""
        from ..parallel import mesh_exec
        info = mesh_exec.mesh_for(len(per_shard_segments), pool=pool)
        if info is None:
            return None
        mesh, s_pad, n_replicas = info
        entries = tuple(
            (si, tuple(s.seg_id for s in segs if s.n_docs > 0))
            for si, segs in enumerate(per_shard_segments))
        if not any(ids for _si, ids in entries):
            return None
        key = (index_name, incarnation, entries,
               pool.devkey if pool is not None else None)
        with tracing.span("cache.get", tier="mesh_stack") as sp:
            ent = self.cache.get(key)
            if sp is not None:
                sp.attrs["hit"] = ent is not None
        if ent is not None:
            return ent.stack
        est = mesh_exec.estimate_mesh_stack_bytes(per_shard_segments)
        if self.cache.max_bytes > 0 and est > self.cache.max_bytes:
            self.oversized += 1
            return None
        if breaker is not None:
            try:
                self.cache.make_room(breaker, est)
            except Exception:  # noqa: BLE001 — degrade, never 429 a search
                self.declined += 1
                return None
        try:
            stack = mesh_exec.build_mesh_stack(per_shard_segments, mesh,
                                               s_pad, n_replicas, pool=pool)
        except BaseException:
            if breaker is not None:
                breaker.release(est)
            raise
        if stack is None:
            if breaker is not None:
                breaker.release(est)
            return None
        nbytes = stack.nbytes
        if breaker is not None and nbytes != est:
            if nbytes > est:
                breaker.add_estimate(nbytes - est, check=False)
            else:
                breaker.release(est - nbytes)
        entry = _StackEntry(stack, nbytes, breaker, index_name)
        if self.cache.put(key, entry):
            # a refresh/merge changed some shard's segment set: the
            # predecessor mesh stack frees its device bytes NOW
            self.cache.invalidate_where(
                lambda k, _e: k[:2] == key[:2] and k != key)
        elif breaker is not None:
            breaker.release(nbytes)
        return stack

    def drop_stale(self, index_name: str, valid: set) -> int:
        """Invalidate entries whose per-shard segment-id sets no longer
        match the live ones — rides the same refresh/flush/merge hook as
        the segment-stack tier (`valid` = {(shard, live seg-id tuple)})."""
        return self.cache.invalidate_where(
            lambda k, _e: k[0] == index_name and set(k[2]) != valid)

    def clear(self, indices: list[str] | None = None) -> int:
        if indices is None:
            return self.cache.clear()
        want = set(indices)
        return self.cache.invalidate_where(lambda k, _e: k[0] in want)

    def stats(self) -> dict:
        out = self.cache.stats()
        out["oversized"] = self.oversized
        out["declined"] = self.declined
        return out


class MeshVectorStackCache:
    """Per-(index, vector field) packed vector MESH stacks for the mesh
    kNN lane (parallel/mesh_knn.py): every shard's vector columns one
    level up, sharded over the device mesh's "shard" axis. Same lifecycle
    contract as MeshStackCache — fielddata-breaker-charged at build
    through make_room admission, released on any removal, keyed by the
    index's FULL per-shard segment-id sets. IVF packs attach lazily to a
    cached stack (their tensors are immutable alongside the segment set);
    their bytes true up against the same breaker via `charge_extra` and
    release with the entry."""

    def __init__(self, max_bytes: int = 0):
        self.oversized = 0
        self.declined = 0
        self.cache = Cache("mesh_vector_stack", max_bytes=max_bytes,
                           weigher=lambda e: e.nbytes,
                           removal_listener=self._on_removal)

    def _on_removal(self, key, entry: _StackEntry, reason: str) -> None:
        if reason == RemovalReason.EVICTED:
            tracing.add_event("cache.evict", tier="mesh_vector_stack",
                              reason=reason, bytes=entry.nbytes)
        if entry.breaker is not None:
            entry.breaker.release(entry.nbytes)

    def get_or_build(self, index_name, incarnation, field,
                     per_shard_segments, breaker=None, pool=None):
        """The index's MeshVectorStack for `field`, building (and
        breaker-charging) on first use. None when declined. `pool` is the
        owning node's DevicePool (None = legacy shared pool)."""
        from ..parallel import mesh_exec, mesh_knn
        info = mesh_exec.mesh_for(len(per_shard_segments), pool=pool)
        if info is None:
            return None
        mesh, s_pad, n_replicas = info
        entries = tuple(
            (si, tuple(s.seg_id for s in segs if s.n_docs > 0))
            for si, segs in enumerate(per_shard_segments))
        if not any(ids for _si, ids in entries):
            return None
        key = (index_name, field, incarnation, entries,
               pool.devkey if pool is not None else None)
        with tracing.span("cache.get", tier="mesh_vector_stack") as sp:
            ent = self.cache.get(key)
            if sp is not None:
                sp.attrs["hit"] = ent is not None
        if ent is not None:
            return ent.stack
        est = mesh_knn.estimate_vector_stack_bytes(per_shard_segments,
                                                   field)
        if est == 0:
            return None
        if self.cache.max_bytes > 0 and est > self.cache.max_bytes:
            self.oversized += 1
            return None
        if breaker is not None:
            try:
                self.cache.make_room(breaker, est)
            except Exception:  # noqa: BLE001 — degrade, never 429 a search
                self.declined += 1
                return None
        try:
            stack = mesh_knn.build_vector_stack(
                per_shard_segments, field, mesh, s_pad, n_replicas,
                pool=pool)
        except BaseException:
            if breaker is not None:
                breaker.release(est)
            raise
        if stack is None:
            if breaker is not None:
                breaker.release(est)
            return None
        nbytes = stack.nbytes
        if breaker is not None and nbytes != est:
            if nbytes > est:
                breaker.add_estimate(nbytes - est, check=False)
            else:
                breaker.release(est - nbytes)
        entry = _StackEntry(stack, nbytes, breaker, index_name)
        if self.cache.put(key, entry):
            # a refresh/merge changed some shard's segment set: stale
            # vector stacks for this (index, field) free device bytes NOW
            self.cache.invalidate_where(
                lambda k, _e: k[:3] == key[:3] and k != key)
        elif breaker is not None:
            breaker.release(nbytes)
        return stack

    def drop_stale(self, index_name: str, valid: set) -> int:
        """Invalidate entries whose per-shard segment-id sets no longer
        match the live ones (same refresh/flush/merge hook as the mesh
        stack tier)."""
        return self.cache.invalidate_where(
            lambda k, _e: k[0] == index_name and set(k[3]) != valid)

    def clear(self, indices: list[str] | None = None) -> int:
        if indices is None:
            return self.cache.clear()
        want = set(indices)
        return self.cache.invalidate_where(lambda k, _e: k[0] in want)

    def stats(self) -> dict:
        out = self.cache.stats()
        out["oversized"] = self.oversized
        out["declined"] = self.declined
        return out


class _PercEntry:
    __slots__ = ("corpus", "nbytes", "breaker", "index_name")

    def __init__(self, corpus, nbytes, breaker, index_name):
        self.corpus = corpus
        self.nbytes = nbytes
        self.breaker = breaker
        self.index_name = index_name


class PercolatorRegistryCache:
    """Per-index percolate corpora for the reverse-search lane
    (search/percolate_exec.py): the registered `.percolator` queries
    extracted into the dense leaf-slot grid + postings CSR. Keyed by the
    index's monotonic per-engine percolator GENERATION — any `.percolator`
    write or delete bumps it, so a delete-then-register of the same count
    can never serve a stale corpus (the ISSUE 18 `_registry_key` bugfix
    keys the same way). Entries charge the `fielddata` breaker through
    make_room admission (LRU corpora shed under pressure; a refused build
    returns None and percolation falls back to the index service's
    one-slot memo — degrade, never 429), release on any removal, and the
    stale predecessor generation dies on the next put."""

    def __init__(self, max_bytes: int = 0):
        self.declined = 0                # breaker refused the build charge
        self.cache = Cache("percolator_registry", max_bytes=max_bytes,
                           weigher=lambda e: e.nbytes,
                           removal_listener=self._on_removal)

    def _on_removal(self, key, entry: _PercEntry, reason: str) -> None:
        if reason == RemovalReason.EVICTED:
            tracing.add_event("cache.evict", tier="percolator_registry",
                              reason=reason, bytes=entry.nbytes)
        if entry.breaker is not None:
            entry.breaker.release(entry.nbytes)

    def get_or_build(self, svc, generation, build):
        """The index's PercolateCorpus at `generation`, building (and
        breaker-charging) on first use. None when declined — breaker
        pressure even after shedding other corpora (the caller keeps a
        plain memo so percolation still runs)."""
        name = getattr(svc, "name", None)
        key = (name, generation)
        with tracing.span("cache.get", tier="percolator_registry") as sp:
            ent = self.cache.get(key)
            if sp is not None:
                sp.attrs["hit"] = ent is not None
        if ent is not None:
            return ent.corpus
        breakers = getattr(svc, "breakers", None)
        breaker = breakers.breaker("fielddata") \
            if breakers is not None else None
        from ..search.percolator import parsed_registry
        est = 4096 + 512 * len(parsed_registry(svc))
        if breaker is not None:
            try:
                self.cache.make_room(breaker, est)
            except Exception:  # noqa: BLE001 — degrade, never 429
                self.declined += 1
                return None
        try:
            corpus = build(svc)
        except BaseException:
            if breaker is not None:
                breaker.release(est)
            raise
        if corpus is None:
            if breaker is not None:
                breaker.release(est)
            return None
        nbytes = corpus.nbytes
        if breaker is not None and nbytes != est:
            if nbytes > est:  # true up estimate drift without re-tripping
                breaker.add_estimate(nbytes - est, check=False)
            else:
                breaker.release(est - nbytes)
        entry = _PercEntry(corpus, nbytes, breaker, name)
        if self.cache.put(key, entry):
            # a registration/delete bumped the generation: the stale
            # predecessor corpus frees its bytes NOW
            self.cache.invalidate_where(
                lambda k, _e: k[0] == key[0] and k != key)
        elif breaker is not None:
            breaker.release(nbytes)   # refused by budget: nothing retained
        return corpus

    def clear(self, indices: list[str] | None = None) -> int:
        if indices is None:
            return self.cache.clear()
        want = set(indices)
        return self.cache.invalidate_where(
            lambda _k, e: e.index_name in want)

    def stats(self) -> dict:
        out = self.cache.stats()
        out["declined"] = self.declined
        return out


class IndicesCacheService:
    """The node's cache roster. One `stats()`/`clear()` surface over the
    three tiers; per-index packed-view caches register here so their
    bytes join the same walk."""

    def __init__(self, settings=None, breakers=None, clock=None):
        get = settings.get if settings is not None else lambda k, d=None: d
        total = breakers.total_limit if breakers is not None \
            and breakers.total_limit > 0 else 6 << 30
        req_bytes = parse_size(get("indices.requests.cache.size", "1%"),
                               total, default=total // 100)
        ttl_raw = get("indices.requests.cache.expire")
        ttl_s = None
        if ttl_raw not in (None, ""):
            from ..mapping.mapper import parse_ttl_ms
            try:
                ttl_s = parse_ttl_ms(ttl_raw) / 1000.0
            except Exception:  # noqa: BLE001 — bad setting != no cache
                ttl_s = None
        self.request_cache = IndicesRequestCache(
            max_bytes=req_bytes, ttl_s=ttl_s,
            breaker=breakers.breaker("request")
            if breakers is not None else None,
            clock=clock)
        try:
            plan_entries = int(get("indices.queries.cache.count", 1024))
        except (TypeError, ValueError):
            plan_entries = 1024
        self.query_plan = Cache(
            "query_plan", max_entries=plan_entries,
            max_bytes=parse_size(get("indices.queries.cache.size", "1%"),
                                 total, default=total // 100),
            clock=clock)
        self.fielddata = FielddataCache(
            max_bytes=parse_size(get("indices.fielddata.cache.size", 0),
                                 total, default=0))
        # packed segment stacks for the stacked dense lane: a real slice of
        # device memory (stacks duplicate segment residency), so the budget
        # defaults to 10% of the breaker total
        self.segment_stacks = SegmentStackCache(
            max_bytes=parse_size(get("indices.stacked.cache.size", "10%"),
                                 total, default=total // 10))
        # mesh stacks duplicate the whole index's segment residency onto
        # the device mesh — same default budget slice as segment stacks
        self.mesh_stacks = MeshStackCache(
            max_bytes=parse_size(get("indices.mesh.cache.size", "10%"),
                                 total, default=total // 10))
        # IVF cluster indexes for the ANN kNN lane (centroids + CSR ≈ 8
        # bytes/doc + nlist*dims*4 — far below the vectors themselves)
        self.ann_indexes = AnnIndexCache(
            max_bytes=parse_size(get("indices.ann.cache.size", "10%"),
                                 total, default=total // 10))
        # packed vector mesh stacks for the mesh kNN lane duplicate the
        # index's vector residency onto the device mesh — same budget
        # slice as the text mesh stacks
        self.mesh_vector_stacks = MeshVectorStackCache(
            max_bytes=parse_size(get("indices.mesh.cache.size", "10%"),
                                 total, default=total // 10))
        # registered-query corpora for the dense percolate lane: host-side
        # CSR + leaf grids (bytes, not device residency) — a 1% slice caps
        # pathological registries without starving real tiers
        self.percolator_registry = PercolatorRegistryCache(
            max_bytes=parse_size(get("indices.percolator.cache.size", "1%"),
                                 total, default=total // 100))
        # per-index packed-view caches (serving views) register here so
        # their byte totals surface without the service owning them
        self._registered: "weakref.WeakValueDictionary[str, Cache]" = \
            weakref.WeakValueDictionary()

    # -- query-plan tier ---------------------------------------------------

    _UNCACHEABLE_MARKERS = ('"now', '"template"', '"indexed_shape"',
                            '"script"')

    def plan_key(self, index: str, incarnation: int, mapping_version: int,
                 query) -> tuple | None:
        """Cache key for a parsed query, or None when the body must not be
        cached (unserializable, date math, external-state lookups)."""
        try:
            qj = json.dumps(query, sort_keys=True)
        except (TypeError, ValueError):
            return None
        if any(m in qj for m in self._UNCACHEABLE_MARKERS):
            return None
        return (index, incarnation, mapping_version, qj)

    def get_plan(self, key):
        if key is None:
            return None
        with tracing.span("cache.get", tier="query_plan") as sp:
            node = self.query_plan.get(key)
            if sp is not None:
                sp.attrs["hit"] = node is not None
        return node

    def put_plan(self, key, node) -> None:
        if key is not None:
            # weight: canonical-JSON size × a small tree-overhead factor —
            # exactness doesn't matter for a host-side tree, bounding does
            with tracing.span("cache.put", tier="query_plan"):
                self.query_plan.put(key, node,
                                    weight=len(key[3]) * 4 + 256)

    # -- roster ------------------------------------------------------------

    def register(self, name: str, cache: Cache) -> None:
        self._registered[name] = cache

    def clear(self, *, query: bool = False, request: bool = False,
              fielddata: bool = False,
              indices: list[str] | None = None) -> dict:
        out = {}
        if request:
            out["request"] = self.request_cache.clear(indices)
        if query:
            if indices is None:
                out["query"] = self.query_plan.clear()
            else:
                want = set(indices)
                out["query"] = self.query_plan.invalidate_where(
                    lambda k, _v: k[0] in want)
            # packed segment/mesh stacks and IVF cluster indexes are
            # query-execution structures: they ride the `query` tier flag
            # (removal releases their breaker charge)
            out["segment_stack"] = self.segment_stacks.clear(indices)
            out["mesh_stack"] = self.mesh_stacks.clear(indices)
            out["mesh_vector_stack"] = self.mesh_vector_stacks.clear(indices)
            out["ann_index"] = self.ann_indexes.clear(indices)  # + quant
            out["percolator_registry"] = \
                self.percolator_registry.clear(indices)
        if fielddata:
            out["fielddata"] = self.fielddata.clear(indices)
        return out

    def stats(self) -> dict:
        out = {"request": self.request_cache.stats(),
               "query_plan": self.query_plan.stats(),
               "fielddata": self.fielddata.stats(),
               "segment_stack": self.segment_stacks.stats(),
               "mesh_stack": self.mesh_stacks.stats(),
               "mesh_vector_stack": self.mesh_vector_stacks.stats(),
               "ann_index": self.ann_indexes.stats(),
               "ann_quant": self.ann_indexes.quant_stats(),
               "percolator_registry": self.percolator_registry.stats()}
        for name, cache in list(self._registered.items()):
            out[name] = cache.stats()
        return out

    def close(self) -> None:
        self.request_cache.cache.clear()
        self.query_plan.clear()
        self.fielddata.cache.clear()
        self.segment_stacks.cache.clear()
        self.mesh_stacks.cache.clear()
        self.mesh_vector_stacks.cache.clear()
        self.ann_indexes.cache.clear()
        self.ann_indexes.quant.clear()
        self.percolator_registry.cache.clear()

    def leak_report(self) -> list[str]:
        """Cache-entry accounting for the chaos leak detector: every tier
        whose stats expose memory bytes must drain after a full clear —
        a non-zero residue means an entry holds breaker charge with no
        owner left to release it."""
        self.clear(query=True, request=True, fielddata=True)
        problems = []
        for tier, st in self.stats().items():
            bytes_ = st.get("memory_size_in_bytes", 0)
            if bytes_:
                problems.append(
                    f"cache tier [{tier}] holds {bytes_} bytes after clear")
        return problems
