"""Node-level indices services (ref org.elasticsearch.indices.*): the
cross-index cache subsystem lives here."""

from .cache_service import IndicesCacheService  # noqa: F401
