"""ClusterService: the single-writer state machine + publish.

Analog of the reference's InternalClusterService
(/root/reference/src/main/java/org/elasticsearch/cluster/service/
InternalClusterService.java:151 — ONE prioritized state thread serializes all
mutations; submitStateUpdateTask :260-285; on master, publish-then-notify
:463-464) and of the publish action
(discovery/zen/publish/PublishClusterStateAction.java:86-98 — the full state
goes to every node; receivers apply and ack).

Tasks are plain functions `task(current: ClusterState) -> ClusterState|None`
(None = no change). Publishing sends the whole serialized state over the
transport seam to every other node; each node's apply callback runs its
reconciler (node.py) before the publish returns — so a task's completion
implies every reachable node has applied the state, the ack semantics of
AckedClusterStateUpdateTask.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

from .state import ClusterState
from .transport import ConnectTransportException, TransportService

PUBLISH_ACTION = "internal:discovery/zen/publish"


class ClusterService:
    def __init__(self, node_id: str, transport: TransportService,
                 apply_fn: Callable[[ClusterState], None]):
        self.node_id = node_id
        self.transport = transport
        self._apply_fn = apply_fn
        self.state = ClusterState.empty()
        self._tasks: "queue.Queue[tuple]" = queue.Queue()
        self._state_lock = threading.RLock()
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"clusterState[{node_id}]", daemon=True)
        self._thread.start()
        transport.register_handler(PUBLISH_ACTION, self._on_publish)

    # -- reads -------------------------------------------------------------

    def current(self) -> ClusterState:
        with self._state_lock:
            return self.state

    @property
    def is_master(self) -> bool:
        return self.current().master_node == self.node_id

    # -- writes (master only) ----------------------------------------------

    def submit_task(self, source: str,
                    task: Callable[[ClusterState], ClusterState | None],
                    wait: bool = True, timeout: float = 30.0) -> ClusterState:
        """Enqueue a state-update task; with wait=True blocks until the task
        ran AND the resulting state was published to every reachable node.
        Must not be called with wait=True from the state thread itself."""
        if wait and threading.current_thread() is self._thread:
            raise RuntimeError("sync submit from the cluster-state thread")
        done = threading.Event() if wait else None
        box: dict[str, Any] = {}
        self._tasks.put((source, task, done, box))
        if not wait:
            return self.current()
        if not done.wait(timeout):
            raise TimeoutError(f"cluster task [{source}] timed out")
        if "error" in box:
            raise box["error"]
        return box["state"]

    def _run(self) -> None:
        while not self._closed.is_set():
            try:
                source, task, done, box = self._tasks.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                new_state = task(self.current())
                if new_state is not None:
                    self._publish(new_state)
                box["state"] = self.current()
            except Exception as e:  # noqa: BLE001 — surface to submitter
                box["error"] = e
            finally:
                if done is not None:
                    done.set()

    def _publish(self, new_state: ClusterState) -> None:
        """Apply locally, then push the full state to every other node
        (ref PublishClusterStateAction.java:86-98). Unreachable nodes are
        skipped — fault detection removes them in a later task."""
        self._apply_local(new_state)
        for node_id in sorted(new_state.nodes):
            if node_id == self.node_id:
                continue
            try:
                self.transport.send(node_id, PUBLISH_ACTION, new_state.data)
            except ConnectTransportException:
                continue

    def _apply_local(self, new_state: ClusterState) -> None:
        with self._state_lock:
            self.state = new_state
        self._apply_fn(new_state)

    def apply_local(self, new_state: ClusterState) -> None:
        """Apply without publishing — the step-down path (we lost quorum and
        can't reach anyone to publish to anyway)."""
        self._apply_local(new_state)

    def reset(self) -> None:
        """Forget the applied state (rejoin path): with master_node back to
        None, the next publish is accepted regardless of version — the
        majority's history replaces ours wholesale."""
        with self._state_lock:
            self.state = ClusterState.empty()

    # -- receive side ------------------------------------------------------

    def _on_publish(self, from_id: str, data: dict) -> dict:
        incoming = ClusterState(data)
        with self._state_lock:
            if incoming.version <= self.state.version and \
                    self.state.master_node is not None:
                # stale publish (e.g. a deposed master): reject, like
                # ZenDiscovery.handleNewClusterStateFromMaster version guard
                return {"applied": False, "version": self.state.version}
            self.state = incoming
        self._apply_fn(incoming)
        return {"applied": True, "version": incoming.version}

    def close(self) -> None:
        self._closed.set()
        self._thread.join(timeout=5)
