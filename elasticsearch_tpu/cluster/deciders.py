"""Composable allocation decider chain (ISSUE 15).

The analog of the reference's decider roster under
cluster/routing/allocation/decider/ (18 deciders chained by
AllocationDeciders.java — the first NO wins, THROTTLE defers): each
decider answers "may this shard copy go on / stay on this node?" with a
verdict AND an explanation, so `/_cluster/allocation/explain` can show
per-decider reasoning instead of a bare boolean.

Deciders here are STATELESS over the cluster state they are handed —
every setting is read live from cluster-level
(`state.data["settings"]`) or index-level metadata, so a settings
update changes behavior on the next allocation round with no plumbing.
The chain keeps one mutable thing: a per-decider veto counter feeding
`es_allocation_decider_vetoes_total{decider=}`.

Roster (reference analog in parens):
  * same_shard      — never two copies of a shard on one node
                      (SameShardAllocationDecider; also enforced
                      structurally by the allocator's holder set)
  * awareness       — spread copies across node attribute values, e.g.
                      zones (AwarenessAllocationDecider)
  * filter          — index.routing.allocation.include/exclude/require
                      + the cluster.routing.allocation.* forms
                      (FilterAllocationDecider)
  * shards_limit    — index.routing.allocation.total_shards_per_node /
                      cluster.routing.allocation.total_shards_per_node
                      (ShardsLimitAllocationDecider)
  * throttling      — cluster.routing.allocation.node_concurrent_recoveries
                      caps INITIALIZING copies per node
                      (ThrottlingAllocationDecider — THROTTLE, not NO)
  * disk            — the low/high watermark gate, wrapping
                      cluster/info.DiskThresholdDecider
                      (DiskThresholdDecider.java)
"""

from __future__ import annotations

from .state import INITIALIZING, UNASSIGNED

YES = "YES"
THROTTLE = "THROTTLE"
NO = "NO"


class Decision:
    """One decider's verdict. Truthy only when YES — a THROTTLE defers
    the allocation to a later round without counting as a veto."""

    __slots__ = ("verdict", "decider", "explanation")

    def __init__(self, verdict: str, decider: str, explanation: str):
        self.verdict = verdict
        self.decider = decider
        self.explanation = explanation

    def __bool__(self) -> bool:
        return self.verdict == YES

    def __repr__(self) -> str:
        return f"Decision({self.verdict}, {self.decider}: {self.explanation})"

    def as_dict(self) -> dict:
        return {"decider": self.decider, "decision": self.verdict,
                "explanation": self.explanation}


def cluster_setting(state, key: str, default=None):
    """Cluster-level dynamic setting (state.data['settings'] — the same
    live-read seam the hedge settings use)."""
    return (state.data.get("settings") or {}).get(key, default)


def index_setting(state, index: str, key: str, default=None):
    """Index-level setting; the prefixed `index.*` key wins over the
    bare creation-time form (repo-wide convention)."""
    meta = state.indices.get(index) or {}
    s = meta.get("settings") or {}
    return s.get(f"index.{key}", s.get(key, default))


def node_attr(state, node_id: str, key: str) -> str | None:
    """A node's filterable attribute: `_id`/`_name` are built in, the
    rest come from the attributes the node declared at join time."""
    n = state.nodes.get(node_id) or {}
    if key == "_id":
        return n.get("id", node_id)
    if key == "_name":
        return n.get("name", node_id)
    v = (n.get("attributes") or {}).get(key)
    return None if v is None else str(v)


def _csv(v) -> list[str]:
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return [str(x) for x in v]
    return [p.strip() for p in str(v).split(",") if p.strip()]


class AllocationDecider:
    """Base: everything is allowed. `can_allocate` gates new placements
    (and relocation targets); `can_remain` gates whether a STARTED copy
    may stay put — a NO there makes rebalance move it off."""

    name = "base"

    def can_allocate(self, state, index: str, sid: int,
                     node_id: str) -> Decision:
        return Decision(YES, self.name, "allowed")

    def can_remain(self, state, index: str, sid: int,
                   node_id: str) -> Decision:
        return Decision(YES, self.name, "allowed")


class SameShardDecider(AllocationDecider):
    """Never two copies of one shard on one node (the invariant the
    allocator also enforces structurally; stated here so explain output
    shows WHY a holder node is not a candidate)."""

    name = "same_shard"

    def can_allocate(self, state, index, sid, node_id):
        for c in state.routing[index][sid]:
            if c["node"] == node_id and c["state"] != UNASSIGNED:
                return Decision(NO, self.name,
                                f"node [{node_id}] already holds a copy "
                                f"of [{index}][{sid}]")
        return Decision(YES, self.name, "no copy of this shard on node")


class AwarenessDecider(AllocationDecider):
    """Spread a shard's copies across the values of the awareness
    attributes (`cluster.routing.allocation.awareness.attributes`,
    e.g. "zone"): no attribute value may hold more than its balanced
    share ceil(copies / distinct values) of the shard's copies."""

    name = "awareness"

    def can_allocate(self, state, index, sid, node_id):
        attrs = _csv(cluster_setting(
            state, "cluster.routing.allocation.awareness.attributes"))
        if not attrs:
            return Decision(YES, self.name, "no awareness attributes set")
        copies = state.routing[index][sid]
        for attr in attrs:
            my_val = node_attr(state, node_id, attr)
            if my_val is None:
                continue        # unlabeled nodes are exempt (ref forced
                                # awareness is opt-in; we mirror that)
            values = {node_attr(state, n, attr) for n in state.nodes}
            values.discard(None)
            if len(values) <= 1:
                continue        # one zone: nothing to spread across
            per_val: dict[str, int] = {}
            for c in copies:
                if c["node"] is None or c["state"] == UNASSIGNED:
                    continue
                v = node_attr(state, c["node"], attr)
                if v is not None:
                    per_val[v] = per_val.get(v, 0) + 1
            total = sum(per_val.values()) + 1     # + the copy being placed
            ceiling = -(-total // len(values))    # ceil
            if per_val.get(my_val, 0) + 1 > ceiling:
                return Decision(
                    NO, self.name,
                    f"too many copies in [{attr}={my_val}] "
                    f"({per_val.get(my_val, 0) + 1} > balanced {ceiling})")
        return Decision(YES, self.name, "copies balanced across zones")


class FilterDecider(AllocationDecider):
    """index.routing.allocation.{include,exclude,require}.<attr> plus
    the cluster.routing.allocation.* forms (FilterAllocationDecider):
    require = every rule must match; include = at least one listed
    value matches (when any include rule exists); exclude = no listed
    value may match. A STARTED copy violating a filter cannot REMAIN —
    that is what makes `exclude._id: node-1` drain a node."""

    name = "filter"

    _KINDS = ("require", "include", "exclude")

    def _rules(self, state, index) -> dict[str, dict[str, list[str]]]:
        out: dict[str, dict[str, list[str]]] = {k: {} for k in self._KINDS}
        cs = state.data.get("settings") or {}
        for key, v in cs.items():
            for kind in self._KINDS:
                pfx = f"cluster.routing.allocation.{kind}."
                if key.startswith(pfx):
                    out[kind][key[len(pfx):]] = _csv(v)
        meta = state.indices.get(index) or {}
        for key, v in (meta.get("settings") or {}).items():
            bare = key[6:] if key.startswith("index.") else key
            for kind in self._KINDS:
                pfx = f"routing.allocation.{kind}."
                if bare.startswith(pfx):
                    out[kind][bare[len(pfx):]] = _csv(v)
        return out

    def _check(self, state, index, node_id) -> Decision:
        rules = self._rules(state, index)
        for attr, vals in rules["require"].items():
            got = node_attr(state, node_id, attr)
            if got not in vals:
                return Decision(
                    NO, self.name,
                    f"node [{attr}={got}] does not match required "
                    f"{vals}")
        if rules["include"]:
            hit = any(node_attr(state, node_id, attr) in vals
                      for attr, vals in rules["include"].items())
            if not hit:
                return Decision(
                    NO, self.name,
                    f"node matches no include rule "
                    f"{dict(rules['include'])}")
        for attr, vals in rules["exclude"].items():
            got = node_attr(state, node_id, attr)
            if got in vals:
                return Decision(
                    NO, self.name,
                    f"node [{attr}={got}] is excluded by {vals}")
        return Decision(YES, self.name, "node passes allocation filters")

    def can_allocate(self, state, index, sid, node_id):
        return self._check(state, index, node_id)

    def can_remain(self, state, index, sid, node_id):
        return self._check(state, index, node_id)


class ShardsLimitDecider(AllocationDecider):
    """Per-node shard-count ceilings:
    index.routing.allocation.total_shards_per_node counts THIS index's
    copies on the node; cluster.routing.allocation.total_shards_per_node
    counts all copies. Unset / <= 0 means unlimited."""

    name = "shards_limit"

    @staticmethod
    def _limit(v) -> int:
        try:
            return int(v)
        except (TypeError, ValueError):
            return 0

    def can_allocate(self, state, index, sid, node_id):
        idx_limit = self._limit(index_setting(
            state, index, "routing.allocation.total_shards_per_node"))
        clu_limit = self._limit(cluster_setting(
            state, "cluster.routing.allocation.total_shards_per_node"))
        if idx_limit <= 0 and clu_limit <= 0:
            return Decision(YES, self.name, "no shard-count limit set")
        on_node = on_node_index = 0
        for iname, shards in state.routing.items():
            for copies in shards:
                for c in copies:
                    if c["node"] == node_id and c["state"] != UNASSIGNED:
                        on_node += 1
                        if iname == index:
                            on_node_index += 1
        if idx_limit > 0 and on_node_index >= idx_limit:
            return Decision(
                NO, self.name,
                f"node holds {on_node_index} copies of [{index}] "
                f">= index limit {idx_limit}")
        if clu_limit > 0 and on_node >= clu_limit:
            return Decision(
                NO, self.name,
                f"node holds {on_node} copies >= cluster limit "
                f"{clu_limit}")
        return Decision(YES, self.name, "below shard-count limits")


class ConcurrentRecoveriesDecider(AllocationDecider):
    """cluster.routing.allocation.node_concurrent_recoveries (default 2)
    caps how many copies may be INITIALIZING on one node at once — a
    node drinking N recovery streams has no bandwidth for an N+1th.
    Verdict is THROTTLE, not NO: the placement retries next round."""

    name = "throttling"

    DEFAULT = 2

    def can_allocate(self, state, index, sid, node_id):
        try:
            limit = int(cluster_setting(
                state, "cluster.routing.allocation."
                "node_concurrent_recoveries", self.DEFAULT))
        except (TypeError, ValueError):
            limit = self.DEFAULT
        if limit <= 0:
            return Decision(YES, self.name, "recovery throttling disabled")
        active = sum(1 for _i, _s, c in state.assigned_shards(node_id)
                     if c["state"] == INITIALIZING)
        if active >= limit:
            return Decision(
                THROTTLE, self.name,
                f"node already running {active} recoveries "
                f">= node_concurrent_recoveries {limit}")
        return Decision(YES, self.name,
                        f"{active} of {limit} recovery slots in use")


class DiskDecider(AllocationDecider):
    """The watermark gate, wrapping cluster/info.DiskThresholdDecider:
    over the LOW watermark a node receives nothing new; over the HIGH
    watermark its copies cannot remain (rebalance drains it)."""

    name = "disk"

    def __init__(self, disk):
        self.disk = disk          # cluster/info.DiskThresholdDecider

    def can_allocate(self, state, index, sid, node_id):
        if self.disk.can_allocate(node_id):
            return Decision(YES, self.name, "below the low watermark")
        u = self.disk.info.usages.get(node_id)
        pct = f"{u.used_percent:.1f}%" if u is not None else "?"
        return Decision(NO, self.name,
                        f"disk {pct} used >= low watermark "
                        f"{self.disk.low_pct}%")

    def can_remain(self, state, index, sid, node_id):
        if not self.disk.should_evacuate(node_id):
            return Decision(YES, self.name, "below the high watermark")
        u = self.disk.info.usages.get(node_id)
        pct = f"{u.used_percent:.1f}%" if u is not None else "?"
        return Decision(NO, self.name,
                        f"disk {pct} used >= high watermark "
                        f"{self.disk.high_pct}% — evacuate")


class DeciderChain:
    """The composed roster. `can_allocate_shard` / `can_remain_shard`
    short-circuit on the first NO (counted into `vetoes`); a THROTTLE
    survives unless a later decider says NO. `explain` runs EVERY
    decider with no short-circuit and no veto accounting — it is the
    read-only path behind /_cluster/allocation/explain."""

    def __init__(self, deciders: list[AllocationDecider]):
        self.deciders = list(deciders)
        self.vetoes: dict[str, int] = {d.name: 0 for d in self.deciders}

    @staticmethod
    def default(disk=None) -> "DeciderChain":
        roster: list[AllocationDecider] = [
            SameShardDecider(), AwarenessDecider(), FilterDecider(),
            ShardsLimitDecider(), ConcurrentRecoveriesDecider()]
        if disk is not None:
            roster.append(DiskDecider(disk))
        return DeciderChain(roster)

    def can_allocate_shard(self, state, index: str, sid: int,
                           node_id: str) -> Decision:
        worst = Decision(YES, "chain", "all deciders allow")
        for d in self.deciders:
            dec = d.can_allocate(state, index, sid, node_id)
            if dec.verdict == NO:
                self.vetoes[d.name] = self.vetoes.get(d.name, 0) + 1
                return dec
            if dec.verdict == THROTTLE:
                worst = dec
        return worst

    def can_remain_shard(self, state, index: str, sid: int,
                         node_id: str) -> Decision:
        for d in self.deciders:
            dec = d.can_remain(state, index, sid, node_id)
            if dec.verdict == NO:
                self.vetoes[d.name] = self.vetoes.get(d.name, 0) + 1
                return dec
        return Decision(YES, "chain", "all deciders allow")

    def veto_total(self) -> int:
        return sum(self.vetoes.values())

    def explain(self, state, index: str, sid: int,
                node_id: str) -> dict:
        """Every decider's verdict for one (shard, node) pair — the
        node_decisions entry of the explain API."""
        decisions = [d.can_allocate(state, index, sid, node_id).as_dict()
                     for d in self.deciders]
        verdicts = {e["decision"] for e in decisions}
        overall = NO if NO in verdicts else (
            THROTTLE if THROTTLE in verdicts else YES)
        return {"node_id": node_id, "decision": overall,
                "deciders": decisions}
