"""ClusterInfoService: disk usage + shard size sampling for allocation.

The analog of /root/reference/src/main/java/org/elasticsearch/cluster/
InternalClusterInfoService.java (periodic NodesStats fs + IndicesStats
store sampling feeding DiskThresholdDecider — cluster/routing/allocation/
decider/DiskThresholdDecider.java: low watermark blocks NEW shard
allocation, high watermark triggers moves off the node).
"""

from __future__ import annotations

import shutil
import time


class DiskUsage:
    __slots__ = ("node_id", "total_bytes", "free_bytes")

    def __init__(self, node_id: str, total_bytes: int, free_bytes: int):
        self.node_id = node_id
        self.total_bytes = total_bytes
        self.free_bytes = free_bytes

    @property
    def used_percent(self) -> float:
        if not self.total_bytes:
            return 0.0
        return 100.0 * (self.total_bytes - self.free_bytes) \
            / self.total_bytes


class ClusterInfoService:
    """Samples per-node disk usage + per-shard sizes on demand (the
    reference samples on a 30s cadence; here refresh() is called by the
    master before allocation rounds — same data, pull not push)."""

    def __init__(self, usage_fn=None):
        # usage_fn(node_id, data_path) -> DiskUsage; overridable for tests
        self._usage_fn = usage_fn or self._real_usage
        self._paths: dict[str, str] = {}
        self.usages: dict[str, DiskUsage] = {}
        self.shard_sizes: dict[tuple[str, int, str], int] = {}
        self.last_refresh = 0.0

    @staticmethod
    def _real_usage(node_id: str, path: str) -> DiskUsage:
        try:
            du = shutil.disk_usage(path)
            return DiskUsage(node_id, du.total, du.free)
        except OSError:
            return DiskUsage(node_id, 0, 0)

    def register_node(self, node_id: str, data_path: str) -> None:
        self._paths[node_id] = data_path

    def refresh(self, shard_sizes: dict | None = None) -> None:
        for node_id, path in self._paths.items():
            self.usages[node_id] = self._usage_fn(node_id, path)
        if shard_sizes is not None:
            self.shard_sizes = dict(shard_sizes)
        self.last_refresh = time.time()

    def stats(self) -> dict:
        return {
            "nodes": {nid: {"total_in_bytes": u.total_bytes,
                            "free_in_bytes": u.free_bytes,
                            "used_percent": round(u.used_percent, 1)}
                      for nid, u in self.usages.items()},
            "shard_sizes": {f"{i}[{s}][{n}]": b
                            for (i, s, n), b in self.shard_sizes.items()},
        }


class DiskThresholdDecider:
    """Low/high watermark decider (ref DiskThresholdDecider.java:90):
    nodes above the LOW watermark receive no new shards; nodes above the
    HIGH watermark should shed shards (rebalance treats them as
    overloaded)."""

    def __init__(self, info: ClusterInfoService,
                 low_pct: float = 85.0, high_pct: float = 90.0,
                 enabled: bool = True):
        self.info = info
        self.low_pct = low_pct
        self.high_pct = high_pct
        self.enabled = enabled

    def can_allocate(self, node_id: str) -> bool:
        if not self.enabled:
            return True
        u = self.info.usages.get(node_id)
        return u is None or u.used_percent < self.low_pct

    def should_evacuate(self, node_id: str) -> bool:
        if not self.enabled:
            return False
        u = self.info.usages.get(node_id)
        return u is not None and u.used_percent >= self.high_pct
