"""TestCluster: N full nodes in one process, on one LocalTransport.

The analog of the reference's InternalTestCluster
(/root/reference/src/test/java/org/elasticsearch/test/InternalTestCluster.java:135
— multiple complete Node instances in one JVM, with helpers like
ensureGreen(), node kill/restart, and transport-level fault injection).
"""

from __future__ import annotations

import time

from .node import ClusterNode
from .transport import LocalTransport


class TestCluster:
    __test__ = False        # not a pytest class, despite the name

    def __init__(self, n_nodes: int, data_path: str,
                 minimum_master_nodes: int | None = None,
                 transport: str = "local", pods: int = 0):
        if minimum_master_nodes is None:
            minimum_master_nodes = n_nodes // 2 + 1
        if transport == "tcp":
            # real loopback sockets + binary frames (cluster/tcp.py) — the
            # same node code, the production wire
            from .tcp import TcpTransport
            self.network = TcpTransport()
        else:
            self.network = LocalTransport()
        self.data_path = data_path
        self.minimum_master_nodes = minimum_master_nodes
        self.pods = max(0, min(int(pods), n_nodes))
        self._pod_split = n_nodes       # fixed denominator: disjoint slices
        self.nodes: dict[str, ClusterNode] = {}
        self._seq = 0
        for _ in range(n_nodes):
            self.add_node()
        # min-id election (ref ElectMasterService sorted-node-id election)
        ids = sorted(self.nodes)
        master = self.nodes[ids[0]]
        master.bootstrap_as_master()
        for nid in ids[1:]:
            self.nodes[nid].join(ids[0])

    def _pod_settings(self, seq: int) -> dict | None:
        """Pod-mode node settings (ISSUE 19): every node OWNS a disjoint
        slice of the process's devices (`node.devices: auto:i/n` — the
        per-node-pool data plane, EXEC_LOCK-free), and nodes are spread
        over `pods` simulated hosts so inter-pod transport rides the
        "dcn" traffic class while intra-pod stays co-hosted."""
        if not self.pods:
            return None
        i = seq - 1
        n = max(self._pod_split, i + 1)
        return {"node.devices": f"auto:{i}/{n}",
                "node.host": f"pod{i * self.pods // n}"}

    def add_node(self, attrs: dict | None = None) -> ClusterNode:
        self._seq += 1
        node_id = f"node-{self._seq}"
        node = ClusterNode(node_id, self.data_path, self.network,
                           minimum_master_nodes=self.minimum_master_nodes,
                           attrs=attrs, settings=self._pod_settings(self._seq))
        self.nodes[node_id] = node
        master = self.master_node()
        if master is not None and master.node_id != node_id:
            node.join(master.node_id)
        return node

    # -- membership helpers -------------------------------------------------

    def master_node(self) -> ClusterNode | None:
        for node in self.nodes.values():
            st = node.cluster.current()
            if st.master_node == node.node_id and not node.closed:
                return node
        return None

    def client(self) -> ClusterNode:
        """Any live node works as coordinator (ref node client)."""
        for node in self.nodes.values():
            if not node.closed:
                return node
        raise RuntimeError("no live nodes")

    def node_holding_primary(self, index: str, shard: int) -> ClusterNode:
        state = self.client().cluster.current()
        primary = state.primary_of(index, shard)
        return self.nodes[primary["node"]]

    def kill_node(self, node_id: str) -> None:
        """Abrupt process death: unregister from the network WITHOUT any
        goodbye — peers discover via fault detection / failed sends."""
        node = self.nodes[node_id]
        node.closed = True
        node.transport.close()
        node.cluster.close()

    def restart_node(self, node_id: str) -> ClusterNode:
        """Bring a killed node back as a fresh process on the same data path
        and node id (ref InternalTestCluster.restartNode). The dead
        instance's engines are closed first — kill_node() simulates abrupt
        death and leaves them open, but a restart within one process must
        release the old file handles and breaker charges before the new
        instance re-opens the same directories."""
        old = self.nodes[node_id]
        if not old.closed:
            self.kill_node(node_id)
        # an in-flight recovery pull (the old applier thread) still owns
        # the shard directory the new instance will reuse: cancel it and
        # wait for a terminal stage before re-opening the same path
        with old._shards_lock:
            for holder in old._shards.values():
                holder.cancel_recovery = True
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with old._recoveries_lock:
                live = [r for r in old.recoveries.values()
                        if r["stage"] not in ("done", "failed", "cancelled")]
            if not live:
                break
            time.sleep(0.02)
        with old._shards_lock:
            for holder in old._shards.values():
                if holder.engine is not None:
                    holder.drop_searcher()
                    holder.engine.close()
                    holder.engine = None
        node = ClusterNode(node_id, self.data_path, self.network,
                           minimum_master_nodes=self.minimum_master_nodes,
                           attrs=old.attrs,
                           settings=getattr(old, "settings", None))
        self.nodes[node_id] = node
        master = self.master_node()
        if master is not None and master.node_id != node_id:
            node.join(master.node_id)
        return node

    def detect_once(self) -> None:
        """One explicit fault-detection round on every live node."""
        for node in list(self.nodes.values()):
            if not node.closed:
                node.fault_detection_round()

    def ensure_green(self, timeout: float = 15.0) -> None:
        self._ensure("green", timeout)

    def ensure_yellow_or_green(self, timeout: float = 15.0) -> None:
        self._ensure("yellow", timeout)

    def _ensure(self, at_least: str, timeout: float) -> None:
        ok = {"green"} if at_least == "green" else {"green", "yellow"}
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            client = self.client()
            h = client.health()
            if h["status"] in ok and h["master_node"] is not None:
                # every live node must have applied a state at this version
                # or later with the same master
                versions = [n.cluster.current().version
                            for n in self.nodes.values() if not n.closed]
                if min(versions) == max(versions):
                    return
            self.detect_once()
            time.sleep(0.02)
        raise TimeoutError(
            f"cluster not {at_least} within {timeout}s: "
            f"{self.client().health()}")

    def close(self) -> None:
        for node in self.nodes.values():
            if not node.closed:
                node.close()
        if hasattr(self.network, "close"):
            self.network.close()
