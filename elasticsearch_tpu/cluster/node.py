"""ClusterNode: a full node — cluster membership, shard hosting, replicated
writes, peer recovery, and the distributed search driver.

Maps to several reference components at once (SURVEY.md §2.3/§2.5/§2.7):
  * join/election/fault-report       — discovery/zen/ZenDiscovery.java:354,500
  * reconciler (state → local shards) — indices/cluster/
                                        IndicesClusterStateService.java:150
  * replicated write                 — action/support/replication/
                                        TransportShardReplicationOperationAction.java:67,118-120
  * peer recovery (file phase)       — indices/recovery/RecoverySourceHandler.java:149-195
  * search scatter-gather            — action/search/type/TransportSearchTypeAction.java:85-177

Design notes (TPU-first deviations from the reference, on purpose):
  * Replicas apply ops with external-version semantics: the primary assigns
    the version, replicas accept any strictly-newer version and treat
    version conflicts as "already applied" — this makes the
    file-copy-then-forward recovery race idempotent without uid-locks.
  * Recovery transfers the checksummed write-once segment files produced by
    index/store.py (flush under the engine lock = the reference's brief
    phase-3 write block), so a recovered replica loads tensors straight to
    device with zero re-tokenization.
  * Dynamic mappings derive deterministically on every copy (same doc ⇒ same
    inferred mapping), so replicas don't block acks on a master mapping
    round-trip; explicit put-mapping still flows through the master.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from ..index.engine import Engine, VersionConflictException
from ..mapping.mapper import MapperService
from ..parallel.routing import shard_id as route_shard
from ..search.shard_searcher import ShardSearcher
from .service import ClusterService
from .state import (INITIALIZING, STARTED, UNASSIGNED, ClusterState, allocate,
                    new_index_routing, remove_node)
from .transport import (ConnectTransportException, LocalTransport,
                        RemoteTransportException, TransportService)

A_JOIN = "internal:discovery/zen/join"
A_PING = "internal:discovery/zen/fd/ping"
A_NODE_FAILED = "internal:discovery/zen/fd/node_failed"
A_SHARD_STARTED = "internal:cluster/shard/started"
A_SHARD_FAILED = "internal:cluster/shard/failed"
A_CREATE_INDEX = "indices:admin/create"
A_DELETE_INDEX = "indices:admin/delete"
A_PUT_MAPPING = "indices:admin/mapping/put"
A_REFRESH = "indices:admin/refresh"
A_FLUSH = "indices:admin/flush"
A_WRITE_P = "indices:data/write/op[p]"
A_WRITE_R = "indices:data/write/op[r]"
A_GET = "indices:data/read/get"
A_QUERY = "indices:data/read/search[phase/query]"
A_FETCH = "indices:data/read/search[phase/fetch/id]"
A_RECOVERY = "internal:index/shard/recovery/files"


class NoMasterException(Exception):
    pass


class UnavailableShardsException(Exception):
    pass


class _ShardHolder:
    """One locally-hosted shard copy."""

    def __init__(self):
        self.engine: Engine | None = None
        self.lock = threading.RLock()
        self.recovering = False
        self.pending: list[dict] = []     # ops buffered during recovery
        self.searcher: tuple[tuple, ShardSearcher] | None = None


class ClusterNode:
    def __init__(self, node_id: str, data_path: str, network: LocalTransport,
                 minimum_master_nodes: int = 1):
        self.node_id = node_id
        self.data_path = os.path.join(data_path, node_id)
        os.makedirs(self.data_path, exist_ok=True)
        self.minimum_master_nodes = minimum_master_nodes
        self.transport = TransportService(node_id, network)
        self.cluster = ClusterService(node_id, self.transport,
                                      self._apply_cluster_state)
        self._shards: dict[tuple[str, int], _ShardHolder] = {}
        self._mappers: dict[str, MapperService] = {}
        self._shards_lock = threading.RLock()
        self.closed = False
        for action, handler in [
                (A_JOIN, self._on_join), (A_PING, self._on_ping),
                (A_NODE_FAILED, self._on_node_failed),
                (A_SHARD_STARTED, self._on_shard_started),
                (A_SHARD_FAILED, self._on_shard_failed),
                (A_CREATE_INDEX, self._on_create_index),
                (A_DELETE_INDEX, self._on_delete_index),
                (A_PUT_MAPPING, self._on_put_mapping),
                (A_REFRESH, self._on_refresh), (A_FLUSH, self._on_flush),
                (A_WRITE_P, self._on_primary_write),
                (A_WRITE_R, self._on_replica_write),
                (A_GET, self._on_get), (A_QUERY, self._on_query),
                (A_FETCH, self._on_fetch), (A_RECOVERY, self._on_recovery)]:
            self.transport.register_handler(action, handler)

    # ------------------------------------------------------------------
    # membership / election (ref ZenDiscovery.java:354 innerJoinCluster)
    # ------------------------------------------------------------------

    def bootstrap_as_master(self) -> None:
        """First node of a cluster: publish a state with self as master."""
        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            st.data["master_node"] = self.node_id
            st.nodes[self.node_id] = {"id": self.node_id,
                                      "name": self.node_id}
            return st
        self.cluster.submit_task("bootstrap-master", task)

    def join(self, master_id: str) -> None:
        self.transport.send(master_id, A_JOIN, {"node": self.node_id})
        # the publish that follows the join task delivers us the state
        deadline = time.monotonic() + 10
        while self.cluster.current().master_node is None:
            if time.monotonic() > deadline:
                raise NoMasterException(f"join to [{master_id}] not published")
            time.sleep(0.01)

    def _on_join(self, from_id: str, req: dict) -> dict:
        joining = req["node"]

        def task(cur: ClusterState) -> ClusterState | None:
            if joining in cur.nodes:
                return None
            st = cur.mutate()
            st.nodes[joining] = {"id": joining, "name": joining}
            allocate(st)
            return st
        self.cluster.submit_task(f"node-join[{joining}]", task, wait=False)
        return {"ok": True}

    def _on_ping(self, from_id: str, req: Any) -> dict:
        cur = self.cluster.current()
        return {"node": self.node_id, "version": cur.version,
                "master": cur.master_node}

    # -- fault detection (ref discovery/zen/fd/, SURVEY §5.3) ----------

    def fault_detection_round(self) -> None:
        """On the master: ping everyone; below quorum STEP DOWN (the
        ZenDiscovery.java:500-596 rejoin-on-quorum-loss guard), otherwise
        drop the dead (NodesFaultDetection). On a non-master: ping the
        master; if gone, elect (MasterFaultDetection + min-id election).
        Masterless: discover a master via the seed list and rejoin, or
        bootstrap an election if a quorum of seeds agrees there is none."""
        state = self.cluster.current()
        if state.master_node == self.node_id:
            dead = []
            for node_id in sorted(state.nodes):
                if node_id == self.node_id:
                    continue
                try:
                    self.transport.send(node_id, A_PING, {})
                except (ConnectTransportException, RemoteTransportException):
                    dead.append(node_id)
            live_count = len(state.nodes) - len(dead)
            if live_count < self.minimum_master_nodes:
                self._step_down()
                return
            for node_id in dead:
                self._remove_node(node_id)
        elif state.master_node is not None:
            try:
                resp = self.transport.send(state.master_node, A_PING, {})
                if resp.get("master") != state.master_node:
                    # our master stepped down (quorum loss): detach and go
                    # find whoever the majority elected
                    self.cluster.reset()
                    self._masterless_round()
            except (ConnectTransportException, RemoteTransportException):
                self._elect_after_master_loss(state)
        else:
            self._masterless_round()

    def _step_down(self) -> None:
        """Local-only demotion: no publish (we can't reach a quorum anyway).
        The next masterless round rejoins whatever master the majority
        elected — at which point the majority's state replaces ours and any
        writes acked during our minority reign are discarded (the same
        acked-write-loss window the reference documents for quorum loss)."""
        def task(cur: ClusterState) -> None:
            if cur.master_node != self.node_id:
                return None
            st = cur.mutate()
            st.data["master_node"] = None
            self.cluster.apply_local(st)
            return None     # already applied; nothing to publish
        self.cluster.submit_task("step-down[no quorum]", task, wait=False)

    def _masterless_round(self) -> None:
        """Find a live master through the seed list (the LocalTransport
        registry doubles as the unicast ping seed list) and rejoin it; if
        nobody has a master and we'd win a quorum election, take over."""
        seeds = [n for n in self.transport.network.connected_nodes()
                 if n != self.node_id]
        live = [self.node_id]
        masters: set[str] = set()
        for node_id in seeds:
            try:
                resp = self.transport.send(node_id, A_PING, {})
                live.append(node_id)
                if resp.get("master"):
                    masters.add(resp["master"])
            except (ConnectTransportException, RemoteTransportException):
                continue
        for master_id in sorted(masters):
            if master_id == self.node_id:
                continue
            try:
                self.rejoin(master_id)
                return
            except (ConnectTransportException, RemoteTransportException,
                    NoMasterException):
                continue
        if len(live) < self.minimum_master_nodes:
            return
        if min(live) == self.node_id:
            def task(cur: ClusterState) -> ClusterState:
                st = cur.mutate()
                st.data["master_node"] = self.node_id
                st.nodes[self.node_id] = {"id": self.node_id,
                                          "name": self.node_id}
                for node_id in list(st.nodes):
                    if node_id not in live:
                        remove_node(st, node_id)
                return st
            self.cluster.submit_task("become-master[bootstrap]", task)

    def rejoin(self, master_id: str) -> None:
        """Reset local cluster state and join `master_id` fresh — the path a
        healed minority node takes back into the majority. The master's next
        publish replaces our state wholesale; our reconciler then drops any
        shards the majority no longer assigns to us."""
        self.cluster.reset()
        self.join(master_id)

    def _elect_after_master_loss(self, state: ClusterState) -> None:
        """Min-id election among reachable members, guarded by the
        minimum_master_nodes quorum (ref ZenDiscovery.java:500-535 — losing
        quorum means NO master, not a split brain)."""
        dead_master = state.master_node
        live = [self.node_id]
        for node_id in sorted(state.nodes):
            if node_id in (self.node_id, dead_master):
                continue
            try:
                self.transport.send(node_id, A_PING, {})
                live.append(node_id)
            except (ConnectTransportException, RemoteTransportException):
                pass
        if len(live) < self.minimum_master_nodes:
            return      # no quorum: stay masterless rather than split-brain
        new_master = min(live)
        if new_master != self.node_id:
            return      # the winner will notice on its own round

        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            st.data["master_node"] = self.node_id
            if dead_master is not None:
                remove_node(st, dead_master)
            return st
        self.cluster.submit_task("become-master", task)

    def _remove_node(self, node_id: str) -> None:
        def task(cur: ClusterState) -> ClusterState | None:
            if node_id not in cur.nodes:
                return None
            st = cur.mutate()
            remove_node(st, node_id)
            return st
        self.cluster.submit_task(f"node-left[{node_id}]", task, wait=False)

    def _on_node_failed(self, from_id: str, req: dict) -> dict:
        """A peer reports a node unreachable (the reference treats transport
        disconnects as immediate failures, MasterFaultDetection.java:183-187).
        Verify before acting — the reporter's link may be the broken one."""
        node_id = req["node"]
        try:
            self.transport.send(node_id, A_PING, {})
            return {"removed": False}
        except (ConnectTransportException, RemoteTransportException):
            self._remove_node(node_id)
            return {"removed": True}

    # ------------------------------------------------------------------
    # master metadata ops (ref cluster/metadata/MetaData*Service)
    # ------------------------------------------------------------------

    def _master_call(self, action: str, payload: dict) -> Any:
        state = self.cluster.current()
        if state.master_node is None:
            raise NoMasterException("no elected master")
        if state.master_node == self.node_id:
            return self.transport._handle(self.node_id, action, payload)
        return self.transport.send(state.master_node, action, payload)

    def create_index(self, name: str, settings: dict | None = None,
                     mappings: dict | None = None) -> None:
        self._master_call(A_CREATE_INDEX, {
            "index": name, "settings": settings or {},
            "mappings": mappings or {}})

    def delete_index(self, name: str) -> None:
        self._master_call(A_DELETE_INDEX, {"index": name})

    def put_mapping(self, index: str, type_name: str, mapping: dict) -> None:
        self._master_call(A_PUT_MAPPING, {
            "index": index, "type": type_name, "mapping": mapping})

    def _on_create_index(self, from_id: str, req: dict) -> dict:
        name, settings = req["index"], req.get("settings") or {}
        n_shards = int(settings.get("number_of_shards",
                                    settings.get("index.number_of_shards", 1)))
        n_replicas = int(settings.get(
            "number_of_replicas", settings.get("index.number_of_replicas", 1)))

        def task(cur: ClusterState) -> ClusterState:
            if name in cur.indices:
                raise ValueError(f"index [{name}] already exists")
            st = cur.mutate()
            st.indices[name] = {"settings": settings,
                                "mappings": req.get("mappings") or {},
                                "aliases": []}
            st.routing[name] = new_index_routing(n_shards, n_replicas)
            allocate(st)
            return st
        self.cluster.submit_task(f"create-index[{name}]", task)
        return {"acknowledged": True}

    def _on_delete_index(self, from_id: str, req: dict) -> dict:
        name = req["index"]

        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            st.indices.pop(name, None)
            st.routing.pop(name, None)
            return st
        self.cluster.submit_task(f"delete-index[{name}]", task)
        return {"acknowledged": True}

    def _on_put_mapping(self, from_id: str, req: dict) -> dict:
        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            meta = st.indices.get(req["index"])
            if meta is None:
                raise KeyError(f"no such index [{req['index']}]")
            cur_map = meta.setdefault("mappings", {})
            merged = MapperService(mappings=cur_map)
            merged.merge(req["type"], req["mapping"])
            meta["mappings"] = merged.mappings_dict()
            return st
        self.cluster.submit_task(f"put-mapping[{req['index']}]", task)
        return {"acknowledged": True}

    # ------------------------------------------------------------------
    # reconciler (ref IndicesClusterStateService.clusterChanged :150)
    # ------------------------------------------------------------------

    def _apply_cluster_state(self, state: ClusterState) -> None:
        with self._shards_lock:
            # mappings from metadata
            for index, meta in state.indices.items():
                svc = self._mappers.get(index)
                if svc is None:
                    self._mappers[index] = MapperService(
                        mappings=meta.get("mappings") or {})
                else:
                    for tname, m in (meta.get("mappings") or {}).items():
                        svc.merge(tname, m)
            # drop shards (and whole indices) no longer assigned here
            # (ref indices/store/IndicesStore state-driven GC)
            assigned = {(i, s) for i, s, _ in
                        state.assigned_shards(self.node_id)}
            for key in [k for k in self._shards
                        if k not in assigned or k[0] not in state.indices]:
                holder = self._shards.pop(key)
                if holder.engine is not None:
                    holder.engine.close()
                import shutil
                shutil.rmtree(self._shard_path(*key), ignore_errors=True)
            for index in [i for i in self._mappers
                          if i not in state.indices]:
                del self._mappers[index]
            todo = [(i, s, c) for i, s, c in
                    state.assigned_shards(self.node_id)
                    if c["state"] == INITIALIZING]
        # recoveries run outside _shards_lock: they call into other nodes
        for index, sid, copy_ in todo:
            self._init_shard(state, index, sid, copy_)

    def _shard_path(self, index: str, sid: int) -> str:
        return os.path.join(self.data_path, "indices", index, str(sid))

    def _init_shard(self, state: ClusterState, index: str, sid: int,
                    copy_: dict) -> None:
        key = (index, sid)
        with self._shards_lock:
            holder = self._shards.setdefault(key, _ShardHolder())
        mappers = self._mappers[index]
        if copy_["primary"]:
            if holder.engine is None:
                holder.engine = Engine(self._shard_path(index, sid), mappers)
            # else: in-place promotion of a copy we already host
            self._report_started(index, sid)
            return
        # replica: peer recovery from the started primary. An EXISTING local
        # engine is stale by definition — this copy was unassigned (e.g.
        # after a failed replication hop) and must re-sync from the primary,
        # or it would come back STARTED while missing acked writes.
        primary = state.primary_of(index, sid)
        if primary is None or primary["state"] != STARTED:
            return      # allocator shouldn't have scheduled this; wait
        with holder.lock:
            holder.recovering = True
            if holder.engine is not None:
                holder.engine.close()
                holder.engine = None
                holder.searcher = None
        try:
            files = self.transport.send(primary["node"], A_RECOVERY,
                                        {"index": index, "shard": sid})
        except (ConnectTransportException, RemoteTransportException):
            with holder.lock:
                holder.recovering = False
            return      # primary vanished; a future state will retry
        path = self._shard_path(index, sid)
        # wipe any stale copy: leftover segment files are mere GC fodder,
        # but a stale TRANSLOG would replay old ops over the recovered state
        import shutil
        shutil.rmtree(path, ignore_errors=True)
        os.makedirs(path, exist_ok=True)
        for rel, blob in files["files"].items():
            dst = os.path.join(path, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            with open(dst, "wb") as f:
                f.write(blob)
        with holder.lock:
            holder.engine = Engine(path, mappers)
            for op in holder.pending:
                self._apply_replica_op(holder, op)
            holder.pending.clear()
            holder.recovering = False
        self._report_started(index, sid)

    def _report_started(self, index: str, sid: int) -> None:
        try:
            self._master_call(A_SHARD_STARTED, {
                "index": index, "shard": sid, "node": self.node_id})
        except (NoMasterException, ConnectTransportException,
                RemoteTransportException):
            pass        # next publish/fault round sorts it out

    def _on_shard_started(self, from_id: str, req: dict) -> dict:
        index, sid, node_id = req["index"], req["shard"], req["node"]

        def task(cur: ClusterState) -> ClusterState | None:
            if index not in cur.routing:
                return None
            st = cur.mutate()
            changed = False
            for c in st.routing[index][sid]:
                if c["node"] == node_id and c["state"] == INITIALIZING:
                    c["state"] = STARTED
                    c.pop("fresh", None)
                    changed = True
            if changed:
                allocate(st)    # replicas may now be able to initialize
                return st
            return None
        self.cluster.submit_task(
            f"shard-started[{index}][{sid}]", task, wait=False)
        return {"ok": True}

    def _on_shard_failed(self, from_id: str, req: dict) -> dict:
        index, sid, node_id = req["index"], req["shard"], req["node"]

        def task(cur: ClusterState) -> ClusterState | None:
            if index not in cur.routing:
                return None
            st = cur.mutate()
            changed = False
            for c in st.routing[index][sid]:
                if c["node"] == node_id and not c["primary"]:
                    c["node"] = None
                    c["state"] = UNASSIGNED
                    changed = True
            if changed:
                allocate(st)
                return st
            return None
        self.cluster.submit_task(
            f"shard-failed[{index}][{sid}][{node_id}]", task, wait=False)
        return {"ok": True}

    # -- recovery source (ref RecoverySourceHandler.java:149-195) -------

    def _on_recovery(self, from_id: str, req: dict) -> dict:
        """Phase 1+3 collapsed: flush under the engine write lock and ship
        the store's checksummed files. The brief lock is the reference's
        finalize-under-write-block; ops acked after the lock releases reach
        the replica through normal forwarding (idempotent by version)."""
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None or holder.engine is None:
            raise UnavailableShardsException(
                f"not hosting [{req['index']}][{req['shard']}]")
        eng = holder.engine
        files: dict[str, bytes] = {}
        with eng._lock:
            eng.flush()
            for fn in sorted(os.listdir(eng.path)):
                fp = os.path.join(eng.path, fn)
                if os.path.isfile(fp):
                    with open(fp, "rb") as f:
                        files[fn] = f.read()
        return {"files": files}

    # ------------------------------------------------------------------
    # write path (ref TransportShardReplicationOperationAction.java:67)
    # ------------------------------------------------------------------

    def index_doc(self, index: str, doc_id: str | None, source: dict,
                  type_name: str = "_doc", routing: str | None = None,
                  **kw) -> dict:
        if doc_id is None:
            import uuid
            doc_id = uuid.uuid4().hex[:20]
        return self._write_op(index, {
            "op": "index", "id": doc_id, "source": source, "type": type_name,
            "routing": routing, **kw})

    def delete_doc(self, index: str, doc_id: str,
                   routing: str | None = None, **kw) -> dict:
        return self._write_op(index, {"op": "delete", "id": doc_id,
                                      "routing": routing, **kw})

    def _write_op(self, index: str, op: dict, timeout: float = 10.0) -> dict:
        """Route to the primary, retrying on stale routing / primary
        failover — the reference's retry-on-cluster-state-change loop."""
        deadline = time.monotonic() + timeout
        last_err: Exception | None = None
        while time.monotonic() < deadline:
            state = self.cluster.current()
            meta = state.index_meta(index)
            if meta is None:
                # auto-create may lose a race with a concurrent creator or
                # hit a masterless interim — both just mean "retry the loop"
                try:
                    self.create_index(index, {}, {})
                except NoMasterException as e:
                    last_err = e
                    time.sleep(0.02)
                except Exception as e:  # noqa: BLE001
                    if "already exists" not in str(e):
                        raise
                    last_err = e
                continue
            n_shards = len(state.routing[index])
            sid = route_shard(op["id"], n_shards, op.get("routing"))
            primary = state.primary_of(index, sid)
            if primary is None or primary["state"] != STARTED:
                time.sleep(0.02)
                continue
            payload = {**op, "index": index, "shard": sid}
            try:
                if primary["node"] == self.node_id:
                    return self._on_primary_write(self.node_id, payload)
                return self.transport.send(primary["node"], A_WRITE_P, payload)
            except ConnectTransportException as e:
                last_err = e
                # transport disconnect == immediate failure report
                try:
                    self._master_call(A_NODE_FAILED,
                                      {"node": primary["node"]})
                except Exception:  # noqa: BLE001 — masterless interim
                    pass
                # the dead node may have BEEN the master: drive a detection
                # round ourselves so an election can proceed (the reference
                # couples this to transport disconnect events)
                self.fault_detection_round()
                time.sleep(0.02)
            except RemoteTransportException as e:
                if e.error_type == "VersionConflictException":
                    raise VersionConflictException(op["id"], -1, -1) from e
                if e.error_type in ("UnavailableShardsException",
                                    "NoMasterException"):
                    # stale routing: the addressee no longer holds the
                    # primary (demoted/relocated) — refresh state and retry
                    last_err = e
                    time.sleep(0.02)
                    continue
                raise
        raise UnavailableShardsException(
            f"[{index}] shard for [{op['id']}] not available: {last_err}")

    def _on_primary_write(self, from_id: str, req: dict) -> dict:
        index, sid = req["index"], req["shard"]
        holder = self._shards.get((index, sid))
        state = self.cluster.current()
        primary = state.primary_of(index, sid)
        if holder is None or holder.engine is None or primary is None \
                or primary["node"] != self.node_id:
            raise UnavailableShardsException(
                f"[{index}][{sid}] primary not on [{self.node_id}]")
        if req["op"] == "index":
            res = holder.engine.index(
                req["id"], req["source"], type_name=req.get("type", "_doc"),
                version=req.get("version"),
                version_type=req.get("version_type", "internal"),
                op_type=req.get("op_type", "index"))
        else:
            res = holder.engine.delete(
                req["id"], version=req.get("version"),
                version_type=req.get("version_type", "internal"))
        # sync replication fan-out (ref :118-120 — replicas ack before we do)
        replica_req = {"index": index, "shard": sid, "op": req["op"],
                       "id": req["id"], "source": req.get("source"),
                       "type": req.get("type", "_doc"),
                       "version": res.version}
        for c in state.shard_copies(index, sid):
            if c["primary"] or c["node"] in (None, self.node_id) \
                    or c["state"] not in (STARTED, INITIALIZING):
                continue
            try:
                self.transport.send(c["node"], A_WRITE_R, replica_req)
            except (ConnectTransportException, RemoteTransportException):
                # failed replica → master unassigns it (ref replica-failure
                # notification); the write itself still succeeds
                try:
                    self._master_call(A_SHARD_FAILED, {
                        "index": index, "shard": sid, "node": c["node"]})
                except Exception:  # noqa: BLE001
                    pass
        return {"_index": index, "_id": res.doc_id, "_version": res.version,
                "created": res.created, "found": res.found}

    def _on_replica_write(self, from_id: str, req: dict) -> dict:
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None:
            raise UnavailableShardsException(
                f"replica [{req['index']}][{req['shard']}] not hosted")
        with holder.lock:
            if holder.recovering or holder.engine is None:
                holder.pending.append(req)
                return {"buffered": True}
            self._apply_replica_op(holder, req)
        return {"applied": True}

    def _apply_replica_op(self, holder: _ShardHolder, req: dict) -> None:
        """External-version apply: strictly-newer wins, equal/older is a
        no-op (the op already arrived via recovery file copy)."""
        try:
            if req["op"] == "index":
                holder.engine.index(req["id"], req["source"],
                                    type_name=req.get("type", "_doc"),
                                    version=req["version"],
                                    version_type="external")
            else:
                holder.engine.delete(req["id"], version=req["version"],
                                     version_type="external")
        except VersionConflictException:
            pass

    # ------------------------------------------------------------------
    # reads
    # ------------------------------------------------------------------

    def get_doc(self, index: str, doc_id: str,
                routing: str | None = None) -> dict:
        state = self.cluster.current()
        if index not in state.routing:
            raise KeyError(f"no such index [{index}]")
        sid = route_shard(doc_id, len(state.routing[index]), routing)
        primary = state.primary_of(index, sid)
        if primary is None or primary["state"] != STARTED:
            raise UnavailableShardsException(f"[{index}][{sid}]")
        payload = {"index": index, "shard": sid, "id": doc_id}
        if primary["node"] == self.node_id:
            return self._on_get(self.node_id, payload)
        return self.transport.send(primary["node"], A_GET, payload)

    def _on_get(self, from_id: str, req: dict) -> dict:
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None or holder.engine is None:
            raise UnavailableShardsException(f"[{req['index']}]")
        r = holder.engine.get(req["id"])
        return {"found": r.found, "_id": req["id"],
                "_version": r.version if r.found else None,
                "_source": r.source if r.found else None}

    # -- distributed search (QUERY_THEN_FETCH over the transport seam) --

    def search(self, index: str, body: dict | None = None) -> dict:
        t0 = time.perf_counter()
        body = body or {}
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        state = self.cluster.current()
        names = state.resolve_index(index)
        if not names:
            raise KeyError(f"no such index [{index}]")
        # shard targets: prefer the local copy, else first started
        targets: list[tuple[str, str, int]] = []   # (node, index, shard)
        for name in names:
            for sid in range(len(state.routing[name])):
                copies = state.started_copies(name, sid)
                if not copies:
                    raise UnavailableShardsException(f"[{name}][{sid}]")
                node = next((c["node"] for c in copies
                             if c["node"] == self.node_id),
                            copies[0]["node"])
                targets.append((node, name, sid))
        # phase 1: query — per-shard top-(from+size) ids and scores
        per_shard: list[dict] = []
        for node, name, sid in targets:
            payload = {"index": name, "shard": sid, "body": body,
                       "size": size + from_}
            if node == self.node_id:
                per_shard.append(self._on_query(self.node_id, payload))
            else:
                per_shard.append(self.transport.send(node, A_QUERY, payload))
        # reduce (ref SearchPhaseController.sortDocs :147)
        cands = []
        total = 0
        max_score = None
        for ti, r in enumerate(per_shard):
            total += r["total"]
            if r["max_score"] is not None:
                ms = float(r["max_score"])
                if max_score is None or ms > max_score:
                    max_score = ms
            for h in r["hits"]:
                cands.append((ti, h["id"], h["score"]))
        cands.sort(key=lambda c: (-c[2], c[1]))
        winners = cands[from_:from_ + size]
        # phase 2: fetch — only from shards owning winners
        by_target: dict[int, list[str]] = {}
        for ti, doc_id, _ in winners:
            by_target.setdefault(ti, []).append(doc_id)
        sources: dict[tuple[int, str], dict | None] = {}
        for ti, ids in by_target.items():
            node, name, sid = targets[ti]
            payload = {"index": name, "shard": sid, "ids": ids,
                       "_source": body.get("_source", True)}
            if node == self.node_id:
                fr = self._on_fetch(self.node_id, payload)
            else:
                fr = self.transport.send(node, A_FETCH, payload)
            for doc_id, src in zip(ids, fr["sources"]):
                sources[(ti, doc_id)] = src
        hits = [{"_index": targets[ti][1], "_id": doc_id,
                 "_score": score, "_source": sources.get((ti, doc_id))}
                for ti, doc_id, score in winners]
        return {"took": int((time.perf_counter() - t0) * 1000),
                "timed_out": False,
                "_shards": {"total": len(targets),
                            "successful": len(targets), "failed": 0},
                "hits": {"total": total, "max_score": max_score,
                         "hits": hits}}

    def _searcher(self, index: str, sid: int,
                  holder: _ShardHolder) -> ShardSearcher:
        key = tuple(s.seg_id for s in holder.engine.segments)
        if holder.searcher is None or holder.searcher[0] != key:
            holder.searcher = (key, ShardSearcher(
                sid, holder.engine.segments, self._mappers[index]))
        return holder.searcher[1]

    def _on_query(self, from_id: str, req: dict) -> dict:
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None or holder.engine is None:
            raise UnavailableShardsException(
                f"[{req['index']}][{req['shard']}]")
        searcher = self._searcher(req["index"], req["shard"], holder)
        body = req.get("body") or {}
        node = searcher.parse([body.get("query") or {"match_all": {}}])
        r = searcher.execute_query_phase(node, size=req["size"], from_=0)
        hits = []
        for pos in range(r.doc_keys.shape[1]):
            key = int(r.doc_keys[0, pos])
            if key < 0:
                continue
            seg = searcher.segments[key >> 32]
            hits.append({"id": seg.ids[key & 0xFFFFFFFF],
                         "score": float(r.scores[0, pos])})
        mx = float(r.max_score[0])
        return {"hits": hits, "total": int(r.total_hits[0]),
                "max_score": None if mx != mx else mx}

    def _on_fetch(self, from_id: str, req: dict) -> dict:
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None or holder.engine is None:
            raise UnavailableShardsException(f"[{req['index']}]")
        sources = []
        for doc_id in req["ids"]:
            r = holder.engine.get(doc_id, realtime=False)
            src = r.source if r.found else None
            if src is not None and req.get("_source") is False:
                src = None
            sources.append(src)
        return {"sources": sources}

    # ------------------------------------------------------------------
    # broadcast admin (ref TransportBroadcastOperationAction)
    # ------------------------------------------------------------------

    def refresh(self, index: str = "_all") -> None:
        self._broadcast(A_REFRESH, index)

    def flush(self, index: str = "_all") -> None:
        self._broadcast(A_FLUSH, index)

    def _broadcast(self, action: str, index: str) -> None:
        state = self.cluster.current()
        nodes = {c["node"] for name in state.resolve_index(index)
                 for copies in state.routing[name] for c in copies
                 if c["node"] is not None and c["state"] != UNASSIGNED}
        for node_id in sorted(nodes):
            try:
                if node_id == self.node_id:
                    self.transport._handle(self.node_id, action,
                                           {"index": index})
                else:
                    self.transport.send(node_id, action, {"index": index})
            except (ConnectTransportException, RemoteTransportException):
                continue

    def _on_refresh(self, from_id: str, req: dict) -> dict:
        names = self.cluster.current().resolve_index(req.get("index", "_all"))
        for (index, sid), holder in list(self._shards.items()):
            if index in names and holder.engine is not None:
                holder.engine.refresh()
        return {"ok": True}

    def _on_flush(self, from_id: str, req: dict) -> dict:
        names = self.cluster.current().resolve_index(req.get("index", "_all"))
        for (index, sid), holder in list(self._shards.items()):
            if index in names and holder.engine is not None:
                holder.engine.flush()
        return {"ok": True}

    # ------------------------------------------------------------------

    def health(self) -> dict:
        state = self.cluster.current()
        return {"cluster_name": state.data["cluster_name"],
                "master_node": state.master_node,
                "version": state.version, **state.health()}

    def close(self) -> None:
        """Simulates process death when called abruptly (harness.kill)."""
        self.closed = True
        self.transport.close()
        self.cluster.close()
        with self._shards_lock:
            for holder in self._shards.values():
                if holder.engine is not None:
                    holder.engine.close()
