"""ClusterNode: a full node — cluster membership, shard hosting, replicated
writes, peer recovery, and the distributed search driver.

Maps to several reference components at once (SURVEY.md §2.3/§2.5/§2.7):
  * join/election/fault-report       — discovery/zen/ZenDiscovery.java:354,500
  * reconciler (state → local shards) — indices/cluster/
                                        IndicesClusterStateService.java:150
  * replicated write                 — action/support/replication/
                                        TransportShardReplicationOperationAction.java:67,118-120
  * peer recovery (file phase)       — indices/recovery/RecoverySourceHandler.java:149-195
  * search scatter-gather            — action/search/type/TransportSearchTypeAction.java:85-177

Design notes (TPU-first deviations from the reference, on purpose):
  * Replicas apply ops with external-version semantics: the primary assigns
    the version, replicas accept any strictly-newer version and treat
    version conflicts as "already applied" — this makes the
    file-copy-then-forward recovery race idempotent without uid-locks.
  * Recovery transfers the checksummed write-once segment files produced by
    index/store.py (flush under the engine lock = the reference's brief
    phase-3 write block), so a recovered replica loads tensors straight to
    device with zero re-tokenization.
  * Dynamic mappings derive deterministically on every copy (same doc ⇒ same
    inferred mapping), so replicas don't block acks on a master mapping
    round-trip; explicit put-mapping still flows through the master.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any

from ..common import tracing
from ..index.engine import Engine, VersionConflictException
from ..mapping.mapper import MapperService
from ..parallel.routing import shard_id as route_shard
from ..search.shard_searcher import ShardSearcher
from .service import ClusterService
from .state import (INITIALIZING, RELOCATING, STARTED, UNASSIGNED,
                    ClusterState, allocate, cancel_relocations_for,
                    finish_relocation, new_index_routing, rebalance,
                    remove_node)
from .transport import (ConnectTransportException, LocalTransport,
                        RemoteTransportException, TransportService)

A_JOIN = "internal:discovery/zen/join"
A_PING = "internal:discovery/zen/fd/ping"
A_NODE_FAILED = "internal:discovery/zen/fd/node_failed"
A_SHARD_STARTED = "internal:cluster/shard/started"
A_SHARD_FAILED = "internal:cluster/shard/failed"
A_CREATE_INDEX = "indices:admin/create"
A_DELETE_INDEX = "indices:admin/delete"
A_PUT_MAPPING = "indices:admin/mapping/put"
A_PUT_ALIAS = "indices:admin/aliases/put"
A_DELETE_ALIAS = "indices:admin/aliases/delete"
A_UPDATE_SETTINGS = "indices:admin/settings/update"
A_CLOSE_INDEX = "indices:admin/close"
A_OPEN_INDEX = "indices:admin/open"
A_SHARD_DATA = "internal:gateway/local/started_shards"
A_REFRESH = "indices:admin/refresh"
A_FLUSH = "indices:admin/flush"
A_WRITE_P = "indices:data/write/op[p]"
A_WRITE_R = "indices:data/write/op[r]"
A_WRITE_R_BULK = "indices:data/write/bulk[r]"
A_GET = "indices:data/read/get"
A_QUERY = "indices:data/read/search[phase/query]"
A_QUERY_HOST = "indices:data/read/search[phase/query/host]"
A_FETCH = "indices:data/read/search[phase/fetch/id]"
A_TERM_STATS = "indices:data/read/search[phase/dfs]"
A_SCROLL_NEXT = "indices:data/read/search[phase/scroll]"
A_SCROLL_CLEAR = "indices:data/read/search[free_context]"
A_RECOVERY = "internal:index/shard/recovery/start"
A_RECOVERY_CHUNK = "internal:index/shard/recovery/chunk"
A_FS_STATS = "internal:monitor/fs"
A_RECOVERY_STATS = "indices:monitor/recovery"
A_CLUSTER_SETTINGS = "cluster:admin/settings/update"
A_NODE_STATS = "cluster:monitor/nodes/stats"
A_NODE_METRICS = "cluster:monitor/nodes/metrics"
A_SHARD_STATS = "indices:monitor/stats[shard]"


class NoMasterException(Exception):
    pass


class SearchContextMissingException(Exception):
    """Expired/unknown scroll id (ref search/SearchContextMissingException
    — a routine 404, not a server fault)."""


class UnavailableShardsException(Exception):
    pass


class _ShardHolder:
    """One locally-hosted shard copy."""

    def __init__(self):
        self.engine: Engine | None = None
        self.lock = threading.RLock()
        self.recovering = False
        self.recovery_aid = None       # allocation id of the in-flight pull
        self.reinit_pending = False    # a newer era waits for the old pull
        self.cancel_recovery = False   # newer state unassigned this copy
        self.pending: list[dict] = []     # ops buffered during recovery
        self.searcher: tuple | None = None   # (key, ShardSearcher, handle)

    def drop_searcher(self) -> None:
        """Release the cached searcher's engine refcount (the leak
        detector asserts the count drains at engine close)."""
        if self.searcher is not None:
            self.searcher[2].release()
            self.searcher = None


class ClusterNode:
    def __init__(self, node_id: str, data_path: str, network: LocalTransport,
                 minimum_master_nodes: int = 1,
                 attrs: dict | None = None,
                 settings: dict | None = None):
        self.node_id = node_id
        self.data_path = os.path.join(data_path, node_id)
        os.makedirs(self.data_path, exist_ok=True)
        self.minimum_master_nodes = minimum_master_nodes
        # filterable node attributes (`node.attr.*` analog) — published
        # into the cluster state at join time for the awareness/filter
        # deciders (ref DiscoveryNode attributes)
        self.attrs = dict(attrs or {})
        # node-local settings overlay (ISSUE 19): `node.devices` carves
        # this node's disjoint device subset into an owned DevicePool (so
        # host reduces dispatch under the pool's private lock, not the
        # process-wide EXEC_LOCK), `node.host` names the simulated host
        # for the transport's DCN traffic classification, and
        # `cluster.mesh.coordinator` arms jax.distributed multi-host init.
        self.settings = dict(settings or {})
        from ..parallel.mesh import (maybe_init_distributed,
                                     resolve_device_pool)
        maybe_init_distributed(self.settings)
        self.device_pool = resolve_device_pool(self.settings)
        host = self.settings.get("node.host")
        if host and hasattr(network, "set_host"):
            network.set_host(node_id, str(host))
        self.transport = TransportService(node_id, network)
        self.cluster = ClusterService(node_id, self.transport,
                                      self._apply_cluster_state)
        self._shards: dict[tuple[str, int], _ShardHolder] = {}
        self._mappers: dict[str, MapperService] = {}
        self._shards_lock = threading.RLock()
        self.closed = False
        # distributed task registry: coordinator tasks here, shard tasks on
        # the copy-holders with the coordinator as parent (the `_task` wire
        # header on shard messages; ref tasks/TaskManager + TaskId)
        from ..common.tasks import TaskManager
        self.tasks = TaskManager(node_id)
        # span tracer: shard subtrees on copy-holders continue the
        # coordinator's trace via the `_trace` wire header (partial traces
        # land in THIS node's ring under the same trace id)
        from ..common.tracing import Tracer
        self.tracer = Tracer()
        for action, handler in [
                (A_JOIN, self._on_join), (A_PING, self._on_ping),
                (A_NODE_FAILED, self._on_node_failed),
                (A_SHARD_STARTED, self._on_shard_started),
                (A_SHARD_FAILED, self._on_shard_failed),
                (A_CREATE_INDEX, self._on_create_index),
                (A_DELETE_INDEX, self._on_delete_index),
                (A_PUT_MAPPING, self._on_put_mapping),
                (A_PUT_ALIAS, self._on_put_alias),
                (A_DELETE_ALIAS, self._on_delete_alias),
                (A_UPDATE_SETTINGS, self._on_update_settings),
                (A_CLOSE_INDEX, self._on_close_index),
                (A_OPEN_INDEX, self._on_open_index),
                (A_SHARD_DATA, self._on_shard_data),
                (A_REFRESH, self._on_refresh), (A_FLUSH, self._on_flush),
                (A_WRITE_P, self._on_primary_write),
                (A_WRITE_R, self._on_replica_write),
                (A_WRITE_R_BULK, self._on_replica_bulk),
                (A_GET, self._on_get), (A_QUERY, self._on_query),
                (A_QUERY_HOST, self._on_query_host),
                (A_FETCH, self._on_fetch),
                (A_TERM_STATS, self._on_term_stats),
                (A_SCROLL_NEXT, self._on_scroll_next),
                (A_SCROLL_CLEAR, self._on_scroll_clear),
                (A_RECOVERY, self._on_recovery),
                (A_RECOVERY_CHUNK, self._on_recovery_chunk),
                (A_RECOVERY_STATS, self._on_recovery_stats),
                (A_CLUSTER_SETTINGS, self._on_cluster_settings),
                (A_FS_STATS, self._on_fs_stats),
                (A_NODE_STATS, self._on_node_stats),
                (A_NODE_METRICS, self._on_node_metrics),
                (A_SHARD_STATS, self._on_shard_stats)]:
            self.transport.register_handler(action, handler)
        # ClusterInfoService + disk watermark decider (cluster/info.py;
        # ref InternalClusterInfoService + DiskThresholdDecider) — the
        # master samples peers' fs stats during fault-detection rounds
        from .info import ClusterInfoService, DiskThresholdDecider
        self.cluster_info = ClusterInfoService()
        self.cluster_info.register_node(node_id, self.data_path)
        self.disk_decider = DiskThresholdDecider(self.cluster_info)
        # composable allocation decider chain (ISSUE 15): awareness /
        # filters / shards-limit / recovery throttling / disk, each with
        # a per-decider verdict behind /_cluster/allocation/explain
        from .deciders import DeciderChain
        self.deciders = DeciderChain.default(self.disk_decider)
        # peer-recovery rate limiting (indices.recovery.max_bytes_per_sec,
        # live from cluster settings): ONE node-wide token bucket shared
        # by every recovery this node pulls, plus per-shard progress rows
        # for GET /_cat/recovery
        from .recovery import RecoveryThrottle
        self.recovery_throttle = RecoveryThrottle(self._recovery_rate)
        self.recoveries: dict[tuple[str, int], dict] = {}
        self._recoveries_lock = threading.Lock()
        # chaos clock-skew seam: offsets WALL-clock reads only (the
        # _cat/recovery start_time_ms column). Durations and the token
        # bucket run on time.monotonic, so a skewed node must never
        # mis-throttle or report negative elapsed — the invariant the
        # ClockSkew disruption asserts.
        self.clock_skew_s = 0.0
        # per-(index, shard) round-robin cursor for read copy selection
        # (ref cluster/routing/OperationRouting.java:144-154)
        self._read_rr: dict[tuple[str, int], int] = {}
        # hedged replica reads (ISSUE 9, SURVEY §2.10.2 upgraded): per-
        # target-node latency EWMAs arm an adaptive p99 deadline; a copy
        # that blows it gets a backup request fired at another copy, the
        # first answer wins and the loser is canceled. Settings
        # (cluster.search.hedge.*) read from cluster-state settings with
        # this overlay dict as the node-local fallback.
        self._node_lat: dict[str, Any] = {}
        self.hedge_settings: dict = {}
        self.hedge_stats = {"fired": 0, "win_primary": 0,
                            "win_backup": 0, "canceled": 0, "failed": 0,
                            "moving": 0}
        # shard-level pinned scroll contexts this node hosts (data-node side
        # of the distributed scroll; ref SearchService contexts + reaper)
        self._scroll_ctx: dict[str, dict] = {}
        self._scroll_seq = 0
        self._scroll_lock = threading.Lock()
        # node-local mesh reduce (ISSUE 11): the co-hosted shard groups'
        # packed mesh stacks — one device program per host per query, the
        # transport carries pre-reduced per-shard results. Keyed by the
        # shard GROUP (index + sids), stale entries displaced on refresh.
        from ..indices.cache_service import (MeshStackCache,
                                             MeshVectorStackCache)
        self._host_mesh_stacks = MeshStackCache(max_bytes=1 << 31)
        self._host_vector_stacks = MeshVectorStackCache(max_bytes=1 << 31)
        self.host_reduce_stats = {"dispatches": 0, "declined": 0,
                                  "errors": 0, "merges": 0,
                                  # pod tier (ISSUE 19): cross-host
                                  # pre-reduced merges + their DCN hops
                                  "pod_dispatches": 0, "dcn_hops": 0}

    # ------------------------------------------------------------------
    # membership / election (ref ZenDiscovery.java:354 innerJoinCluster)
    # ------------------------------------------------------------------

    def bootstrap_as_master(self) -> None:
        """First node of a cluster: publish a state with self as master."""
        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            st.data["master_node"] = self.node_id
            st.nodes[self.node_id] = {"id": self.node_id,
                                      "name": self.node_id,
                                      "attributes": dict(self.attrs)}
            return st
        self.cluster.submit_task("bootstrap-master", task)

    def join(self, master_id: str) -> None:
        self.transport.send(master_id, A_JOIN, {"node": self.node_id,
                                                "attrs": self.attrs})
        # the publish that follows the join task delivers us the state
        deadline = time.monotonic() + 10
        while self.cluster.current().master_node is None:
            if time.monotonic() > deadline:
                raise NoMasterException(f"join to [{master_id}] not published")
            time.sleep(0.01)

    def _on_join(self, from_id: str, req: dict) -> dict:
        joining = req["node"]
        attrs = req.get("attrs") or {}

        def task(cur: ClusterState) -> ClusterState | None:
            st = cur.mutate()
            if joining in st.nodes:
                # REJOIN behind an id the table still knows: a restarted
                # process (or one back from a partition the master never
                # noticed). Its copies may be STARTED in the table while
                # the process behind the id holds nothing — reset them to
                # UNASSIGNED so allocation re-assigns with a real
                # (checksum-delta-cheap) recovery instead of serving a
                # zombie copy with no engine.
                remove_node(st, joining, decider=self.deciders)
            st.nodes[joining] = {"id": joining, "name": joining,
                                 "attributes": dict(attrs)}
            allocate(st, decider=self.deciders)
            rebalance(st, decider=self.deciders)    # a joining node receives shards (VERDICT r4 #9)
            return st
        self.cluster.submit_task(f"node-join[{joining}]", task, wait=False)
        return {"ok": True}

    def _on_ping(self, from_id: str, req: Any) -> dict:
        cur = self.cluster.current()
        # `member`: whether the PINGER is in our cluster state — the
        # MasterFaultDetection "node does not exist on master" signal. A
        # node the master removed during a partition pings a master that
        # still answers with the same master id, so without this bit the
        # healed node would never learn it was dropped and never rejoin
        # (found by the chaos harness's isolate→heal rounds).
        return {"node": self.node_id, "version": cur.version,
                "master": cur.master_node,
                "member": from_id in cur.nodes or from_id == self.node_id}

    def _on_shard_stats(self, from_id: str, req: Any) -> dict:
        """Per-shard stats for the BROADCAST template (ref action/support/
        broadcast/TransportBroadcastOperationAction — every node answers
        for the shards it holds; the coordinator aggregates)."""
        names = set(req.get("indices") or [])
        out = []
        with self._shards_lock:
            holders = list(self._shards.items())
        for (index, sid), holder in holders:
            if names and index not in names:
                continue
            if holder.engine is None:
                continue
            st = holder.engine.segment_stats()
            out.append({"index": index, "shard": sid,
                        "docs": holder.engine.doc_count(),
                        "deleted": st["deleted"],
                        "segments": st["count"],
                        "store_bytes": st["memory_in_bytes"]})
        return {"shards": out}

    def indices_stats(self, index: str = "_all") -> dict:
        """Broadcast fan-out: collect shard stats from every node holding
        copies, aggregate per index (the _stats shape over a real
        cluster)."""
        state = self.cluster.current()
        names = state.resolve_index(index)
        if not names and index not in ("_all", "*", ""):
            raise KeyError(f"no such index [{index}]")
        per_index: dict[str, dict] = {
            n: {"docs": 0, "deleted": 0, "segments": 0, "store_bytes": 0,
                "shards": 0} for n in names}
        # _shards counts SHARD COPIES consulted, like the reference's
        # broadcast responses — not nodes
        total = sum(1 for n in names
                    for copies in state.routing.get(n, [])
                    for c in copies if c["state"] == STARTED)
        successful = 0
        for node_id in sorted(state.nodes):
            try:
                if node_id == self.node_id:
                    out = self._on_shard_stats(self.node_id,
                                               {"indices": names})
                else:
                    out = self.transport.send(node_id, A_SHARD_STATS,
                                              {"indices": names})
            except (ConnectTransportException, RemoteTransportException):
                continue
            successful += len(out["shards"])
            for sh in out["shards"]:
                agg = per_index.get(sh["index"])
                if agg is None:
                    continue
                agg["docs"] += sh["docs"]
                agg["deleted"] += sh["deleted"]
                agg["segments"] += sh["segments"]
                agg["store_bytes"] += sh["store_bytes"]
                agg["shards"] += 1
        indices = {
            n: {"total": {
                "docs": {"count": a["docs"], "deleted": a["deleted"]},
                "store": {"size_in_bytes": a["store_bytes"]},
                "segments": {"count": a["segments"]},
                "shard_copies": a["shards"]}}
            for n, a in per_index.items()}
        return {"_shards": {"total": total, "successful": successful,
                            "failed": max(total - successful, 0)},
                "_all": {"total": {
                    "docs": {"count": sum(a["docs"]
                                          for a in per_index.values()),
                             "deleted": sum(a["deleted"]
                                            for a in per_index.values())},
                    "store": {"size_in_bytes": sum(
                        a["store_bytes"] for a in per_index.values())}}},
                "indices": indices}

    def _on_node_stats(self, from_id: str, req: Any) -> dict:
        """Full per-node stats for the nodes-template fan-out (ref
        action/admin/cluster/node/stats/TransportNodesStatsAction — every
        node answers for itself; the coordinator assembles the map)."""
        from ..common import monitor
        docs = 0
        shards = 0
        with self._shards_lock:         # the reconciler mutates _shards
            holders = list(self._shards.values())
        for holder in holders:
            if holder.engine is not None:
                docs += holder.engine.doc_count()
                shards += 1
        return {"name": self.node_id,
                "indices": {"docs": {"count": docs},
                            "shard_count": shards},
                "os": monitor.os_stats(),
                "process": monitor.process_stats(),
                "jvm": monitor.runtime_stats(),
                "fs": monitor.fs_stats([self.data_path])}

    def metric_sections(self) -> dict:
        """This node's scrapeable registries as OpenMetrics walk input
        (common/metrics.openmetrics_families) — the cluster analog of
        NodeService.metric_sections(), restricted to what a ClusterNode
        actually runs (shard engines, tasks, host monitor)."""
        from ..common import monitor
        docs = 0
        shards = 0
        with self._shards_lock:
            holders = list(self._shards.values())
        for holder in holders:
            if holder.engine is not None:
                docs += holder.engine.doc_count()
                shards += 1
        proc = monitor.process_stats()
        os_st = monitor.os_stats()
        load = os_st.get("load_average") or [0.0]
        from ..serving.qos import hedge_snapshot
        from .recovery import snapshot as _recovery_snapshot
        sections = {
            "node": (None, {"docs": docs, "shards": shards}),
            # node-local mesh reduce (ISSUE 11): host-reduce programs this
            # node ran (data-node side), declines down the fan-out ladder,
            # errors, and coordinator-side pre-reduced merges —
            # es_search_mesh_host_reduce_dispatches_total et al.
            "search": (None, {
                "mesh_host_reduce_dispatches_total":
                    self.host_reduce_stats["dispatches"],
                "mesh_host_reduce_declined_total":
                    self.host_reduce_stats["declined"],
                "mesh_host_reduce_errors_total":
                    self.host_reduce_stats["errors"],
                "mesh_host_reduce_merges_total":
                    self.host_reduce_stats["merges"],
                # pod reduce (ISSUE 19): coordinator-side merges whose
                # pre-reduced message crossed a host boundary (ONE DCN
                # hop per remote node), and the raw cross-host hop count
                "pod_reduce_dispatches_total":
                    self.host_reduce_stats["pod_dispatches"],
                "pod_reduce_dcn_hops_total":
                    self.host_reduce_stats["dcn_hops"]}),
            # hedged-read outcomes + per-class transport send queues
            # (ISSUE 9): es_search_hedged_total{outcome=},
            # es_transport_class_queue_depth{class=}
            "search_hedged": ("outcome",
                              {o: {"total": c}
                               for o, c in hedge_snapshot().items()}),
            # peer-recovery stream counters (ISSUE 15):
            # es_recovery_bytes_total, es_recovery_throttle_waits_total...
            # process-wide (cluster/recovery.py) — every node scrapes the
            # same truth the bench's throttle-compliance check reads
            "recovery": (None, dict(_recovery_snapshot())),
            # per-decider allocation vetoes:
            # es_allocation_decider_vetoes_total{decider=}
            "allocation_decider": ("decider",
                                   {name: {"vetoes_total": n}
                                    for name, n
                                    in self.deciders.vetoes.items()}),
            "tasks": (None, self.tasks.stats()),
            "process": (None, {
                "resident_bytes": proc.get("mem", {})
                .get("resident_in_bytes", 0),
                "threads": proc.get("threads", 0)}),
            "os": (None, {"load_1m": load[0],
                          "cpu_percent": os_st["cpu"]["percent"]}),
        }
        class_stats = getattr(self.transport.network, "class_stats", None)
        if class_stats is not None:          # TcpTransport has no classes
            sections["transport_class"] = ("class", class_stats())
        # per-transport-class latency EWMAs (ISSUE 19): the "dcn" class
        # gets its own deadline so cross-host hops never poison the ICI
        # hedge deadline — es_transport_latency_ewma_ms{class=}
        from ..serving.qos import transport_latency_snapshot
        lat = transport_latency_snapshot()
        if lat:
            sections["transport_latency"] = (
                "class", {c: {"ewma_ms": v["ewma_ms"],
                              "deadline_ms": v["deadline_ms"],
                              "observations_total": v["n"]}
                          for c, v in lat.items()})
        # fault-injection accounting (ISSUE 14): both transports count the
        # faults they actually applied — es_transport_faults_injected_total
        fault_stats = getattr(self.transport.network, "fault_stats", None)
        if fault_stats is not None:
            sections["transport"] = (None, fault_stats())
        return sections

    def _on_node_metrics(self, from_id: str, req: Any) -> dict:
        return {"sections": self.metric_sections()}

    def nodes_metric_sections(self) -> dict:
        """Fan out the metrics action to every live node; live nodes whose
        handler errors surface as failure entries (the nodes template,
        same contract as nodes_stats)."""
        state = self.cluster.current()
        out: dict = {}
        failures: list = []
        for node_id in sorted(state.nodes):
            try:
                if node_id == self.node_id:
                    out[node_id] = self.metric_sections()
                else:
                    out[node_id] = self.transport.send(
                        node_id, A_NODE_METRICS, {})["sections"]
            except ConnectTransportException:
                continue              # dead node: absent from the map
            except RemoteTransportException as e:
                failures.append({"node": node_id, "reason": str(e)})
        return {"sections_by_node": out, "failures": failures}

    def nodes_stats(self) -> dict:
        """Coordinator-side fan-out to every live node (the nodes
        template, ref TransportNodesOperationAction)."""
        state = self.cluster.current()
        out: dict = {}
        failures: list = []
        for node_id in sorted(state.nodes):
            try:
                if node_id == self.node_id:
                    out[node_id] = self._on_node_stats(self.node_id, {})
                else:
                    out[node_id] = self.transport.send(
                        node_id, A_NODE_STATS, {})
            except ConnectTransportException:
                continue              # dead node: absent from the map
            except RemoteTransportException as e:
                # LIVE node whose handler errored: report, don't hide
                # (ref TransportNodesOperationAction FailedNodeException)
                failures.append({"node": node_id, "reason": str(e)})
        return {"nodes": out, "failures": failures}

    def _on_fs_stats(self, from_id: str, req: Any) -> dict:
        """Per-node disk usage for the master's ClusterInfoService
        (ref TransportNodesStatsAction fs metric)."""
        import shutil
        try:
            du = shutil.disk_usage(self.data_path)
            return {"total": du.total, "free": du.free}
        except OSError:
            return {"total": 0, "free": 0}

    def refresh_cluster_info(self) -> None:
        """Master-side sampling round: every live node's disk usage
        (ref InternalClusterInfoService 30s cadence — here pulled during
        fault-detection rounds)."""
        from .info import DiskUsage
        state = self.cluster.current()
        for node_id in state.nodes:
            if node_id == self.node_id:
                out = self._on_fs_stats(self.node_id, {})
            else:
                try:
                    out = self.transport.send(node_id, A_FS_STATS, {})
                except (ConnectTransportException,
                        RemoteTransportException):
                    continue
            self.cluster_info.usages[node_id] = DiskUsage(
                node_id, int(out.get("total", 0)), int(out.get("free", 0)))

    # -- fault detection (ref discovery/zen/fd/, SURVEY §5.3) ----------

    def fault_detection_round(self) -> None:
        """On the master: ping everyone; below quorum STEP DOWN (the
        ZenDiscovery.java:500-596 rejoin-on-quorum-loss guard), otherwise
        drop the dead (NodesFaultDetection). On a non-master: ping the
        master; if gone, elect (MasterFaultDetection + min-id election).
        Masterless: discover a master via the seed list and rejoin, or
        bootstrap an election if a quorum of seeds agrees there is none."""
        state = self.cluster.current()
        if state.master_node == self.node_id:
            self.refresh_cluster_info()   # disk usages for the deciders
            dead = []
            for node_id in sorted(state.nodes):
                if node_id == self.node_id:
                    continue
                try:
                    self.transport.send(node_id, A_PING, {})
                except (ConnectTransportException, RemoteTransportException):
                    dead.append(node_id)
            live_count = len(state.nodes) - len(dead)
            if live_count < self.minimum_master_nodes:
                self._step_down()
                return
            for node_id in dead:
                self._remove_node(node_id)
        elif state.master_node is not None:
            try:
                resp = self.transport.send(state.master_node, A_PING, {})
                if resp.get("master") != state.master_node:
                    # our master stepped down (quorum loss): detach and go
                    # find whoever the majority elected
                    self.cluster.reset()
                    self._masterless_round()
                elif not resp.get("member", True):
                    # the master dropped us while we were partitioned
                    # away (MasterFaultDetection's node-does-not-exist
                    # contract): reset and rejoin fresh — the master's
                    # next publish replaces our stale state wholesale
                    self.rejoin(state.master_node)
            except (ConnectTransportException, RemoteTransportException):
                self._elect_after_master_loss(state)
        else:
            self._masterless_round()

    def _step_down(self) -> None:
        """Local-only demotion: no publish (we can't reach a quorum anyway).
        The next masterless round rejoins whatever master the majority
        elected — at which point the majority's state replaces ours and any
        writes acked during our minority reign are discarded (the same
        acked-write-loss window the reference documents for quorum loss)."""
        def task(cur: ClusterState) -> None:
            if cur.master_node != self.node_id:
                return None
            st = cur.mutate()
            st.data["master_node"] = None
            self.cluster.apply_local(st)
            return None     # already applied; nothing to publish
        self.cluster.submit_task("step-down[no quorum]", task, wait=False)

    def _masterless_round(self) -> None:
        """Find a live master through the seed list (the LocalTransport
        registry doubles as the unicast ping seed list) and rejoin it; if
        nobody has a master and we'd win a quorum election, take over."""
        seeds = [n for n in self.transport.network.connected_nodes()
                 if n != self.node_id]
        live = [self.node_id]
        masters: set[str] = set()
        for node_id in seeds:
            try:
                resp = self.transport.send(node_id, A_PING, {})
                live.append(node_id)
                if resp.get("master"):
                    masters.add(resp["master"])
            except (ConnectTransportException, RemoteTransportException):
                continue
        for master_id in sorted(masters):
            if master_id == self.node_id:
                continue
            try:
                self.rejoin(master_id)
                return
            except (ConnectTransportException, RemoteTransportException,
                    NoMasterException):
                continue
        if len(live) < self.minimum_master_nodes:
            return
        if min(live) == self.node_id:
            def task(cur: ClusterState) -> ClusterState:
                st = cur.mutate()
                st.data["master_node"] = self.node_id
                st.nodes[self.node_id] = {"id": self.node_id,
                                          "name": self.node_id}
                for node_id in list(st.nodes):
                    if node_id not in live:
                        remove_node(st, node_id, decider=self.deciders)
                return st
            self.cluster.submit_task("become-master[bootstrap]", task)

    def rejoin(self, master_id: str) -> None:
        """Reset local cluster state and join `master_id` fresh — the path a
        healed minority node takes back into the majority. The master's next
        publish replaces our state wholesale; our reconciler then drops any
        shards the majority no longer assigns to us."""
        self.cluster.reset()
        self.join(master_id)

    def _elect_after_master_loss(self, state: ClusterState) -> None:
        """Min-id election among reachable members, guarded by the
        minimum_master_nodes quorum (ref ZenDiscovery.java:500-535 — losing
        quorum means NO master, not a split brain)."""
        dead_master = state.master_node
        live = [self.node_id]
        for node_id in sorted(state.nodes):
            if node_id in (self.node_id, dead_master):
                continue
            try:
                self.transport.send(node_id, A_PING, {})
                live.append(node_id)
            except (ConnectTransportException, RemoteTransportException):
                pass
        if len(live) < self.minimum_master_nodes:
            return      # no quorum: stay masterless rather than split-brain
        new_master = min(live)
        if new_master != self.node_id:
            return      # the winner will notice on its own round

        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            st.data["master_node"] = self.node_id
            if dead_master is not None:
                remove_node(st, dead_master, decider=self.deciders)
            return st
        self.cluster.submit_task("become-master", task)

    def _remove_node(self, node_id: str) -> None:
        def task(cur: ClusterState) -> ClusterState | None:
            if node_id not in cur.nodes:
                return None
            st = cur.mutate()
            remove_node(st, node_id, decider=self.deciders)
            return st
        self.cluster.submit_task(f"node-left[{node_id}]", task, wait=False)

    def _on_node_failed(self, from_id: str, req: dict) -> dict:
        """A peer reports a node unreachable (the reference treats transport
        disconnects as immediate failures, MasterFaultDetection.java:183-187).
        Verify before acting — the reporter's link may be the broken one."""
        node_id = req["node"]
        try:
            self.transport.send(node_id, A_PING, {})
            return {"removed": False}
        except (ConnectTransportException, RemoteTransportException):
            self._remove_node(node_id)
            return {"removed": True}

    # ------------------------------------------------------------------
    # master metadata ops (ref cluster/metadata/MetaData*Service)
    # ------------------------------------------------------------------

    def _master_call(self, action: str, payload: dict) -> Any:
        state = self.cluster.current()
        if state.master_node is None:
            raise NoMasterException("no elected master")
        if state.master_node == self.node_id:
            return self.transport._handle(self.node_id, action, payload)
        return self.transport.send(state.master_node, action, payload)

    def create_index(self, name: str, settings: dict | None = None,
                     mappings: dict | None = None) -> None:
        self._master_call(A_CREATE_INDEX, {
            "index": name, "settings": settings or {},
            "mappings": mappings or {}})

    def delete_index(self, name: str) -> None:
        self._master_call(A_DELETE_INDEX, {"index": name})

    def put_mapping(self, index: str, type_name: str, mapping: dict) -> None:
        self._master_call(A_PUT_MAPPING, {
            "index": index, "type": type_name, "mapping": mapping})

    def _on_create_index(self, from_id: str, req: dict) -> dict:
        name, settings = req["index"], req.get("settings") or {}
        n_shards = int(settings.get("number_of_shards",
                                    settings.get("index.number_of_shards", 1)))
        n_replicas = int(settings.get(
            "number_of_replicas", settings.get("index.number_of_replicas", 1)))

        def task(cur: ClusterState) -> ClusterState:
            if name in cur.indices:
                raise ValueError(f"index [{name}] already exists")
            st = cur.mutate()
            st.indices[name] = {"settings": settings,
                                "mappings": req.get("mappings") or {},
                                "aliases": []}
            st.routing[name] = new_index_routing(n_shards, n_replicas)
            allocate(st, decider=self.deciders)
            return st
        self.cluster.submit_task(f"create-index[{name}]", task)
        return {"acknowledged": True}

    def _on_delete_index(self, from_id: str, req: dict) -> dict:
        name = req["index"]

        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            st.indices.pop(name, None)
            st.routing.pop(name, None)
            return st
        self.cluster.submit_task(f"delete-index[{name}]", task)
        return {"acknowledged": True}

    # -- cluster-level metadata services (ref cluster/metadata/
    #    MetaDataIndexAliasesService, MetaDataUpdateSettingsService,
    #    MetaDataIndexStateService) ---------------------------------------

    def put_alias(self, index: str, alias: str,
                  props: dict | None = None) -> None:
        self._master_call(A_PUT_ALIAS, {"index": index, "alias": alias,
                                        "props": props or {}})

    def delete_alias(self, index: str, alias: str) -> None:
        self._master_call(A_DELETE_ALIAS, {"index": index, "alias": alias})

    def update_index_settings(self, index: str, settings: dict) -> None:
        self._master_call(A_UPDATE_SETTINGS, {"index": index,
                                              "settings": settings})

    def close_index(self, index: str) -> None:
        self._master_call(A_CLOSE_INDEX, {"index": index})

    def open_index(self, index: str) -> None:
        self._master_call(A_OPEN_INDEX, {"index": index})

    def _on_put_alias(self, from_id: str, req: dict) -> dict:
        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            meta = st.indices.get(req["index"])
            if meta is None:
                raise KeyError(f"no such index [{req['index']}]")
            aliases = meta.get("aliases")
            if not isinstance(aliases, dict):     # legacy list form
                aliases = {a: {} for a in (aliases or [])}
            aliases[req["alias"]] = req.get("props") or {}
            meta["aliases"] = aliases
            return st
        self.cluster.submit_task(f"put-alias[{req['alias']}]", task)
        return {"acknowledged": True}

    def _on_delete_alias(self, from_id: str, req: dict) -> dict:
        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            meta = st.indices.get(req["index"])
            if meta is None:
                raise KeyError(f"no such index [{req['index']}]")
            aliases = meta.get("aliases")
            if isinstance(aliases, dict):
                aliases.pop(req["alias"], None)
            elif isinstance(aliases, list) and req["alias"] in aliases:
                aliases.remove(req["alias"])
            return st
        self.cluster.submit_task(f"delete-alias[{req['alias']}]", task)
        return {"acknowledged": True}

    def _on_update_settings(self, from_id: str, req: dict) -> dict:
        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            meta = st.indices.get(req["index"])
            if meta is None:
                raise KeyError(f"no such index [{req['index']}]")
            settings = dict(meta.get("settings") or {})
            settings.update(req.get("settings") or {})
            meta["settings"] = settings
            # a replica-count change RESIZES the routing table live
            # (ref MetaDataUpdateSettingsService.updateSettings ->
            # routing table rebuild + reallocation). Read the count from
            # the UPDATE REQUEST (either key form) — the merged map holds
            # stale creation-time values under the other key
            upd = req.get("settings") or {}
            nr = upd.get("index.number_of_replicas",
                         upd.get("number_of_replicas"))
            if nr is not None:
                nr = int(nr)
                for copies in st.routing.get(req["index"], []):
                    replicas = [c for c in copies if not c["primary"]]
                    # shed UNASSIGNED/INITIALIZING copies before STARTED
                    # ones (the reference drops ignored/unassigned first)
                    order = {UNASSIGNED: 0, INITIALIZING: 1, STARTED: 2}
                    replicas.sort(key=lambda c: order.get(c["state"], 1))
                    for surplus in replicas[: max(len(replicas) - nr, 0)]:
                        copies.remove(surplus)
                    for _ in range(nr - len(replicas)):
                        copies.append({"node": None, "primary": False,
                                       "state": UNASSIGNED})
                allocate(st, decider=self.deciders)
            return st
        self.cluster.submit_task(f"update-settings[{req['index']}]", task)
        return {"acknowledged": True}

    def _on_close_index(self, from_id: str, req: dict) -> dict:
        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            meta = st.indices.get(req["index"])
            if meta is None:
                raise KeyError(f"no such index [{req['index']}]")
            meta["state"] = "close"
            # deallocate: reconcilers drop local shards; data dirs remain
            st.routing.pop(req["index"], None)
            return st
        self.cluster.submit_task(f"close-index[{req['index']}]", task)
        return {"acknowledged": True}

    def _on_open_index(self, from_id: str, req: dict) -> dict:
        name = req["index"]
        # gateway-style primary allocation: probe which nodes still hold
        # shard data from before the close, and pin primaries there so
        # reopening recovers the documents (ref gateway/
        # GatewayAllocator primary-by-existing-copy allocation)
        holders: dict[int, str] = {}
        for node_id in sorted(self.cluster.current().nodes):
            try:
                if node_id == self.node_id:
                    out = self._on_shard_data(self.node_id, {"index": name})
                else:
                    out = self.transport.send(node_id, A_SHARD_DATA,
                                              {"index": name})
            except (ConnectTransportException, RemoteTransportException):
                continue
            for sid in out.get("shards", []):
                holders.setdefault(int(sid), node_id)

        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            meta = st.indices.get(name)
            if meta is None:
                raise KeyError(f"no such index [{name}]")
            if meta.get("state") != "close":
                return None
            meta["state"] = "open"
            settings = meta.get("settings") or {}

            def get_s(key, default):
                # prefixed key WINS: updates arrive as index.* and must
                # not be shadowed by the stale bare creation-time key
                return settings.get(f"index.{key}",
                                    settings.get(key, default))
            routing = new_index_routing(int(get_s("number_of_shards", 1)),
                                        int(get_s("number_of_replicas", 1)))
            for sid, copies in enumerate(routing):
                node_id = holders.get(sid)
                if node_id is not None and node_id in st.nodes:
                    copies[0]["node"] = node_id
                    copies[0]["state"] = INITIALIZING
            st.routing[name] = routing
            allocate(st, decider=self.deciders)
            return st
        self.cluster.submit_task(f"open-index[{name}]", task)
        return {"acknowledged": True}

    def _on_shard_data(self, from_id: str, req: dict) -> dict:
        """Which shards of `index` have data dirs on this node (the
        gateway allocator's TransportNodesListGatewayStartedShards)."""
        base = os.path.join(self.data_path, "indices", req["index"])
        out = []
        if os.path.isdir(base):
            for d in os.listdir(base):
                if d.isdigit():
                    out.append(int(d))
        return {"shards": sorted(out)}

    def _on_put_mapping(self, from_id: str, req: dict) -> dict:
        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            meta = st.indices.get(req["index"])
            if meta is None:
                raise KeyError(f"no such index [{req['index']}]")
            cur_map = meta.setdefault("mappings", {})
            merged = MapperService(mappings=cur_map)
            merged.merge(req["type"], req["mapping"])
            meta["mappings"] = merged.mappings_dict()
            return st
        self.cluster.submit_task(f"put-mapping[{req['index']}]", task)
        return {"acknowledged": True}

    # ------------------------------------------------------------------
    # reconciler (ref IndicesClusterStateService.clusterChanged :150)
    # ------------------------------------------------------------------

    def _apply_cluster_state(self, state: ClusterState) -> None:
        with self._shards_lock:
            # mappings from metadata
            for index, meta in state.indices.items():
                svc = self._mappers.get(index)
                if svc is None:
                    self._mappers[index] = MapperService(
                        mappings=meta.get("mappings") or {})
                else:
                    for tname, m in (meta.get("mappings") or {}).items():
                        svc.merge(tname, m)
            # drop shards (and whole indices) no longer assigned here
            # (ref indices/store/IndicesStore state-driven GC)
            assigned = {(i, s) for i, s, _ in
                        state.assigned_shards(self.node_id)}
            closed = {i for i, m in state.indices.items()
                      if (m or {}).get("state") == "close"}
            for key in [k for k in self._shards
                        if k not in assigned or k[0] not in state.indices]:
                holder = self._shards.pop(key)
                # an in-flight recovery pull (another thread, outside this
                # lock) observes the flag between chunks and aborts —
                # cancel_relocations_for / reassignment cancels cleanly
                # instead of streaming to a dead-end copy (ISSUE 15)
                holder.cancel_recovery = True
                if holder.engine is not None:
                    holder.drop_searcher()
                    holder.engine.close()
                # a CLOSED index keeps its shard data on disk (the engine
                # shuts down, the files stay for reopen — ref
                # MetaDataIndexStateService close semantics); only deleted
                # or relocated-away shards GC their directories
                if key[0] not in closed:
                    import shutil
                    shutil.rmtree(self._shard_path(*key),
                                  ignore_errors=True)
            # GC data dirs of indices DELETED from the metadata entirely —
            # including ones closed first (their shards left self._shards
            # at close time, so the loop above can't see them)
            idx_root = os.path.join(self.data_path, "indices")
            if os.path.isdir(idx_root):
                import shutil
                for iname in os.listdir(idx_root):
                    if iname not in state.indices:
                        shutil.rmtree(os.path.join(idx_root, iname),
                                      ignore_errors=True)
            for index in [i for i in self._mappers
                          if i not in state.indices]:
                del self._mappers[index]
            todo = [(i, s, c) for i, s, c in
                    state.assigned_shards(self.node_id)
                    if c["state"] == INITIALIZING]
        # recoveries run outside _shards_lock: they call into other nodes
        for index, sid, copy_ in todo:
            self._init_shard(state, index, sid, copy_)

    def _shard_path(self, index: str, sid: int) -> str:
        return os.path.join(self.data_path, "indices", index, str(sid))

    def _init_shard(self, state: ClusterState, index: str, sid: int,
                    copy_: dict) -> None:
        key = (index, sid)
        with self._shards_lock:
            holder = self._shards.setdefault(key, _ShardHolder())
        mappers = self._mappers[index]
        if copy_["primary"]:
            if holder.engine is None:
                holder.engine = Engine(self._shard_path(index, sid), mappers)
            # else: in-place promotion of a copy we already host
            self._report_started(index, sid, copy_.get("aid"))
            return
        # replica / relocation target: peer recovery over the seam. An
        # EXISTING local engine is stale by definition — this copy was
        # unassigned (e.g. after a failed replication hop) and must re-sync,
        # or it would come back STARTED while missing acked writes.
        source_node = copy_.get("recover_from")
        if source_node is None:
            primary = state.primary_of(index, sid)
            if primary is None \
                    or primary["state"] not in (STARTED, RELOCATING):
                return      # allocator shouldn't have scheduled this; wait
            source_node = primary["node"]
        aid = copy_.get("aid")
        with holder.lock:
            if holder.recovering:
                if holder.recovery_aid == aid:
                    return      # THIS pull is already in flight
                # an OLDER era's pull is still streaming (its started
                # report would be dropped by the master's aid fence):
                # abort it and re-enter once its thread exits — without
                # this handoff the new assignment would sit INITIALIZING
                # with no pull behind it
                holder.cancel_recovery = True
                if not holder.reinit_pending:
                    holder.reinit_pending = True
                    threading.Thread(
                        target=self._reinit_after_cancel,
                        args=(index, sid, holder),
                        name=f"recovery-reinit[{self.node_id}]"
                             f"[{index}][{sid}]",
                        daemon=True).start()
                return
            holder.recovering = True
            holder.recovery_aid = aid
            holder.cancel_recovery = False
            if holder.engine is not None:
                holder.drop_searcher()
                holder.engine.close()
                holder.engine = None
        # the stream itself runs OFF the state-apply thread (ref: the
        # dedicated recovery thread pool). Applied inline it would block
        # the master's publish for the whole transfer, serializing every
        # later state task behind one slow stream — which is exactly what
        # made mid-stream cancellation (cancel_relocations_for, index
        # deletion) unreachable. The holder is registered with
        # `recovering` set BEFORE this returns, so replica ops arriving
        # early buffer into `pending` instead of failing.
        threading.Thread(
            target=self._run_peer_recovery,
            args=(index, sid, holder, source_node, mappers,
                  copy_.get("aid")),
            name=f"recovery[{self.node_id}][{index}][{sid}]",
            daemon=True).start()

    def _reinit_after_cancel(self, index: str, sid: int, holder) -> None:
        """A newer assignment era superseded an in-flight pull: wait for
        the aborted stream's thread to exit, then re-run _init_shard
        against the CURRENT state (the era that displaced it — or an even
        newer one; _init_shard re-reads the copy either way)."""
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with holder.lock:
                if not holder.recovering:
                    holder.reinit_pending = False
                    break
            time.sleep(0.01)
        else:
            with holder.lock:
                holder.reinit_pending = False
            return
        if self.closed:
            return
        state = self.cluster.current()
        if index not in state.routing:
            return      # deleted while the old pull drained
        copy_ = next(
            (c for c in state.shard_copies(index, sid)
             if c["node"] == self.node_id and c["state"] == INITIALIZING),
            None)
        if copy_ is not None and index in self._mappers:
            self._init_shard(state, index, sid, copy_)

    def _run_peer_recovery(self, index: str, sid: int, holder,
                           source_node: str, mappers,
                           aid: int | None = None) -> None:
        path = self._shard_path(index, sid)
        from .recovery import RecoveryCancelled, record
        rec = {"index": index, "shard": sid, "source": source_node,
               "target": self.node_id, "stage": "init",
               "files_total": 0, "files_reused": 0, "bytes_total": 0,
               "bytes_recovered": 0, "throttle_waits": 0, "retries": 0,
               "start_s": time.monotonic(),
               "start_time_ms": self._wall_ms(), "elapsed_ms": 0.0}
        with self._recoveries_lock:
            self.recoveries[(index, sid)] = rec
        try:
            with self.tracer.request(
                    "recovery",
                    attrs={"index": index, "shard": sid,
                           "source": source_node}):
                ok = self._recover_files_from(source_node, index, sid,
                                              path, holder=holder, rec=rec)
        except RecoveryCancelled:
            # a newer cluster state unassigned this copy mid-stream:
            # abandon the pull, GC the partial files, report nothing
            rec["stage"] = "cancelled"
            record("cancelled_total")
            import shutil
            shutil.rmtree(path, ignore_errors=True)
            with holder.lock:
                holder.recovering = False
            rec["elapsed_ms"] = (time.monotonic() - rec["start_s"]) * 1000
            return
        except (ConnectTransportException, RemoteTransportException):
            ok = False
        rec["elapsed_ms"] = (time.monotonic() - rec["start_s"]) * 1000
        if not ok:
            rec["stage"] = "failed"
            with holder.lock:
                holder.recovering = False
            # tell the master so it unassigns/reverts THIS assignment and
            # re-allocates now — waiting for an incidental later publish
            # leaves the copy INITIALIZING (and the cluster un-green)
            # indefinitely
            self._report_failed(index, sid, aid)
            return
        rec["stage"] = "done"
        record("completed_total")
        with holder.lock:
            holder.engine = Engine(path, mappers)
            for op in holder.pending:
                self._apply_replica_op(holder, op)
            holder.pending.clear()
            holder.recovering = False
        self._report_started(index, sid, aid)

    RECOVERY_CHUNK = 1 << 19   # 512 KiB per RPC — bounded memory both sides
    RECOVERY_RETRIES = 3       # per-chunk resend attempts before giving up
    RECOVERY_RETRY_BACKOFF_S = 0.05   # doubled per attempt

    def _recovery_rate(self) -> float:
        """Live `indices.recovery.max_bytes_per_sec` (cluster settings;
        default 40mb like the reference's RecoverySettings). 0 / negative
        disables the throttle."""
        from .recovery import parse_bytes
        st = self.cluster.current().data.get("settings") or {}
        return parse_bytes(
            st.get("indices.recovery.max_bytes_per_sec", "40mb"))

    def _check_cancel(self, holder, index: str, sid: int) -> None:
        if holder is not None and holder.cancel_recovery:
            from .recovery import RecoveryCancelled
            raise RecoveryCancelled(f"[{index}][{sid}] unassigned")

    def _recovery_chunk_call(self, source: str, payload: dict,
                             rec: dict | None, holder=None) -> dict:
        """One chunk RPC with retry-with-backoff: a transient send fault
        (chaos drop, queue timeout) resends the SAME bounded read —
        chunk reads are pure, so the retry is idempotent by construction.
        The cancel flag wins over the retry loop: once this copy is
        unassigned, a failing source (often deleted along with the copy)
        must surface as a clean cancellation, not a retry storm ending
        in `failed`. The final failure propagates and aborts."""
        from .recovery import record
        for attempt in range(self.RECOVERY_RETRIES + 1):
            self._check_cancel(holder, payload["index"], payload["shard"])
            try:
                return self.transport.send(source, A_RECOVERY_CHUNK,
                                           payload)
            except (ConnectTransportException, RemoteTransportException):
                self._check_cancel(holder, payload["index"],
                                   payload["shard"])
                if attempt >= self.RECOVERY_RETRIES:
                    raise
                record("retries_total")
                if rec is not None:
                    rec["retries"] += 1
                time.sleep(self.RECOVERY_RETRY_BACKOFF_S * (2 ** attempt))
        raise AssertionError("unreachable")

    def _recover_files_from(self, source: str, index: str, sid: int,
                            path: str, holder=None,
                            rec: dict | None = None) -> bool:
        """STREAMING, delta peer recovery (ref indices/recovery/
        RecoverySourceHandler.java:149-195): fetch the source's file
        manifest, REUSE local files whose name+size+checksum already match
        (the checksum-delta phase-1 optimization), stream the rest in
        bounded chunks, verify each file's checksum on arrival. Never holds
        more than one chunk in memory per side. Each received chunk pays
        the node-wide token bucket (`indices.recovery.max_bytes_per_sec`),
        failed sends retry with backoff, and the holder's cancel flag is
        honored between chunks (RecoveryCancelled)."""
        import zlib

        from .recovery import record

        self._check_cancel(holder, index, sid)
        manifest = self.transport.send(source, A_RECOVERY,
                                       {"index": index, "shard": sid})
        os.makedirs(path, exist_ok=True)
        want = {f["name"]: f for f in manifest["files"]}
        if rec is not None:
            rec["stage"] = "index"
            rec["files_total"] = len(want)
            rec["bytes_total"] = sum(f["size"] for f in want.values())
        # drop local files not in the manifest — INCLUDING the translog
        # (a stale translog would replay old ops over recovered state)
        for root, _dirs, files in os.walk(path):
            for fn in files:
                fp = os.path.join(root, fn)
                if os.path.relpath(fp, path) not in want:
                    os.remove(fp)
        reused = 0
        for rel, meta in want.items():
            self._check_cancel(holder, index, sid)
            dst = os.path.join(path, rel)
            if os.path.exists(dst) \
                    and os.path.getsize(dst) == meta["size"] \
                    and _crc_prefix(dst, meta["size"],
                                    self.RECOVERY_CHUNK) == meta["crc"]:
                reused += 1
                if rec is not None:
                    rec["files_reused"] += 1
                continue        # identical — skip the copy entirely
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            crc = 0
            with open(dst, "wb") as f:
                off = 0
                while off < meta["size"]:
                    self._check_cancel(holder, index, sid)
                    n = min(self.RECOVERY_CHUNK, meta["size"] - off)
                    t0 = time.monotonic_ns()
                    r = self._recovery_chunk_call(source, {
                        "index": index, "shard": sid, "file": rel,
                        "offset": off, "length": n}, rec, holder=holder)
                    got = len(r["data"])
                    # the TARGET pays the token bucket for what it just
                    # pulled — N concurrent recoveries share one budget
                    slept = self.recovery_throttle.acquire(got)
                    tracing.add_span("recovery_chunk", t0,
                                     time.monotonic_ns(), file=rel,
                                     offset=off, bytes=got,
                                     throttle_s=round(slept, 4))
                    record("bytes_total", got)
                    record("chunks_total")
                    if slept > 0.0:
                        record("throttle_waits_total")
                    if rec is not None:
                        rec["bytes_recovered"] += got
                        if slept > 0.0:
                            rec["throttle_waits"] += 1
                    f.write(r["data"])
                    crc = zlib.crc32(r["data"], crc)
                    off += got
                    if not r["data"]:
                        break
            if crc != meta["crc"]:
                return False        # torn read; retry on a later state
        return True

    def _report_started(self, index: str, sid: int,
                        aid: int | None = None) -> None:
        try:
            self._master_call(A_SHARD_STARTED, {
                "index": index, "shard": sid, "node": self.node_id,
                "aid": aid})
        except (NoMasterException, ConnectTransportException,
                RemoteTransportException):
            pass        # next publish/fault round sorts it out

    def _report_failed(self, index: str, sid: int,
                       aid: int | None = None) -> None:
        try:
            self._master_call(A_SHARD_FAILED, {
                "index": index, "shard": sid, "node": self.node_id,
                "aid": aid})
        except (NoMasterException, ConnectTransportException,
                RemoteTransportException):
            pass        # next publish/fault round sorts it out

    def _on_shard_started(self, from_id: str, req: dict) -> dict:
        index, sid, node_id = req["index"], req["shard"], req["node"]
        # allocation-id fence (ref AllocationId): a report only acts on
        # the assignment era it came from. Without this, a restarted
        # process's STALE report (its pre-kill pull completing late)
        # matched the copy's NEW assignment and marked STARTED a copy
        # whose actual pull had failed — a zombie serving nothing.
        aid = req.get("aid")

        def task(cur: ClusterState) -> ClusterState | None:
            if index not in cur.routing:
                return None
            st = cur.mutate()
            changed = False
            for c in st.routing[index][sid]:
                if c["node"] == node_id and c["state"] == INITIALIZING \
                        and (aid is None or c.get("aid") == aid):
                    if c.get("relocation"):
                        changed |= finish_relocation(st, index, sid, node_id)
                    else:
                        c["state"] = STARTED
                        c.pop("fresh", None)
                        changed = True
            if changed:
                allocate(st, decider=self.deciders)    # replicas may now be able to initialize
                rebalance(st, decider=self.deciders)   # ...and the next relocation wave can start
                return st
            return None
        self.cluster.submit_task(
            f"shard-started[{index}][{sid}]", task, wait=False)
        return {"ok": True}

    def _on_shard_failed(self, from_id: str, req: dict) -> dict:
        index, sid, node_id = req["index"], req["shard"], req["node"]
        # same allocation-id fence as shard-started: a late failure
        # notice from a previous era must not unassign (or revert the
        # relocation of) the copy's CURRENT, healthy assignment. A
        # report without an aid (legacy callers, harness) matches any.
        aid = req.get("aid")

        def task(cur: ClusterState) -> ClusterState | None:
            if index not in cur.routing:
                return None
            st = cur.mutate()
            changed = False
            copies = st.routing[index][sid]
            for c in [c for c in copies if c["node"] == node_id
                      and (aid is None or c.get("aid") == aid)]:
                if c.get("relocation"):
                    copies.remove(c)     # failed target: revert the move
                    for s in copies:
                        if s.get("relocating_to") == node_id:
                            s["state"] = STARTED
                            s.pop("relocating_to", None)
                    changed = True
                elif c["state"] == RELOCATING:
                    # failing SOURCE mid-move: the target's recovery
                    # source is gone, so drop the orphaned target AND
                    # clear the pointer — unassigning while leaving
                    # `relocating_to` behind is the zombie that made
                    # finish_relocation later double-handle the shard
                    # (ISSUE 15 race fix)
                    tgt = c.pop("relocating_to", None)
                    for t in [t for t in copies
                              if t.get("relocation")
                              and (t["node"] == tgt
                                   or t.get("recover_from") == node_id)]:
                        copies.remove(t)
                    if c["primary"]:
                        c["state"] = STARTED   # same revert as cancel
                    else:
                        c["node"] = None
                        c["state"] = UNASSIGNED
                    changed = True
                elif not c["primary"]:
                    c["node"] = None
                    c["state"] = UNASSIGNED
                    changed = True
            if changed:
                allocate(st, decider=self.deciders)
                # a failure reshapes the table: re-evaluate moves so an
                # interrupted drain (exclude filter, disk evacuation)
                # retries instead of stranding the shard on a vetoed node
                rebalance(st, decider=self.deciders)
                return st
            return None
        self.cluster.submit_task(
            f"shard-failed[{index}][{sid}][{node_id}]", task, wait=False)
        return {"ok": True}

    # -- recovery source (ref RecoverySourceHandler.java:149-195) -------

    def _on_recovery(self, from_id: str, req: dict) -> dict:
        """Recovery phase 1 START: flush under the engine write lock, then
        publish the file MANIFEST (name, size, crc). Segment files are
        write-once after flush, so chunk reads need no lock; ops acked
        after the lock releases reach the target through normal forwarding
        (idempotent by version). Ref RecoverySourceHandler.java:149-195 —
        the checksum manifest is what enables the delta-reuse phase."""
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None or holder.engine is None:
            raise UnavailableShardsException(
                f"not hosting [{req['index']}][{req['shard']}]")
        eng = holder.engine
        names: list[tuple[str, int]] = []
        with eng._lock:
            # lock held only for flush + size snapshot — checksums run
            # AFTER release (post-flush files are write-once/append-only,
            # so the [0, size) prefix is stable; code review r5)
            eng.flush()
            for fn in sorted(os.listdir(eng.path)):
                fp = os.path.join(eng.path, fn)
                if os.path.isfile(fp):
                    names.append((fn, os.path.getsize(fp)))
        files = [{"name": fn, "size": size,
                  "crc": _crc_prefix(os.path.join(eng.path, fn), size,
                                     self.RECOVERY_CHUNK)}
                 for fn, size in names]
        return {"files": files}

    def _on_recovery_chunk(self, from_id: str, req: dict) -> dict:
        """One bounded chunk of a write-once recovery file."""
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None or holder.engine is None:
            raise UnavailableShardsException(
                f"not hosting [{req['index']}][{req['shard']}]")
        fp = os.path.join(holder.engine.path, req["file"])
        length = min(int(req["length"]), self.RECOVERY_CHUNK)
        with open(fp, "rb") as f:
            f.seek(int(req["offset"]))
            return {"data": f.read(length)}

    # -- recovery progress + cluster settings (ISSUE 15) ----------------

    def _wall_ms(self) -> int:
        """Wall-clock ms WITH the chaos clock skew applied — used only
        for reported timestamps, never for durations or throttling."""
        return int((time.time() + self.clock_skew_s) * 1000)

    def _on_recovery_stats(self, from_id: str, req: Any) -> dict:
        """This node's per-shard recovery rows (target side) for the
        GET /_cat/recovery fan-out (ref RecoveryState / indices:monitor/
        recovery)."""
        rows = []
        with self._recoveries_lock:
            recs = [dict(r) for r in self.recoveries.values()]
        for row in recs:
            if row["stage"] not in ("done", "failed", "cancelled"):
                row["elapsed_ms"] = \
                    (time.monotonic() - row["start_s"]) * 1000
            row.pop("start_s", None)
            rows.append(row)
        return {"recoveries": rows}

    def cat_recovery(self) -> list[dict]:
        """Every node's recovery rows, sorted — GET /_cat/recovery."""
        state = self.cluster.current()
        rows: list[dict] = []
        for node_id in sorted(state.nodes):
            try:
                if node_id == self.node_id:
                    out = self._on_recovery_stats(self.node_id, {})
                else:
                    out = self.transport.send(node_id, A_RECOVERY_STATS,
                                              {})
            except (ConnectTransportException, RemoteTransportException):
                continue
            rows.extend(out.get("recoveries", []))
        rows.sort(key=lambda r: (r["index"], r["shard"], r["target"]))
        return rows

    def update_cluster_settings(self, settings: dict) -> dict:
        """PUT /_cluster/settings: merge into the live cluster-level
        settings map and reroute — the deciders read these live, so an
        exclude filter update starts draining on this very task."""
        return self._master_call(A_CLUSTER_SETTINGS,
                                 {"settings": settings})

    def _on_cluster_settings(self, from_id: str, req: dict) -> dict:
        upd = req.get("settings") or {}

        def task(cur: ClusterState) -> ClusterState:
            st = cur.mutate()
            cs = dict(st.data.get("settings") or {})
            for k, v in upd.items():
                if v is None:
                    cs.pop(k, None)     # null resets to default
                else:
                    cs[k] = v
            st.data["settings"] = cs
            # allocation settings changed: reroute under the new rules
            allocate(st, decider=self.deciders)
            rebalance(st, decider=self.deciders)
            return st
        self.cluster.submit_task("cluster-settings", task)
        return {"acknowledged": True, "transient": dict(upd)}

    def allocation_explain(self, index: str | None = None,
                           shard: int | None = None,
                           primary: bool | None = None) -> dict:
        """POST /_cluster/allocation/explain: run EVERY decider for one
        shard copy against EVERY node and report the per-decider
        verdicts (ref ClusterAllocationExplainAction). With no body the
        first unassigned copy explains itself, like the reference."""
        state = self.cluster.current()
        target = None
        if index is None:
            for iname, shards in state.routing.items():
                for sid, copies in enumerate(shards):
                    for c in copies:
                        if c["state"] == UNASSIGNED:
                            index, shard, target = iname, sid, c
                            break
                    if target is not None:
                        break
                if target is not None:
                    break
            if target is None:
                raise ValueError(
                    "unable to find any unassigned shards to explain — "
                    "specify index and shard")
        if index not in state.routing:
            raise KeyError(f"no such index [{index}]")
        sid = int(shard or 0)
        if sid >= len(state.routing[index]):
            raise KeyError(f"no such shard [{index}][{sid}]")
        copies = state.routing[index][sid]
        if target is None:
            if primary is not None:
                target = next((c for c in copies
                               if bool(c["primary"]) == bool(primary)),
                              copies[0])
            else:
                target = next((c for c in copies
                               if c["state"] == UNASSIGNED), copies[0])
        decisions = [self.deciders.explain(state, index, sid, n)
                     for n in sorted(state.nodes)]
        overall = {d["decision"] for d in decisions}
        can = "yes" if "YES" in overall else (
            "throttle" if "THROTTLE" in overall else "no")
        return {"index": index, "shard": sid,
                "primary": bool(target["primary"]),
                "current_state": target["state"].lower(),
                "current_node": target.get("node"),
                "can_allocate": can,
                "node_allocation_decisions": decisions}

    # ------------------------------------------------------------------
    # write path (ref TransportShardReplicationOperationAction.java:67)
    # ------------------------------------------------------------------

    def index_doc(self, index: str, doc_id: str | None, source: dict,
                  type_name: str = "_doc", routing: str | None = None,
                  _local_defer: set | None = None,
                  _replica_defer: dict | None = None, **kw) -> dict:
        if doc_id is None:
            import uuid
            doc_id = uuid.uuid4().hex[:20]
        return self._write_op(index, {
            "op": "index", "id": doc_id, "source": source, "type": type_name,
            "routing": routing, **kw}, local_defer=_local_defer,
            replica_defer=_replica_defer)

    def delete_doc(self, index: str, doc_id: str,
                   routing: str | None = None,
                   _local_defer: set | None = None,
                   _replica_defer: dict | None = None, **kw) -> dict:
        return self._write_op(index, {"op": "delete", "id": doc_id,
                                      "routing": routing, **kw},
                              local_defer=_local_defer,
                              replica_defer=_replica_defer)

    def bulk(self, operations: list[tuple[str, dict, dict | None]]) -> list[dict]:
        """(action, meta, source) ops -> per-item results (ref
        TransportBulkAction split-by-shard; per-item error contract).

        Group commit for locally-held primaries: their ops defer the
        per-op translog fsync and every touched local engine syncs ONCE
        at the end of the request (the reference's per-request
        durability). Ops forwarded to remote primaries keep their per-op
        durability — the remote node acks only after its own fsync.

        Replica replication batches the same way (ISSUE 11 satellite):
        locally-held primaries append each replica op to a per-target-NODE
        batch instead of sending one framed A_WRITE_R per op, and the
        whole request's replication rides ONE A_WRITE_R_BULK send per
        (node, request) on the bulk transport class — per-op apply/buffer
        semantics on the replica and per-shard failure reporting are
        unchanged."""
        items = []
        deferred: set = set()    # local engines written with sync=False
        replica_defer: dict[str, list[dict]] = {}   # node -> replica ops
        try:
            for op_t in operations:
                # (action, meta, source) or (action, meta, source, raw_len)
                action, meta, source = op_t[0], op_t[1], op_t[2]
                index = meta.get("_index")
                type_name = meta.get("_type", "_doc")
                doc_id = meta.get("_id")
                try:
                    if action in ("index", "create"):
                        r = self.index_doc(
                            index, doc_id, source, type_name=type_name,
                            routing=meta.get("_routing")
                            or meta.get("routing"),
                            op_type="create" if action == "create"
                            else "index",
                            _local_defer=deferred,
                            _replica_defer=replica_defer)
                        items.append({action: {
                            "_index": index, "_type": type_name,
                            "_id": r["_id"], "_version": r["_version"],
                            "status": 201 if r.get("created") else 200}})
                    elif action == "delete":
                        r = self.delete_doc(
                            index, doc_id,
                            routing=meta.get("_routing")
                            or meta.get("routing"),
                            _local_defer=deferred,
                            _replica_defer=replica_defer)
                        items.append({"delete": {
                            "_index": index, "_type": type_name,
                            "_id": doc_id,
                            "_version": r["_version"],
                            "found": r.get("found", True),
                            "status": 200 if r.get("found", True) else 404}})
                    else:
                        items.append({action: {
                            "status": 400,
                            "error": f"unsupported bulk action [{action}]"}})
                except VersionConflictException as e:
                    items.append({action: {"_index": index, "_id": doc_id,
                                           "status": 409, "error": str(e)}})
                except Exception as e:  # noqa: BLE001 — per-item contract
                    items.append({action: {"_index": index, "_id": doc_id,
                                           "status": 400, "error": str(e)}})
        finally:
            # the request's whole replication: ONE framed send per target
            # node (bulk transport class), replicas ack before we return
            self._flush_replica_batches(replica_defer)
            for eng in deferred:
                try:
                    eng.translog.sync()
                except Exception:  # noqa: BLE001 — engine may have closed
                    pass
        return items

    def _flush_replica_batches(self, replica_defer: dict) -> None:
        """Send each target node its batched replica ops as one framed
        A_WRITE_R_BULK message. Failure semantics match the per-op path:
        an unreachable/erroring replica node fails its shards to the
        master (the write itself already succeeded on the primary), and
        per-op not-hosted errors come back in the response."""
        for target, ops in replica_defer.items():
            if not ops:
                continue
            failed_shards: list[tuple[str, int]] = []
            try:
                r = self.transport.send(target, A_WRITE_R_BULK,
                                        {"ops": ops})
                failed_shards = [(f["index"], f["shard"])
                                 for f in r.get("failed", [])]
            except (ConnectTransportException, RemoteTransportException):
                failed_shards = sorted({(op["index"], op["shard"])
                                        for op in ops})
            for index, sid in failed_shards:
                aid = next((c.get("aid") for c
                            in self.cluster.current().shard_copies(index, sid)
                            if c["node"] == target), None)
                try:
                    self._master_call(A_SHARD_FAILED, {
                        "index": index, "shard": sid, "node": target,
                        "aid": aid})
                except Exception:  # noqa: BLE001 — masterless interim
                    pass

    def _on_replica_bulk(self, from_id: str, req: dict) -> dict:
        """Apply a batch of replica ops in arrival order — exactly the
        per-op A_WRITE_R semantics (buffer during recovery, external-
        version apply), one framed message for the whole request."""
        applied = 0
        failed: list[dict] = []
        for op in req.get("ops", []):
            holder = self._shards.get((op["index"], op["shard"]))
            if holder is None:
                failed.append({"index": op["index"], "shard": op["shard"]})
                continue
            with holder.lock:
                if holder.recovering or holder.engine is None:
                    holder.pending.append(op)
                else:
                    self._apply_replica_op(holder, op)
            applied += 1
        return {"applied": applied, "failed": failed}

    def _write_op(self, index: str, op: dict, timeout: float = 10.0,
                  local_defer: set | None = None,
                  replica_defer: dict | None = None) -> dict:
        """Route to the primary, retrying on stale routing / primary
        failover — the reference's retry-on-cluster-state-change loop.
        local_defer: when set and the primary is LOCAL, the engine write
        skips its per-op fsync and the engine joins the set for the
        caller's single end-of-request sync (bulk group commit).
        replica_defer: when set and the primary is LOCAL, replica ops
        batch per target node instead of one framed send per op — the
        caller flushes one A_WRITE_R_BULK per node at request end."""
        deadline = time.monotonic() + timeout
        last_err: Exception | None = None
        while time.monotonic() < deadline:
            state = self.cluster.current()
            meta = state.index_meta(index)
            if meta is None:
                # auto-create may lose a race with a concurrent creator or
                # hit a masterless interim — both just mean "retry the loop"
                try:
                    self.create_index(index, {}, {})
                except NoMasterException as e:
                    last_err = e
                    time.sleep(0.02)
                except Exception as e:  # noqa: BLE001
                    if "already exists" not in str(e):
                        raise
                    last_err = e
                continue
            n_shards = len(state.routing[index])
            sid = route_shard(op["id"], n_shards, op.get("routing"))
            primary = state.primary_of(index, sid)
            if primary is None \
                    or primary["state"] not in (STARTED, RELOCATING):
                time.sleep(0.02)
                continue
            payload = {**op, "index": index, "shard": sid}
            try:
                if primary["node"] == self.node_id:
                    if local_defer is not None:
                        payload = {**payload, "sync": False}
                    res = self._on_primary_write(self.node_id, payload,
                                                 _replica_defer=replica_defer)
                    if local_defer is not None:
                        holder = self._shards.get((index, sid))
                        if holder is not None and holder.engine is not None:
                            local_defer.add(holder.engine)
                    return res
                return self.transport.send(primary["node"], A_WRITE_P, payload)
            except ConnectTransportException as e:
                last_err = e
                # transport disconnect == immediate failure report
                try:
                    self._master_call(A_NODE_FAILED,
                                      {"node": primary["node"]})
                except Exception:  # noqa: BLE001 — masterless interim
                    pass
                # the dead node may have BEEN the master: drive a detection
                # round ourselves so an election can proceed (the reference
                # couples this to transport disconnect events)
                self.fault_detection_round()
                time.sleep(0.02)
            except RemoteTransportException as e:
                if e.error_type == "VersionConflictException":
                    raise VersionConflictException(op["id"], -1, -1) from e
                if e.error_type in ("UnavailableShardsException",
                                    "NoMasterException"):
                    # stale routing: the addressee no longer holds the
                    # primary (demoted/relocated) — refresh state and retry
                    last_err = e
                    time.sleep(0.02)
                    continue
                raise
        raise UnavailableShardsException(
            f"[{index}] shard for [{op['id']}] not available: {last_err}")

    def _on_primary_write(self, from_id: str, req: dict,
                          _replica_defer: dict | None = None) -> dict:
        index, sid = req["index"], req["shard"]
        holder = self._shards.get((index, sid))
        state = self.cluster.current()
        primary = state.primary_of(index, sid)
        if holder is None or holder.engine is None or primary is None \
                or primary["node"] != self.node_id:
            raise UnavailableShardsException(
                f"[{index}][{sid}] primary not on [{self.node_id}]")
        if req["op"] == "index":
            mappers = self._mappers[index]
            mv = mappers.mapping_version()
            res = holder.engine.index(
                req["id"], req["source"], type_name=req.get("type", "_doc"),
                version=req.get("version"),
                version_type=req.get("version_type", "internal"),
                op_type=req.get("op_type", "index"),
                sync=req.get("sync"))
            if mappers.mapping_version() != mv:
                # dynamic mapping delta -> master metadata, so COORDINATORS
                # can parse queries/sorts on the new fields (ref
                # TransportIndexAction.java:194-227 MappingUpdatedAction;
                # here post-ack because replicas re-derive deterministically)
                tname = req.get("type", "_doc")
                try:
                    self._master_call(A_PUT_MAPPING, {
                        "index": index, "type": tname,
                        "mapping": mappers._mappers[tname].mapping_dict()})
                except Exception:  # noqa: BLE001 — next write retries
                    pass
        else:
            res = holder.engine.delete(
                req["id"], version=req.get("version"),
                version_type=req.get("version_type", "internal"),
                sync=req.get("sync"))
        # sync replication fan-out (ref :118-120 — replicas ack before we do)
        replica_req = {"index": index, "shard": sid, "op": req["op"],
                       "id": req["id"], "source": req.get("source"),
                       "type": req.get("type", "_doc"),
                       "version": res.version}
        for c in state.shard_copies(index, sid):
            if c["primary"] or c["node"] in (None, self.node_id) \
                    or c["state"] not in (STARTED, INITIALIZING,
                                          RELOCATING):
                continue
            if _replica_defer is not None:
                # bulk batching: this op joins its target node's batch —
                # ONE framed send per (node, request) at request end
                _replica_defer.setdefault(c["node"], []).append(replica_req)
                continue
            try:
                self.transport.send(c["node"], A_WRITE_R, replica_req)
            except (ConnectTransportException, RemoteTransportException):
                # failed replica → master unassigns it (ref replica-failure
                # notification); the write itself still succeeds
                try:
                    self._master_call(A_SHARD_FAILED, {
                        "index": index, "shard": sid, "node": c["node"],
                        "aid": c.get("aid")})
                except Exception:  # noqa: BLE001
                    pass
        return {"_index": index, "_id": res.doc_id, "_version": res.version,
                "created": res.created, "found": res.found}

    def _on_replica_write(self, from_id: str, req: dict) -> dict:
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None:
            raise UnavailableShardsException(
                f"replica [{req['index']}][{req['shard']}] not hosted")
        with holder.lock:
            if holder.recovering or holder.engine is None:
                holder.pending.append(req)
                return {"buffered": True}
            self._apply_replica_op(holder, req)
        return {"applied": True}

    def _apply_replica_op(self, holder: _ShardHolder, req: dict) -> None:
        """External-version apply: strictly-newer wins, equal/older is a
        no-op (the op already arrived via recovery file copy)."""
        try:
            if req["op"] == "index":
                holder.engine.index(req["id"], req["source"],
                                    type_name=req.get("type", "_doc"),
                                    version=req["version"],
                                    version_type="external")
            else:
                holder.engine.delete(req["id"], version=req["version"],
                                     version_type="external")
        except VersionConflictException:
            pass

    # ------------------------------------------------------------------
    # reads
    # ------------------------------------------------------------------

    def get_doc(self, index: str, doc_id: str,
                routing: str | None = None) -> dict:
        """Single-shard read with retry-on-next-copy (ref action/support/
        single/shard/TransportShardSingleOperationAction.java:123 — a
        failed copy falls through to the next one in the iteration;
        round-robin start spreads read load across copies)."""
        state = self.cluster.current()
        if index not in state.routing:
            raise KeyError(f"no such index [{index}]")
        sid = route_shard(doc_id, len(state.routing[index]), routing)
        copies = [c for c in state.routing[index][sid]
                  if c["state"] == STARTED]
        if not copies:
            raise UnavailableShardsException(f"[{index}][{sid}]")
        # prefer local, then rotate (OperationRouting.java:144-154)
        rr = self._read_rr
        start = rr.get((index, sid), 0)
        rr[(index, sid)] = start + 1
        ordered = sorted(
            copies, key=lambda c: (c["node"] != self.node_id,))
        if ordered[0]["node"] != self.node_id and len(ordered) > 1:
            ordered = ordered[start % len(ordered):] \
                + ordered[: start % len(ordered)]
        payload = {"index": index, "shard": sid, "id": doc_id}
        last_err: Exception | None = None
        for c in ordered:
            try:
                if c["node"] == self.node_id:
                    return self._on_get(self.node_id, payload)
                return self.transport.send(c["node"], A_GET, payload)
            except (ConnectTransportException, RemoteTransportException,
                    UnavailableShardsException) as e:
                last_err = e             # dead/stale copy: try the next
        raise UnavailableShardsException(
            f"[{index}][{sid}]: all copies failed") from last_err

    def _on_get(self, from_id: str, req: dict) -> dict:
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None or holder.engine is None:
            raise UnavailableShardsException(f"[{req['index']}]")
        r = holder.engine.get(req["id"])
        return {"found": r.found, "_id": req["id"],
                "_version": r.version if r.found else None,
                "_source": r.source if r.found else None}

    # -- distributed search (QUERY_THEN_FETCH over the transport seam) --
    #
    # The FULL search body crosses the seam: query, sort, aggs, highlight,
    # suggest, rescore, knn, search_after, _source. The shard side parses
    # with ITS mappers and returns wire-encoded QuerySearchResult pieces
    # (doc keys + scores + materialized sort values + agg partials +
    # suggest partials); the coordinator reduces exactly like the
    # single-node controller. A DFS term-stats round runs first so every
    # shard scores with cluster-global IDF — distributed answers match the
    # single-node engine bit-for-bit (ref TransportSearchTypeAction.java:
    # 85-177 + SearchPhaseController.java:282-399 + DfsPhase.java:57-81).

    def search_shards(self, state: ClusterState, names: list[str],
                      preference: str | None = None) -> list[tuple]:
        """One STARTED copy per shard, round-robin across copies so
        replicas add read QPS (ref OperationRouting.java:144-154);
        preference=_local / _primary / _only_local supported."""
        targets: list[tuple[str, str, int]] = []   # (node, index, shard)
        for name in names:
            for sid in range(len(state.routing[name])):
                copies = state.started_copies(name, sid)
                if not copies:
                    raise UnavailableShardsException(f"[{name}][{sid}]")
                if preference in ("_local", "_only_local"):
                    node = next((c["node"] for c in copies
                                 if c["node"] == self.node_id), None)
                    if node is None:
                        if preference == "_only_local":
                            raise UnavailableShardsException(
                                f"[{name}][{sid}] has no local copy")
                        node = copies[0]["node"]
                elif preference == "_primary":
                    node = next((c["node"] for c in copies if c["primary"]),
                                copies[0])["node"] \
                        if any(c["primary"] for c in copies) \
                        else copies[0]["node"]
                else:
                    rr = self._read_rr.get((name, sid), 0)
                    self._read_rr[(name, sid)] = rr + 1
                    node = copies[rr % len(copies)]["node"]
                targets.append((node, name, sid))
        return targets

    def _shard_call(self, node: str, action: str, payload: dict):
        # always through the network object — self-sends round-trip the
        # wire format too, so wire-unsafe payloads fail in every test
        # topology, not only when the shard happens to be remote
        return self.transport.send(node, action, payload)

    # -- hedged replica reads (ISSUE 9) -----------------------------------

    def _hedge_setting(self, key: str, default):
        st = self.cluster.current().data.get("settings") or {}
        return st.get(key, self.hedge_settings.get(key, default))

    def _observe_node_latency(self, node: str, ms: float) -> None:
        from ..serving.qos import Ewma
        lat = self._node_lat.get(node)
        if lat is None:
            lat = self._node_lat[node] = Ewma()
        lat.observe(ms)

    def _cross_host(self, node: str) -> bool:
        """True when `node` sits on a different (known) simulated host —
        the hop rides DCN, not ICI (transport `set_host` topology)."""
        host_of = getattr(self.transport.network, "host_of", None)
        if host_of is None:
            return False
        mine, theirs = host_of(self.node_id), host_of(node)
        return mine is not None and theirs is not None and mine != theirs

    def _observe_host_hop(self, node: str, ms: float) -> None:
        """Latency of one A_QUERY_HOST pre-reduced hop. Cross-host hops
        observe into the per-transport-class "dcn" EWMA — NEVER into
        `_node_lat`, whose per-node EWMAs arm the intra-host hedge
        deadline (a slow DCN link must not poison the ICI deadline).
        Co-hosted hops observe "reg"."""
        from ..serving.qos import observe_transport_latency
        if self._cross_host(node):
            self.host_reduce_stats["dcn_hops"] += 1
            from .host_reduce import note_dcn_hop
            note_dcn_hop()      # process-wide mirror for the sampler ring
            observe_transport_latency("dcn", ms)
        else:
            observe_transport_latency("reg", ms)

    def _query_with_hedge(self, state, name: str, sid: int, node: str,
                          payload: dict):
        """A_QUERY with an adaptive hedge (SURVEY §2.10.2's load-balanced
        reads, upgraded to hedging): when the chosen copy's response
        exceeds its p99-of-EWMA deadline (`cluster.search.hedge.*`), the
        SAME query fires at another STARTED copy and the first success
        wins; the loser's late answer is observed, discarded and counted
        as canceled. Error semantics are unchanged — with no success the
        primary's error raises exactly as the unhedged call would.
        Returns (result, serving_node)."""
        from ..serving.qos import record_hedge
        enabled = self._hedge_setting("cluster.search.hedge.enable", True)
        if isinstance(enabled, str):
            enabled = enabled.strip().lower() not in ("false", "0", "no",
                                                      "off")
        backups = [c["node"] for c in state.started_copies(name, sid)
                   if c["node"] != node]
        # hedge-over-moving-copy (ISSUE 15): a copy that is the source or
        # the recovery feed of an in-flight relocation is ALSO streaming
        # recovery chunks — arm the hedge even on a cold EWMA and tighten
        # the deadline by cluster.search.hedge.moving_factor so the SLO
        # holds while the move completes
        copies = state.routing.get(name, [[]] * (sid + 1))[sid] \
            if name in state.routing else []
        moving = any(
            (c["node"] == node and c["state"] == RELOCATING)
            or (c.get("relocation") and c.get("recover_from") == node)
            for c in copies)
        lat = self._node_lat.get(node)
        cold = lat is None or lat.n == 0
        if not enabled or not backups or (cold and not moving):
            # cold copy / nothing to hedge onto: the plain synchronous
            # call (and its latency seeds the EWMA for next time)
            t1 = time.perf_counter()
            r = self._shard_call(node, A_QUERY, payload)
            self._observe_node_latency(
                node, (time.perf_counter() - t1) * 1000)
            return r, node

        def _f(key, default):
            try:
                return float(self._hedge_setting(key, default))
            except (TypeError, ValueError):
                return default
        min_ms = _f("cluster.search.hedge.min_ms", 50.0)
        max_ms = _f("cluster.search.hedge.max_ms", 5000.0)
        k = _f("cluster.search.hedge.deviations", 3.0)
        base_ms = min_ms if cold else lat.deadline_ms(k)
        deadline_s = min(max(base_ms, min_ms), max_ms) / 1000.0
        if moving:
            factor = _f("cluster.search.hedge.moving_factor", 0.5)
            deadline_s *= max(min(factor, 1.0), 0.01)

        import contextvars
        cond = threading.Condition()
        results: list[tuple] = []
        winner: list[str] = []

        def call(target: str) -> None:
            t1 = time.perf_counter()
            try:
                r = self._shard_call(target, A_QUERY, payload)
                self._observe_node_latency(
                    target, (time.perf_counter() - t1) * 1000)
                out = ("ok", r, target)
            except (ConnectTransportException,
                    RemoteTransportException) as e:
                out = ("err", e, target)
            with cond:
                results.append(out)
                if out[0] == "ok" and winner and winner[0] != target:
                    # the race's loser finally answered: canceled —
                    # observed, discarded, counted
                    record_hedge("canceled")
                    self.hedge_stats["canceled"] += 1
                cond.notify_all()

        def _success():
            return next((r for r in results if r[0] == "ok"), None)

        launched = 1
        ctx = contextvars.copy_context()
        threading.Thread(target=ctx.run, args=(call, node),
                         daemon=True).start()
        with cond:
            cond.wait_for(lambda: results, timeout=deadline_s)
            lapsed = not results
        if lapsed:
            # deadline blown: fire the backup; the span sits under the
            # coordinator's query span in GET /_traces
            backup = backups[0]
            record_hedge("fired")
            self.hedge_stats["fired"] += 1
            if moving:
                record_hedge("moving")
                self.hedge_stats["moving"] += 1
            launched = 2
            with tracing.span("hedge", index=name, shard=sid,
                              primary=node, backup=backup):
                ctx2 = contextvars.copy_context()
                threading.Thread(target=ctx2.run, args=(call, backup),
                                 daemon=True).start()
                with cond:
                    cond.wait_for(lambda: _success() is not None
                                  or len(results) >= launched)
        with cond:
            got = _success()
            if got is None and len(results) < launched:
                # primary errored inside the deadline; the backup (if
                # any) may still answer — wait it out
                cond.wait_for(lambda: _success() is not None
                              or len(results) >= launched)
                got = _success()
            if got is not None:
                winner.append(got[2])
        if got is not None:
            if launched == 2:
                outcome = "win_primary" if got[2] == node else "win_backup"
                record_hedge(outcome)
                self.hedge_stats[outcome] += 1
            return got[1], got[2]
        if launched == 2:
            record_hedge("failed")
            self.hedge_stats["failed"] += 1
        raise next(r[1] for r in results if r[2] == node)

    def _dfs_stats(self, targets, query, names) -> dict | None:
        """All-reduce term statistics across shards (ref DfsPhase.java:57-81)
        so BM25 IDF is corpus-global. Returns a wire dict or None when the
        query holds no terms."""
        from ..search.query_parser import QueryParser
        terms: dict[str, set] = {}
        for name in names:
            mappers = self._mappers.get(name)
            if mappers is None:
                continue
            try:
                QueryParser(mappers).parse(query).collect_terms(terms)
            except Exception:  # noqa: BLE001 — shard-side parse will report
                return None
        if not any(terms.values()):
            return None       # term-less query: nothing to all-reduce
        terms_wire = {f: sorted(ts) for f, ts in terms.items()}
        dfs = {"doc_count": 0, "sum_dl": {}, "dfs": {}}
        for node, name, sid in targets:
            try:
                r = self._shard_call(node, A_TERM_STATS, {
                    "index": name, "shard": sid, "terms": terms_wire})
            except (ConnectTransportException, RemoteTransportException):
                continue       # the query round will account the failure
            dfs["doc_count"] += r["doc_count"]
            for f, v in r["sum_dl"].items():
                dfs["sum_dl"][f] = dfs["sum_dl"].get(f, 0.0) + v
            for f, t, df in r["dfs"]:
                key = f + "\x00" + t
                dfs["dfs"][key] = dfs["dfs"].get(key, 0) + df
        return {"doc_count": dfs["doc_count"], "sum_dl": dfs["sum_dl"],
                "dfs": [[*k.split("\x00", 1), v]
                        for k, v in dfs["dfs"].items()],
                "terms": terms_wire}

    def _on_term_stats(self, from_id: str, req: dict) -> dict:
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None or holder.engine is None:
            raise UnavailableShardsException(
                f"[{req['index']}][{req['shard']}]")
        from ..search.query_dsl import CollectionStats
        searcher = self._searcher(req["index"], req["shard"], holder)
        tbf = {f: set(ts) for f, ts in (req.get("terms") or {}).items()}
        stats = CollectionStats.from_segments(searcher.segments, tbf)
        return {"doc_count": stats.doc_count,
                "sum_dl": stats.field_sum_dl,
                "dfs": [[f, t, df]
                        for (f, t), df in stats.doc_freqs.items()]}

    def _task_header(self, task) -> dict:
        """Wire header linking a shard-level message to its coordinator
        task (crosses the JSON transport as plain strings)."""
        return {"parent": task.id, "trace": task.trace_id,
                "opaque": task.opaque_id}

    @staticmethod
    def _trace_header() -> dict | None:
        """The `_trace` wire header (next to `_task`): the active span's
        (trace id, span id), so the copy-holder's shard subtree parents
        under the coordinator's span. None when nothing is traced."""
        from ..common import tracing
        return tracing.wire_header()

    def search(self, index: str, body: dict | None = None,
               preference: str | None = None,
               scroll: str | None = None) -> dict:
        with self.tasks.scope("indices:data/read/search",
                              description=f"indices[{index}]") as task:
            return self._search(index, body, preference, scroll, task)

    def _search(self, index: str, body: dict | None,
                preference: str | None, scroll: str | None, task) -> dict:
        t0 = time.perf_counter()
        body = body or {}
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        state = self.cluster.current()
        names = state.resolve_index(index)
        if not names:
            raise KeyError(f"no such index [{index}]")
        targets = self.search_shards(state, names, preference)
        if scroll is not None:
            return self._scroll_start(targets, body, size, scroll, t0)

        query = body.get("query") or {"match_all": {}}
        if body.get("knn") is not None and body.get("sort") is not None:
            raise ValueError("knn search cannot be combined with sort")
        if body.get("rank") is not None:
            # hybrid fusion is a single-node coordinator feature so far;
            # silently serving the knn list alone would misrepresent it
            raise ValueError(
                "rank fusion is not supported on the cluster search path")
        dfs = self._dfs_stats(targets, query, names) \
            if body.get("knn") is None else None
        agg_specs = None
        if body.get("aggs") or body.get("aggregations"):
            from ..search.aggs.aggregators import parse_aggs
            agg_specs = parse_aggs(body.get("aggs")
                                   or body.get("aggregations"))

        # phase 1: query fan-out, partial-failure accounting (a failed
        # shard reduces coverage, never aborts the search — ref
        # TransportSearchTypeAction onFirstPhaseResult failure path).
        #
        # Node-local mesh reduce (ISSUE 11): shards co-hosted on one node
        # group into ONE A_QUERY_HOST message — the data node runs all of
        # them as one shard_map program (one device fetch per host) and
        # returns pre-reduced per-shard wire results, bitwise-identical
        # to the per-shard fan-out. Declines/errors fall back to the
        # hedged per-shard path below.
        per_shard: list[tuple[int, dict]] = []
        failures: list[dict] = []
        host_served: set[int] = set()
        with tracing.span("query", shards=len(targets)):
            from .host_reduce import body_eligible
            if body_eligible(body) and self._host_reduce_enabled():
                groups: dict[tuple[str, str], list[int]] = {}
                for ti, (node, name, sid) in enumerate(targets):
                    groups.setdefault((node, name), []).append(ti)
                host_groups = [(node, name, tis)
                               for (node, name), tis in groups.items()
                               if len(tis) >= 2]

                def _call_host(node, name, tis, results):
                    sids = [targets[ti][2] for ti in tis]
                    payload = {"index": name, "shards": sids,
                               "body": body, "size": size + from_,
                               "dfs": dfs,
                               "_task": self._task_header(task),
                               "_trace": self._trace_header()}
                    try:
                        with tracing.span("mesh_host_reduce", index=name,
                                          node=node, shards=len(sids)):
                            t1 = time.perf_counter()
                            results[(node, name)] = self._shard_call(
                                node, A_QUERY_HOST, payload)
                            self._observe_host_hop(
                                node, (time.perf_counter() - t1) * 1000.0)
                    except (ConnectTransportException,
                            RemoteTransportException):
                        results[(node, name)] = None
                if host_groups:
                    # per-HOST calls fan out concurrently (the reference's
                    # async shard fan-out, one message per host): the
                    # hosts' mesh programs overlap instead of serializing
                    import contextvars
                    results: dict = {}
                    threads = []
                    for node, name, tis in host_groups[1:]:
                        ctx = contextvars.copy_context()
                        t = threading.Thread(
                            target=ctx.run, args=(_call_host, node, name,
                                                  tis, results),
                            daemon=True)
                        t.start()
                        threads.append(t)
                    _call_host(*host_groups[0][:3], results)
                    for t in threads:
                        t.join()
                    for node, name, tis in host_groups:
                        r = results.get((node, name))
                        if r is None:
                            self.host_reduce_stats["errors"] += 1
                            continue     # per-shard fallback below
                        if r.get("declined") is not None:
                            continue     # the data node counted its reason
                        self.host_reduce_stats["merges"] += 1
                        if self._cross_host(node):
                            # pod tier: a pre-reduced result crossed the
                            # host boundary — ONE DCN hop carried the
                            # whole host's shards, and the merge below
                            # is the same bitwise host merge
                            self.host_reduce_stats["pod_dispatches"] += 1
                            from .host_reduce import note_pod_dispatch
                            note_pod_dispatch()
                        for ti in tis:
                            per_shard.append((ti, r["shards"][str(
                                targets[ti][2])]))
                            host_served.add(ti)
            for ti, (node, name, sid) in enumerate(targets):
                if ti in host_served:
                    continue
                payload = {"index": name, "shard": sid, "body": body,
                           "size": size + from_, "dfs": dfs,
                           "_task": self._task_header(task),
                           "_trace": self._trace_header()}
                try:
                    r, _served = self._query_with_hedge(
                        state, name, sid, node, payload)
                    per_shard.append((ti, r))
                except (ConnectTransportException,
                        RemoteTransportException) as e:
                    failures.append({"shard": sid, "index": name,
                                     "node": node, "reason": str(e)})
        # agg/suggest partials must merge in target order regardless of
        # which lane served each shard (float merges are order-sensitive)
        per_shard.sort(key=lambda e: e[0])
        if not per_shard and targets:
            raise UnavailableShardsException(
                f"all shards failed for [{index}]: {failures}")

        reduced = self._reduce(per_shard, targets, body, names,
                               from_, size)
        hits = self._fetch_phase(reduced, targets, body, task)
        resp = self._render_response(reduced, hits, targets, failures,
                                     agg_specs, per_shard, body, t0)
        return resp

    def _parse_sort_specs(self, body: dict, names: list[str]):
        from ..search.sort import parse_sort
        mappers = [self._mappers[n] for n in names if n in self._mappers]
        return parse_sort(body.get("sort"), mappers)

    def _reduce(self, per_shard, targets, body, names, from_, size):
        """Cross-shard sort-merge on wire results
        (ref SearchPhaseController.sortDocs:147,233)."""
        from ..search import sort as sort_mod
        sort = self._parse_sort_specs(body, names)
        entries = []
        total = 0
        max_score = None
        for ti, r in per_shard:
            total += r["total"]
            if r["max_score"] is not None:
                ms = float(r["max_score"])
                if max_score is None or ms > max_score:
                    max_score = ms
            for pos, doc_id in enumerate(r["ids"]):
                score = r["scores"][pos]
                sv = r["sort"][pos] if r.get("sort") is not None else None
                if sort is None:
                    primary = -score if score is not None else float("inf")
                else:
                    primary = sort_mod.compare_key(sv, sort)
                entries.append((primary, ti, pos, doc_id, score, sv))
        entries.sort(key=lambda e: (e[0], e[1], e[2]))
        window = entries[from_: from_ + size]
        return {"window": window, "total": total, "max_score": max_score,
                "sorted": sort is not None}

    def _fetch_phase(self, reduced, targets, body, task=None) -> dict:
        """Fetch fan-out to winning shards only; highlight runs ON the data
        node inside fetch (ref FetchPhase sub-phases)."""
        by_target: dict[int, list[str]] = {}
        for _, ti, _pos, doc_id, _score, _sv in reduced["window"]:
            by_target.setdefault(ti, []).append(doc_id)
        fetched: dict[tuple[int, str], dict] = {}
        for ti, ids in by_target.items():
            node, name, sid = targets[ti]
            payload = {"index": name, "shard": sid, "ids": ids,
                       "_source": body.get("_source", True),
                       "highlight": body.get("highlight"),
                       "query": body.get("query")}
            if task is not None:
                payload["_task"] = self._task_header(task)
                payload["_trace"] = self._trace_header()
            try:
                fr = self._shard_call(node, A_FETCH, payload)
            except (ConnectTransportException, RemoteTransportException):
                continue    # hit rendered without source (copy just died)
            for doc_id, hit in zip(ids, fr["hits"]):
                fetched[(ti, doc_id)] = hit
        return fetched

    def _render_response(self, reduced, fetched, targets, failures,
                         agg_specs, per_shard, body, t0) -> dict:
        hits = []
        for _, ti, _pos, doc_id, score, sv in reduced["window"]:
            h = fetched.get((ti, doc_id), {})
            entry = {"_index": targets[ti][1],
                     "_type": h.get("_type", "_doc"),
                     "_id": doc_id, "_score": score}
            if h.get("_source") is not None:
                entry["_source"] = h["_source"]
            if reduced["sorted"]:
                entry["sort"] = sv
            if h.get("highlight"):
                entry["highlight"] = h["highlight"]
            hits.append(entry)
        resp = {"took": int((time.perf_counter() - t0) * 1000),
                "timed_out": False,
                "_shards": {"total": len(targets),
                            "successful": len(per_shard),
                            "failed": len(failures),
                            **({"failures": failures} if failures else {})},
                "hits": {"total": reduced["total"],
                         "max_score": reduced["max_score"],
                         "hits": hits}}
        if agg_specs is not None:
            from ..search.aggs.aggregators import (merge_shard_partials,
                                                   render)
            from ..search.aggs.wire import partials_from_wire
            parts = [partials_from_wire(agg_specs, r["aggs"])
                     for _, r in per_shard if r.get("aggs") is not None]
            resp["aggregations"] = render(
                agg_specs, merge_shard_partials(agg_specs, parts))
        sugg = [r["suggest"] for _, r in per_shard
                if r.get("suggest") is not None]
        if sugg:
            from ..search.suggest import merge_suggest
            resp["suggest"] = merge_suggest(body.get("suggest") or {}, sugg)
        return resp

    def msearch(self, items: list[tuple[dict, dict]]) -> dict:
        """(header, body) pairs -> {"responses": [...]}, per-item errors
        (ref TransportMultiSearchAction)."""
        responses = []
        for header, sbody in items:
            try:
                responses.append(self.search(
                    header.get("index", "_all"), sbody,
                    preference=header.get("preference")))
            except Exception as e:  # noqa: BLE001 — per-item contract
                responses.append({"error": f"{type(e).__name__}[{e}]"})
        return {"responses": responses}

    def count(self, index: str, body: dict | None = None) -> dict:
        r = self.search(index, {**(body or {}), "size": 0, "from": 0})
        return {"count": r["hits"]["total"], "_shards": r["_shards"]}

    def _searcher(self, index: str, sid: int,
                  holder: _ShardHolder) -> ShardSearcher:
        eng = holder.engine
        key = (tuple(s.seg_id for s in eng.segments),
               tuple(s.live_gen for s in eng.segments))
        if holder.searcher is None or holder.searcher[0] != key:
            holder.drop_searcher()
            # per-index search-lane settings ride the cluster state
            # (prefixed key wins, the update-settings convention) so the
            # blockwise opt-out/block width behave like the local node's
            meta = self.cluster.current().indices.get(index) or {}
            settings = meta.get("settings") or {}

            def get_s(k, default):
                return settings.get(f"index.{k}", settings.get(k, default))
            blockwise = str(get_s("search.blockwise.enable", True)) \
                .strip().lower() not in ("false", "0", "no")
            try:
                block_docs = int(get_s("search.block_docs", 0)) or None
            except (TypeError, ValueError):
                block_docs = None
            # kNN/ANN settings ride the cluster state the same way, so
            # cluster shard copies serve the same lane as a local node
            from ..index.index_service import knn_options_from
            holder.searcher = (key, ShardSearcher(
                sid, eng.segments, self._mappers[index],
                blockwise=blockwise, block_docs=block_docs,
                knn_opts=knn_options_from(get_s)),
                eng.acquire_searcher(
                    site=f"cluster[{index}][{sid}]/_searcher"))
        return holder.searcher[1]

    @contextlib.contextmanager
    def _shard_task_scope(self, action: str, req: dict):
        """Register the shard-level action under the coordinator task the
        message carries (remote copy-holders show the coordinator as
        parent — TaskId-over-the-wire semantics). When the message also
        carries a `_trace` header, the shard phase records a local span
        subtree continuing the coordinator's trace."""
        hdr = req.get("_task") or {}
        desc = f"shard [{req['index']}][{req['shard']}]"
        with self.tasks.scope(
                action, description=desc,
                parent_task_id=hdr.get("parent"),
                trace_id=hdr.get("trace"),
                opaque_id=hdr.get("opaque")) as task:
            with self.tracer.remote(req.get("_trace"), action,
                                    attrs={"description": desc,
                                           "node": self.node_id}):
                yield task

    def _on_query(self, from_id: str, req: dict) -> dict:
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None or holder.engine is None:
            raise UnavailableShardsException(
                f"[{req['index']}][{req['shard']}]")
        searcher = self._searcher(req["index"], req["shard"], holder)
        body = req.get("body") or {}
        k = int(req["size"])
        with self._shard_task_scope(
                "indices:data/read/search[phase/query]", req):
            return _shard_query_phase(searcher, self._mappers[req["index"]],
                                      body, k, req.get("dfs"),
                                      search_after=req.get("search_after"))

    _host_reduce_error_logged = 0

    def _host_reduce_enabled(self) -> bool:
        """`cluster.search.host_reduce.enable` (default true) — read live
        from cluster-state settings, like the hedge settings."""
        from .host_reduce import HOST_REDUCE_SETTING, setting_enabled
        st = self.cluster.current().data.get("settings") or {}
        return setting_enabled(st.get(HOST_REDUCE_SETTING, True))

    def _on_query_host(self, from_id: str, req: dict) -> dict:
        """Data-node side of the node-local mesh reduce: run every
        requested co-hosted shard's query phase as ONE shard_map program
        and return pre-reduced per-shard wire results. Declines (wire
        `{"declined": reason}`) send the coordinator down the per-shard
        fan-out — never an error."""
        from . import host_reduce
        if not self._host_reduce_enabled():
            return {"declined": "disabled"}
        index = req["index"]
        sids = [int(s) for s in req["shards"]]
        desc = f"shards [{index}]{sids}"
        with self.tasks.scope(
                "indices:data/read/search[phase/query/host]",
                description=desc,
                parent_task_id=(req.get("_task") or {}).get("parent"),
                trace_id=(req.get("_task") or {}).get("trace"),
                opaque_id=(req.get("_task") or {}).get("opaque")):
            with self.tracer.remote(req.get("_trace"), "mesh_host_reduce",
                                    attrs={"description": desc,
                                           "node": self.node_id}):
                try:
                    out, reason = host_reduce.try_host_reduce(
                        self, index, sids, req.get("body") or {},
                        int(req["size"]), req.get("dfs"))
                except Exception:  # noqa: BLE001 — fan-out is always correct
                    self.host_reduce_stats["errors"] += 1
                    if ClusterNode._host_reduce_error_logged < 10:
                        ClusterNode._host_reduce_error_logged += 1
                        import logging
                        logging.getLogger(__name__).warning(
                            "host mesh reduce failed; served via the "
                            "per-shard fan-out instead", exc_info=True)
                    return {"declined": "error"}
        if out is None:
            self.host_reduce_stats["declined"] += 1
            return {"declined": reason}
        self.host_reduce_stats["dispatches"] += 1
        return out

    def _on_fetch(self, from_id: str, req: dict) -> dict:
        holder = self._shards.get((req["index"], req["shard"]))
        if holder is None or holder.engine is None:
            raise UnavailableShardsException(f"[{req['index']}]")
        with self._shard_task_scope(
                "indices:data/read/search[phase/fetch/id]", req):
            return _shard_fetch_phase(holder.engine,
                                      self._mappers[req["index"]], req)

    # -- distributed scroll (ref scroll_id encoding per-shard context ids,
    #    action/search/type/TransportSearchHelper + SearchService
    #    keep-alive contexts; cursors advance per shard by the LAST
    #    GLOBALLY-EMITTED doc, the lastEmittedDocPerShard contract of
    #    SearchPhaseController.sortDocs) --------------------------------

    def _scroll_start(self, targets, body, size, keep_alive, t0) -> dict:
        if any(k in body for k in ("knn", "rescore", "search_after")):
            raise ValueError("scroll does not support "
                             "knn/rescore/search_after")
        ctxs = []
        ok_targets = []
        for node, name, sid in targets:
            try:
                r = self._shard_call(node, A_SCROLL_NEXT, {
                    "index": name, "shard": sid,
                    "init": {"body": body, "keep_alive": keep_alive}})
            except (ConnectTransportException,
                    RemoteTransportException):
                continue    # partial scroll, like the query phase
            ctxs.append(r["ctx"])
            ok_targets.append((node, name, sid))
        if not ok_targets:
            raise UnavailableShardsException(
                "scroll could not pin any shard context")
        targets = ok_targets
        with self._scroll_lock:
            self._scroll_seq += 1
            scroll_id = f"c-scroll-{self.node_id}-{self._scroll_seq}"
            ctx = {"targets": list(targets), "ctxs": ctxs,
                   "cursors": [None] * len(targets), "size": size,
                   "keep_alive": keep_alive,
                   "expiry": time.monotonic() + _keepalive_secs(keep_alive),
                   "lock": threading.Lock()}
            self._scroll_ctx[scroll_id] = ctx
        out = self._scroll_batch(ctx, t0)
        out["_scroll_id"] = scroll_id
        return out

    def scroll(self, scroll_id: str, keep_alive: str | None = None) -> dict:
        t0 = time.perf_counter()
        with self._scroll_lock:
            ctx = self._scroll_ctx.get(scroll_id)
            if ctx is None or ctx["expiry"] < time.monotonic():
                self._scroll_ctx.pop(scroll_id, None)
                ctx = None
        if ctx is None:
            raise SearchContextMissingException(
                f"No search context found for id [{scroll_id}]")
        if keep_alive:
            ctx["keep_alive"] = keep_alive
        ctx["expiry"] = time.monotonic() + _keepalive_secs(ctx["keep_alive"])
        out = self._scroll_batch(ctx, t0)
        out["_scroll_id"] = scroll_id
        return out

    def clear_scroll(self, scroll_id: str) -> bool:
        ctx = self._scroll_ctx.pop(scroll_id, None)
        if ctx is None:
            return False
        for (node, name, sid), cid in zip(ctx["targets"], ctx["ctxs"]):
            try:
                self._shard_call(node, A_SCROLL_CLEAR, {"ctx": cid})
            except (ConnectTransportException, RemoteTransportException):
                pass
        return True

    def _scroll_batch(self, ctx, t0) -> dict:
        with ctx["lock"]:
            return self._scroll_batch_locked(ctx, t0)

    def _scroll_batch_locked(self, ctx, t0) -> dict:
        from ..search import sort as sort_mod
        size = ctx["size"]
        per_shard = []
        failures = []
        for ti, ((node, name, sid), cid) in enumerate(
                zip(ctx["targets"], ctx["ctxs"])):
            try:
                r = self._shard_call(node, A_SCROLL_NEXT, {
                    "index": name, "shard": sid, "ctx": cid, "size": size,
                    "after": ctx["cursors"][ti],
                    "keep_alive": ctx["keep_alive"]})
                per_shard.append((ti, r))
            except (ConnectTransportException,
                    RemoteTransportException) as e:
                failures.append({"shard": sid, "index": name,
                                 "reason": str(e)})
        entries = []
        total = 0
        max_score = None
        specs = None
        for ti, r in per_shard:
            total += r["total"]
            if r["max_score"] is not None:
                ms = float(r["max_score"])
                max_score = ms if max_score is None else max(max_score, ms)
            if specs is None and r.get("specs") is not None:
                specs = [sort_mod.SortSpec(**sp) for sp in r["specs"]]
            for h in r["hits"]:
                entries.append((sort_mod.compare_key(h["sort"], specs),
                                ti, h))
        entries.sort(key=lambda e: (e[0], e[1]))
        window = entries[:size]
        # advance each shard's cursor to its LAST EMITTED doc
        for _, ti, h in window:
            ctx["cursors"][ti] = h["sort"]
        hits = []
        for _, ti, h in window:
            entry = {"_index": ctx["targets"][ti][1],
                     "_type": h.get("_type", "_doc"), "_id": h["_id"],
                     "_score": h.get("score")}
            if h.get("_source") is not None:
                entry["_source"] = h["_source"]
            if not h.get("implicit_sort"):
                entry["sort"] = h["sort"]
            hits.append(entry)
        return {"took": int((time.perf_counter() - t0) * 1000),
                "timed_out": False,
                "_shards": {"total": len(ctx["targets"]),
                            "successful": len(per_shard),
                            "failed": len(failures)},
                "hits": {"total": total, "max_score": max_score,
                         "hits": hits}}

    def _on_scroll_next(self, from_id: str, req: dict) -> dict:
        self._reap_scroll_ctx()
        if "init" in req:
            holder = self._shards.get((req["index"], req["shard"]))
            if holder is None or holder.engine is None:
                raise UnavailableShardsException(
                    f"[{req['index']}][{req['shard']}]")
            searcher = self._searcher(req["index"], req["shard"], holder)
            init = req["init"]
            with self._scroll_lock:
                self._scroll_seq += 1
                cid = f"ctx-{self.node_id}-{self._scroll_seq}"
                self._scroll_ctx[cid] = _make_shard_scroll_ctx(
                    searcher, self._mappers[req["index"]], init["body"],
                    _keepalive_secs(init["keep_alive"]))
            return {"ctx": cid}
        ctx = self._scroll_ctx.get(req["ctx"])
        if ctx is None:
            raise UnavailableShardsException(
                f"scroll context [{req['ctx']}] expired")
        ctx["expiry"] = time.monotonic() \
            + _keepalive_secs(req.get("keep_alive", "1m"))
        return _shard_scroll_batch(ctx, int(req["size"]), req.get("after"))

    def _on_scroll_clear(self, from_id: str, req: dict) -> dict:
        return {"found": self._scroll_ctx.pop(req["ctx"], None) is not None}

    def _reap_scroll_ctx(self) -> None:
        now = time.monotonic()
        with self._scroll_lock:
            for cid in [c for c, ctx in self._scroll_ctx.items()
                        if ctx.get("expiry", now) < now]:
                del self._scroll_ctx[cid]

    # ------------------------------------------------------------------
    # broadcast admin (ref TransportBroadcastOperationAction)
    # ------------------------------------------------------------------

    def refresh(self, index: str = "_all") -> None:
        self._broadcast(A_REFRESH, index)

    def flush(self, index: str = "_all") -> None:
        self._broadcast(A_FLUSH, index)

    def _broadcast(self, action: str, index: str) -> None:
        state = self.cluster.current()
        nodes = {c["node"] for name in state.resolve_index(index)
                 for copies in state.routing[name] for c in copies
                 if c["node"] is not None and c["state"] != UNASSIGNED}
        for node_id in sorted(nodes):
            try:
                if node_id == self.node_id:
                    self.transport._handle(self.node_id, action,
                                           {"index": index})
                else:
                    self.transport.send(node_id, action, {"index": index})
            except (ConnectTransportException, RemoteTransportException):
                continue

    def _on_refresh(self, from_id: str, req: dict) -> dict:
        names = self.cluster.current().resolve_index(req.get("index", "_all"))
        for (index, sid), holder in list(self._shards.items()):
            if index in names and holder.engine is not None:
                holder.engine.refresh()
        return {"ok": True}

    def _on_flush(self, from_id: str, req: dict) -> dict:
        names = self.cluster.current().resolve_index(req.get("index", "_all"))
        for (index, sid), holder in list(self._shards.items()):
            if index in names and holder.engine is not None:
                holder.engine.flush()
        return {"ok": True}

    # ------------------------------------------------------------------

    def health(self) -> dict:
        state = self.cluster.current()
        return {"cluster_name": state.data["cluster_name"],
                "master_node": state.master_node,
                "version": state.version, **state.health()}

    def close(self) -> None:
        """Simulates process death when called abruptly (harness.kill)."""
        self.closed = True
        self.transport.close()
        self.cluster.close()
        with self._shards_lock:
            for holder in self._shards.values():
                if holder.engine is not None:
                    holder.drop_searcher()
                    holder.engine.close()


# ---------------------------------------------------------------------------
# Data-node search phases (shared by RPC handlers; ref SearchService
# executeQueryPhase/executeFetchPhase — the shard side of the 2-phase
# protocol, returning WIRE-SAFE results)
# ---------------------------------------------------------------------------

def _crc_prefix(path: str, size: int, chunk: int) -> int:
    """crc32 over the first `size` bytes (recovery file identity — files
    are write-once/append-only after flush, so the prefix is stable)."""
    import zlib
    crc = 0
    remaining = size
    with open(path, "rb") as f:
        while remaining > 0:
            b = f.read(min(chunk, remaining))
            if not b:
                break
            crc = zlib.crc32(b, crc)
            remaining -= len(b)
    return crc


def _keepalive_secs(s: str) -> float:
    from ..node import _duration_secs     # one duration grammar everywhere
    return _duration_secs(s)


def _jsonval(v):
    """Materialized sort values / scores -> JSON-safe."""
    import numpy as np
    if isinstance(v, (list, tuple)):
        return [_jsonval(x) for x in v]
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        f = float(v)
        return None if f != f else f
    if isinstance(v, float) and v != v:
        return None
    if isinstance(v, (np.str_, np.bool_)):
        return v.item()
    return v


def _stats_from_wire(dfs: dict | None):
    if dfs is None:
        return None
    from ..search.query_dsl import CollectionStats
    return CollectionStats(
        doc_count=dfs["doc_count"],
        field_sum_dl=dict(dfs["sum_dl"]),
        doc_freqs={(f, t): df for f, t, df in dfs["dfs"]})


def _shard_query_phase(searcher: ShardSearcher, mappers: MapperService,
                       body: dict, k: int, dfs: dict | None,
                       search_after=None) -> dict:
    """Execute the FULL query phase for one shard and wire-encode the
    result (keys + scores + materialized sort values + agg/suggest
    partials). The coordinator windows [from, from+size) after the merge,
    so `k` = from + size here."""
    from ..search.aggs.aggregators import parse_aggs
    from ..search.sort import parse_sort

    stats = _stats_from_wire(dfs)
    sort = parse_sort(body.get("sort"), [mappers])
    if search_after is None:
        search_after = body.get("search_after") or None
    if search_after is not None and sort is None:
        raise ValueError("search_after requires a sort")
    agg_specs = parse_aggs(body.get("aggs") or body.get("aggregations")) \
        if (body.get("aggs") or body.get("aggregations")) else None
    rescore_spec = body.get("rescore")
    if isinstance(rescore_spec, list):
        rescore_spec = rescore_spec[0] if rescore_spec else None
    if rescore_spec is not None and sort is not None:
        raise ValueError("rescore cannot be used with a sort")
    window = int(rescore_spec.get("window_size", k)) if rescore_spec else 0
    knn = body.get("knn")

    if knn is not None:
        fnode = searcher.parse([knn["filter"]]) if knn.get("filter") else None
        raw_np = knn.get("nprobe")
        r = searcher.execute_knn(
            knn["field"], [knn["query_vector"]],
            k=int(knn.get("k", k)), metric=knn.get("metric", "cosine"),
            filter_node=fnode,
            nprobe=int(raw_np) if raw_np is not None else None,
            exact=bool(knn.get("exact", False)),
            quantization=knn.get("quantization"))
    else:
        node = searcher.parse([body.get("query") or {"match_all": {}}])
        r = searcher.execute_query_phase(
            node, size=max(k, window), from_=0, sort=sort,
            global_stats=stats, aggs=agg_specs,
            search_after=search_after,
            track_scores=bool(body.get("track_scores", False))
            if sort is not None else True)
        if rescore_spec is not None:
            r = searcher.rescore(r, rescore_spec)

    from ..search.shard_searcher import LOCAL_MASK, SEG_SHIFT
    ids, scores, svs = [], [], []
    for pos in range(r.doc_keys.shape[1]):
        key = int(r.doc_keys[0, pos])
        if key < 0:
            continue
        seg = searcher.segments[key >> SEG_SHIFT]
        # doc IDS cross the seam, not positional keys: the fetch phase may
        # race a flush/merge that reshuffles (segment, local) addresses —
        # ids stay stable (the reference's fetch uses context-pinned
        # readers; id addressing is the equivalent safety here)
        ids.append(seg.ids[key & LOCAL_MASK])
        sc = float(r.scores[0, pos])
        scores.append(None if sc != sc else sc)
        if r.sort_values is not None:
            svs.append(_jsonval(r.sort_values[0, pos]))
    mx = float(r.max_score[0])
    out: dict = {"ids": ids, "scores": scores,
                 "sort": svs if r.sort_values is not None else None,
                 "total": int(r.total_hits[0]),
                 "max_score": None if mx != mx else mx}
    if agg_specs is not None and r.aggs is not None:
        from ..search.aggs.wire import partials_to_wire
        out["aggs"] = partials_to_wire(agg_specs, r.aggs)
    if body.get("suggest"):
        from ..search.suggest import run_suggest
        out["suggest"] = run_suggest(body["suggest"], searcher.segments)
    return out


def _shard_fetch_phase(engine: Engine, mappers: MapperService,
                       req: dict) -> dict:
    """Resolve doc IDS to rendered hits; _source filtering and HIGHLIGHT
    run here, on the data node (ref FetchPhase.java sub-phases). Fetch is
    by id, not positional key, so a flush/merge racing between the query
    and fetch phases can never serve the wrong document."""
    from ..search.query_parser import QueryParser
    from ..search.shard_searcher import _filter_source

    hl_spec = None
    terms_by_field: dict[str, set] = {}
    if req.get("highlight"):
        from ..search.highlight import parse_highlight
        hl_spec = parse_highlight(req["highlight"])
        if req.get("query"):
            try:
                QueryParser(mappers).parse(req["query"]) \
                    .collect_terms(terms_by_field)
            except Exception:  # noqa: BLE001 — highlight degrades to none
                pass

    def an_for(fname):
        for dm in mappers._mappers.values():
            if fname in dm.fields:
                return dm.search_analyzer_for(fname)
        return mappers.analysis.analyzer("standard")

    src_spec = req.get("_source", True)
    hits = []
    for doc_id in req["ids"]:
        r = engine.get(doc_id, realtime=False)
        if not r.found:
            hits.append({"_id": doc_id, "_type": "_doc", "_source": None})
            continue
        raw_src = r.source
        src = None if src_spec is False \
            else _filter_source(raw_src, src_spec if src_spec is not True
                                else None)
        hit = {"_id": doc_id, "_type": r.type_name, "_source": src}
        if hl_spec is not None:
            from ..search.highlight import highlight_hit
            hl = highlight_hit(hl_spec, raw_src, terms_by_field, an_for)
            if hl:
                hit["highlight"] = hl
        hits.append(hit)
    return {"hits": hits}


def _make_shard_scroll_ctx(searcher: ShardSearcher, mappers: MapperService,
                           body: dict, keep_secs: float) -> dict:
    """Pin a point-in-time snapshot of the shard for scrolling: copy the
    segment list with frozen liveness (concurrent deletes/merges never
    change what the scroll sees — ref ScanContext reader pinning)."""
    import dataclasses as _dc

    from ..search.sort import DOC, SCORE, SortSpec, parse_sort

    segs = [_dc.replace(s, live_host=s.live_host.copy(),
                        live_count=s.live_count)
            for s in searcher.segments]
    pinned = ShardSearcher(searcher.shard_id, segs, mappers)
    user_sort = parse_sort(body.get("sort"), [mappers])
    implicit = user_sort is None
    specs = list(user_sort) if user_sort else \
        [SortSpec(field=SCORE, order="desc")]
    if not any(sp.field == DOC for sp in specs):
        specs = specs + [SortSpec(field=DOC, order="asc")]
    return {"searcher": pinned, "body": body, "specs": specs,
            "implicit": implicit,
            "expiry": time.monotonic() + keep_secs}


def _shard_scroll_batch(ctx: dict, size: int, after) -> dict:
    """One scroll batch from a pinned shard context: the next `size` docs
    after the shard's last GLOBALLY-emitted cursor, with sources inline
    (scroll fetches eagerly — one RPC per shard per batch)."""
    from ..search.shard_searcher import LOCAL_MASK, SEG_SHIFT

    searcher: ShardSearcher = ctx["searcher"]
    body = ctx["body"]
    specs = ctx["specs"]
    node = searcher.parse([body.get("query") or {"match_all": {}}])
    r = searcher.execute_query_phase(
        node, size=size, from_=0, sort=specs, search_after=after,
        track_scores=True)
    hits = []
    for pos in range(r.doc_keys.shape[1]):
        key = int(r.doc_keys[0, pos])
        if key < 0:
            continue
        seg = searcher.segments[key >> SEG_SHIFT]
        local = key & LOCAL_MASK
        sc = float(r.scores[0, pos])
        hits.append({"_id": seg.ids[local], "_type": seg.types[local],
                     "_source": seg.stored[local],
                     "score": None if sc != sc else sc,
                     "sort": _jsonval(r.sort_values[0, pos]),
                     "implicit_sort": ctx["implicit"]})
    mx = float(r.max_score[0])
    return {"hits": hits, "total": int(r.total_hits[0]),
            "max_score": None if mx != mx else mx,
            "specs": [{"field": sp.field, "order": sp.order,
                       "missing": sp.missing}
                      for sp in specs]}
