"""Transport seam: named-action RPC between nodes.

The analog of the reference's TransportService + LocalTransport
(/root/reference/src/main/java/org/elasticsearch/transport/TransportService.java:60,
252,317 — registerHandler(action, handler) / sendRequest(node, action, req);
transport/local/LocalTransport.java — the in-process transport used by the
test cluster, which still serializes every message so wire bugs surface).

Every message crosses the seam as JSON bytes (bytes payloads wrapped in a
base64 tag) — the AssertingLocalTransport discipline: a payload that cannot
round-trip the wire format fails loudly in-process, exactly where a real
DCN/gRPC transport would fail. Fault injection (disconnect/drop rules) lives
here too, the MockTransportService analog
(src/test/java/org/elasticsearch/test/transport/MockTransportService.java).
"""

from __future__ import annotations

import base64
import json
import threading
from typing import Any, Callable


class TransportException(Exception):
    pass


class ConnectTransportException(TransportException):
    """Target node unreachable (dead, disconnected, or rule-dropped)."""

    def __init__(self, node_id: str, action: str = ""):
        super().__init__(f"cannot connect to node [{node_id}]"
                         + (f" for action [{action}]" if action else ""))
        self.node_id = node_id


class ActionNotFoundTransportException(TransportException):
    pass


class RemoteTransportException(TransportException):
    """Handler on the remote node raised; carries the remote error type so
    callers can branch on it (the reference serializes exceptions the same
    way)."""

    def __init__(self, node_id: str, action: str, error_type: str, message: str):
        super().__init__(f"[{node_id}][{action}] {error_type}: {message}")
        self.node_id = node_id
        self.action = action
        self.error_type = error_type
        self.error_message = message


_BYTES_TAG = "__b64__"
_ESC_TAG = "__esc__"


def _encode(obj: Any) -> Any:
    """Make a payload JSON-safe; bytes become tagged base64 strings. User
    dicts that happen to contain a tag key are escape-wrapped so document
    content can never be mistaken for wire framing."""
    if isinstance(obj, bytes):
        return {_BYTES_TAG: base64.b64encode(obj).decode("ascii")}
    if isinstance(obj, dict):
        enc = {k: _encode(v) for k, v in obj.items()}
        if _BYTES_TAG in obj or _ESC_TAG in obj:
            return {_ESC_TAG: enc}
        return enc
    if isinstance(obj, (list, tuple)):
        return [_encode(v) for v in obj]
    return obj


def _decode(obj: Any) -> Any:
    if isinstance(obj, dict):
        if set(obj) == {_BYTES_TAG}:
            return base64.b64decode(obj[_BYTES_TAG])
        if set(obj) == {_ESC_TAG}:
            return {k: _decode(v) for k, v in obj[_ESC_TAG].items()}
        return {k: _decode(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode(v) for v in obj]
    return obj


def roundtrip(payload: Any) -> Any:
    """Serialize + deserialize — the wire. Raises TypeError on content that
    could never cross a real transport (live objects, arrays, ...)."""
    return _decode(json.loads(json.dumps(_encode(payload))))


class LocalTransport:
    """The shared in-process 'network': a registry of node transports.

    Doubles as the discovery seed list — `connected_nodes()` is what a zen
    ping round would discover (ref discovery/zen/ping/unicast). Thread-safe;
    handlers execute synchronously in the caller's thread (the reference's
    LocalTransport hands off to a thread pool; synchronous execution keeps
    tests deterministic and still exercises the full serialize boundary).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: dict[str, "TransportService"] = {}
        # fault-injection rules: (from_id|None, to_id) pairs that fail —
        # None matches any sender (full isolation of to_id)
        self._disconnected: set[tuple[str | None, str]] = set()
        self.messages_sent = 0
        self.bytes_sent = 0
        self.max_message_bytes = 0   # largest single frame (recovery tests
                                     # assert chunking bounds this)

    def register(self, service: "TransportService") -> None:
        with self._lock:
            self._nodes[service.node_id] = service

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def connected_nodes(self) -> list[str]:
        with self._lock:
            return sorted(self._nodes)

    # -- fault injection (MockTransportService analog) --------------------

    def disconnect(self, node_id: str, from_id: str | None = None) -> None:
        """Make node_id unreachable (from from_id, or from everyone)."""
        with self._lock:
            self._disconnected.add((from_id, node_id))

    def reconnect(self, node_id: str, from_id: str | None = None) -> None:
        with self._lock:
            self._disconnected.discard((from_id, node_id))

    def partition(self, side_a: list[str], side_b: list[str]) -> None:
        """Two-way network partition between node groups
        (ref test/disruption/NetworkPartition)."""
        with self._lock:
            for a in side_a:
                for b in side_b:
                    self._disconnected.add((a, b))
                    self._disconnected.add((b, a))

    def heal(self) -> None:
        with self._lock:
            self._disconnected.clear()

    # -- the wire ----------------------------------------------------------

    def deliver(self, from_id: str, to_id: str, action: str,
                payload: Any) -> Any:
        with self._lock:
            blocked = ((from_id, to_id) in self._disconnected
                       or (None, to_id) in self._disconnected)
            target = self._nodes.get(to_id)
        if blocked or target is None:
            raise ConnectTransportException(to_id, action)
        wire = json.dumps(_encode(payload))
        with self._lock:
            self.messages_sent += 1
            self.bytes_sent += len(wire)
            self.max_message_bytes = max(self.max_message_bytes, len(wire))
        request = _decode(json.loads(wire))
        response = target._handle(from_id, action, request)
        wire_resp = json.dumps(_encode(response))
        with self._lock:
            self.bytes_sent += len(wire_resp)
            self.max_message_bytes = max(self.max_message_bytes,
                                         len(wire_resp))
        return _decode(json.loads(wire_resp))


class TransportService:
    """Per-node RPC hub (ref TransportService.java:60). Actions are named
    strings (e.g. "indices:data/write/index[p]"); local sends short-circuit
    the registry but still round-trip the wire format."""

    def __init__(self, node_id: str, network: LocalTransport):
        self.node_id = node_id
        self.network = network
        self._handlers: dict[str, Callable[[str, Any], Any]] = {}
        network.register(self)

    def register_handler(self, action: str,
                         handler: Callable[[str, Any], Any]) -> None:
        """handler(from_node_id, request) -> response (JSON-safe)."""
        self._handlers[action] = handler

    def send(self, node_id: str, action: str, payload: Any = None) -> Any:
        """Synchronous request/response. Raises ConnectTransportException if
        the target is unreachable, RemoteTransportException if its handler
        raised."""
        return self.network.deliver(self.node_id, node_id, action, payload)

    def _handle(self, from_id: str, action: str, request: Any) -> Any:
        handler = self._handlers.get(action)
        if handler is None:
            raise ActionNotFoundTransportException(
                f"no handler for [{action}] on [{self.node_id}]")
        try:
            return handler(from_id, request)
        except TransportException:
            raise
        except Exception as e:  # noqa: BLE001 — serialize like a real wire
            raise RemoteTransportException(
                self.node_id, action, type(e).__name__, str(e)) from e

    def close(self) -> None:
        self.network.unregister(self.node_id)
