"""Transport seam: named-action RPC between nodes.

The analog of the reference's TransportService + LocalTransport
(/root/reference/src/main/java/org/elasticsearch/transport/TransportService.java:60,
252,317 — registerHandler(action, handler) / sendRequest(node, action, req);
transport/local/LocalTransport.java — the in-process transport used by the
test cluster, which still serializes every message so wire bugs surface).

Every message crosses the seam as JSON bytes (bytes payloads wrapped in a
base64 tag) — the AssertingLocalTransport discipline: a payload that cannot
round-trip the wire format fails loudly in-process, exactly where a real
DCN/gRPC transport would fail. Fault injection (disconnect/drop rules) lives
here too, the MockTransportService analog
(src/test/java/org/elasticsearch/test/transport/MockTransportService.java).
"""

from __future__ import annotations

import base64
import json
import threading
from typing import Any, Callable


class TransportException(Exception):
    pass


class ConnectTransportException(TransportException):
    """Target node unreachable (dead, disconnected, or rule-dropped)."""

    def __init__(self, node_id: str, action: str = ""):
        super().__init__(f"cannot connect to node [{node_id}]"
                         + (f" for action [{action}]" if action else ""))
        self.node_id = node_id


class ActionNotFoundTransportException(TransportException):
    pass


class RemoteTransportException(TransportException):
    """Handler on the remote node raised; carries the remote error type so
    callers can branch on it (the reference serializes exceptions the same
    way)."""

    def __init__(self, node_id: str, action: str, error_type: str, message: str):
        super().__init__(f"[{node_id}][{action}] {error_type}: {message}")
        self.node_id = node_id
        self.action = action
        self.error_type = error_type
        self.error_message = message


# -- transport traffic classes (ISSUE 9) ------------------------------------
# The reference opens FIVE typed connection sets per node pair
# (NettyTransport.java:180-184: recovery=2, bulk=3, reg=6, state=1, ping=1)
# so recovery chunk streaming and bulk replication can never head-of-line-
# block query fan-out or cluster-state publishing. Here each (sender,
# target, class) tuple gets its own connection budget: a send first takes
# a class connection, waits in ITS CLASS's queue when the budget is full,
# and classes are fully isolated from each other. Same-thread nested sends
# re-enter their held connection (the in-process transport runs handlers
# in the caller's thread), and an implausibly-long wait fails OPEN with a
# counter rather than deadlocking the cluster.

TRAFFIC_CLASS_CONNECTIONS = {"recovery": 2, "bulk": 3, "reg": 6,
                             "state": 1, "ping": 1,
                             # sixth class (ISSUE 19): latency-sensitive
                             # traffic CROSSING a host boundary — the
                             # pod data plane's one pre-reduced DCN hop
                             # per host per query. Its own budget +
                             # queue keep slow DCN links from eating the
                             # intra-host "reg" connections, and the QoS
                             # EWMA tier keys off the class so DCN
                             # latency never poisons the ICI hedge
                             # deadline.
                             "dcn": 4}

#: fail-open ceiling for a class-connection wait; a timeout means the
#: class was saturated for this long — counted, never fatal
CLASS_WAIT_TIMEOUT_S = 30.0


def class_of_action(action: str) -> str:
    """Traffic class of a named transport action (mirrors the reference's
    ConnectionProfile mapping onto its five connection types)."""
    if action.startswith("internal:index/shard/recovery"):
        return "recovery"
    if action.startswith("indices:data/write"):
        return "bulk"
    if action == "internal:discovery/zen/fd/ping":
        return "ping"
    if action.startswith(("internal:cluster", "internal:discovery",
                          "internal:gateway", "cluster:",
                          "indices:admin")):
        return "state"
    return "reg"   # search/get/stats — the latency-sensitive default
                   # ("dcn" when the hop crosses hosts — LocalTransport
                   # upgrades per (sender, target) host identity)


_BYTES_TAG = "__b64__"
_ESC_TAG = "__esc__"


def _encode(obj: Any) -> Any:
    """Make a payload JSON-safe; bytes become tagged base64 strings. User
    dicts that happen to contain a tag key are escape-wrapped so document
    content can never be mistaken for wire framing."""
    if isinstance(obj, bytes):
        return {_BYTES_TAG: base64.b64encode(obj).decode("ascii")}
    if isinstance(obj, dict):
        enc = {k: _encode(v) for k, v in obj.items()}
        if _BYTES_TAG in obj or _ESC_TAG in obj:
            return {_ESC_TAG: enc}
        return enc
    if isinstance(obj, (list, tuple)):
        return [_encode(v) for v in obj]
    return obj


def _decode(obj: Any) -> Any:
    if isinstance(obj, dict):
        if set(obj) == {_BYTES_TAG}:
            return base64.b64decode(obj[_BYTES_TAG])
        if set(obj) == {_ESC_TAG}:
            return {k: _decode(v) for k, v in obj[_ESC_TAG].items()}
        return {k: _decode(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode(v) for v in obj]
    return obj


def roundtrip(payload: Any) -> Any:
    """Serialize + deserialize — the wire. Raises TypeError on content that
    could never cross a real transport (live objects, arrays, ...)."""
    return _decode(json.loads(json.dumps(_encode(payload))))


class LocalTransport:
    """The shared in-process 'network': a registry of node transports.

    Doubles as the discovery seed list — `connected_nodes()` is what a zen
    ping round would discover (ref discovery/zen/ping/unicast). Thread-safe;
    handlers execute synchronously in the caller's thread (the reference's
    LocalTransport hands off to a thread pool; synchronous execution keeps
    tests deterministic and still exercises the full serialize boundary).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: dict[str, "TransportService"] = {}
        # fault-injection rules: (from_id|None, to_id) pairs that fail —
        # None matches any sender (full isolation of to_id)
        self._disconnected: set[tuple[str | None, str]] = set()
        # action-prefix-scoped drop rules (ISSUE 14): (from_id|None, to_id,
        # action_prefix) triples that fail — kills a single action class
        # (e.g. only replica bulk) without severing the link, so fault
        # detection pings keep flowing while the targeted traffic dies
        self._drop_rules: set[tuple[str | None, str, str]] = set()
        # latency-injection rules: (to_id, action_prefix) -> seconds of
        # added delivery delay (the slow-replica half of the
        # MockTransportService analog; hedged-read tests use this)
        self._delays: dict[tuple[str, str], float] = {}
        # es_transport_faults_injected_total: every fault this layer
        # actually APPLIED to a delivery (blocked, rule-dropped, delayed)
        self.faults_injected = 0
        self.messages_sent = 0
        self.bytes_sent = 0
        self.max_message_bytes = 0   # largest single frame (recovery tests
                                     # assert chunking bounds this)
        # per-(sender, target, class) connection budgets + per-class queue
        # accounting (ISSUE 9; ref NettyTransport.java:180-184)
        self._class_sems: dict[tuple[str, str, str],
                               threading.Semaphore] = {}
        self._held = threading.local()   # same-thread re-entrancy
        # simulated host identity (ISSUE 19): node_id -> host name. Two
        # nodes on DIFFERENT hosts exchange latency-sensitive traffic on
        # the "dcn" class instead of "reg" (ICI within a host, DCN
        # between — SURVEY §5.8). Unregistered nodes count as co-hosted.
        self._hosts: dict[str, str] = {}
        self._class_stats: dict[str, dict] = {
            c: {"sent_total": 0, "bytes_sent_total": 0, "queue_depth": 0,
                "max_queue_depth": 0, "queue_timeouts_total": 0,
                "connections": TRAFFIC_CLASS_CONNECTIONS[c]}
            for c in TRAFFIC_CLASS_CONNECTIONS}

    def register(self, service: "TransportService") -> None:
        with self._lock:
            self._nodes[service.node_id] = service

    def set_host(self, node_id: str, host: str) -> None:
        """Pin a node to a simulated host (the pods harness's topology
        declaration); cross-host "reg" traffic upgrades to "dcn"."""
        with self._lock:
            self._hosts[node_id] = str(host)

    def host_of(self, node_id: str) -> str | None:
        with self._lock:
            return self._hosts.get(node_id)

    def _class_for(self, from_id: str, to_id: str, action: str) -> str:
        """Traffic class of this delivery: class_of_action, with "reg"
        upgraded to "dcn" when sender and target sit on different
        (known) hosts."""
        tc = class_of_action(action)
        if tc != "reg":
            return tc
        with self._lock:
            fh = self._hosts.get(from_id)
            th = self._hosts.get(to_id)
        if fh is not None and th is not None and fh != th:
            return "dcn"
        return tc

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def connected_nodes(self) -> list[str]:
        with self._lock:
            return sorted(self._nodes)

    # -- fault injection (MockTransportService analog) --------------------

    def disconnect(self, node_id: str, from_id: str | None = None) -> None:
        """Make node_id unreachable (from from_id, or from everyone)."""
        with self._lock:
            self._disconnected.add((from_id, node_id))

    def reconnect(self, node_id: str, from_id: str | None = None) -> None:
        with self._lock:
            self._disconnected.discard((from_id, node_id))

    def partition(self, side_a: list[str], side_b: list[str]) -> None:
        """Two-way network partition between node groups
        (ref test/disruption/NetworkPartition)."""
        with self._lock:
            for a in side_a:
                for b in side_b:
                    self._disconnected.add((a, b))
                    self._disconnected.add((b, a))

    def add_rule(self, node_id: str, action_prefix: str = "",
                 from_id: str | None = None) -> None:
        """Drop every message TO node_id whose action starts with
        action_prefix ("" = every action — equivalent to disconnect), from
        from_id or from anyone. Unlike disconnect, a scoped rule leaves the
        rest of the link healthy: chaos can kill only bulk replication (or
        only the query phase) while pings keep the node in the cluster."""
        with self._lock:
            self._drop_rules.add((from_id, node_id, action_prefix))

    def clear_rule(self, node_id: str, action_prefix: str = "",
                   from_id: str | None = None) -> None:
        with self._lock:
            self._drop_rules.discard((from_id, node_id, action_prefix))

    def clear_rules(self) -> None:
        with self._lock:
            self._drop_rules.clear()

    def _rule_dropped(self, from_id: str, to_id: str, action: str) -> bool:
        # caller holds the lock
        if not self._drop_rules:
            return False
        return any(nid == to_id and (frm is None or frm == from_id)
                   and action.startswith(pfx)
                   for frm, nid, pfx in self._drop_rules)

    def fault_stats(self) -> dict:
        """Leaves for the `transport` metric section
        (es_transport_faults_injected_total) + active-rule gauges."""
        with self._lock:
            return {"faults_injected_total": self.faults_injected,
                    "disconnected_links": len(self._disconnected),
                    "drop_rules": len(self._drop_rules),
                    "delay_rules": len(self._delays)}

    def heal(self) -> None:
        with self._lock:
            self._disconnected.clear()
            self._drop_rules.clear()
            self._delays.clear()

    def add_delay(self, node_id: str, action_prefix: str,
                  seconds: float) -> None:
        """Inject delivery latency into every message TO node_id whose
        action starts with action_prefix (slow-replica fault injection —
        the hedged-read and traffic-class tests drive this)."""
        with self._lock:
            self._delays[(node_id, action_prefix)] = float(seconds)

    def clear_delay(self, node_id: str, action_prefix: str) -> None:
        with self._lock:
            self._delays.pop((node_id, action_prefix), None)

    def _delay_of(self, to_id: str, action: str) -> float:
        with self._lock:
            if not self._delays:
                return 0.0
            return max((s for (nid, pfx), s in self._delays.items()
                        if nid == to_id and action.startswith(pfx)),
                       default=0.0)

    # -- typed connection classes (ISSUE 9) --------------------------------

    def _acquire_class(self, from_id: str, to_id: str, tclass: str):
        """Take a class connection for the (sender, target) pair, queueing
        in the class's OWN send queue when the budget is full — classes
        never contend with each other. Returns a release callable, or
        None when this thread already holds a connection of the tuple
        (nested same-pair sends re-enter; the in-process transport runs
        handlers in the caller's thread)."""
        key = (from_id, to_id, tclass)
        held: dict = getattr(self._held, "keys", None) or {}
        if held.get(key):
            return None              # re-entrant: ride the held connection
        with self._lock:
            sem = self._class_sems.get(key)
            if sem is None:
                sem = self._class_sems[key] = threading.Semaphore(
                    TRAFFIC_CLASS_CONNECTIONS[tclass])
            st = self._class_stats[tclass]
            st["queue_depth"] += 1
            st["max_queue_depth"] = max(st["max_queue_depth"],
                                        st["queue_depth"])
        acquired = sem.acquire(timeout=CLASS_WAIT_TIMEOUT_S)
        with self._lock:
            st = self._class_stats[tclass]
            st["queue_depth"] -= 1
            if not acquired:
                # fail OPEN: a class saturated past the ceiling proceeds
                # (counted) rather than wedging the cluster
                st["queue_timeouts_total"] += 1
            st["sent_total"] += 1
        held[key] = True
        self._held.keys = held

        def release():
            held.pop(key, None)
            if acquired:
                sem.release()
        return release

    def class_stats(self) -> dict:
        """{class: leaves} for the `transport_class` metric section
        (es_transport_class_queue_depth{class=} et al.)."""
        with self._lock:
            return {c: dict(st) for c, st in self._class_stats.items()}

    # -- the wire ----------------------------------------------------------

    def deliver(self, from_id: str, to_id: str, action: str,
                payload: Any) -> Any:
        with self._lock:
            blocked = ((from_id, to_id) in self._disconnected
                       or (None, to_id) in self._disconnected
                       or self._rule_dropped(from_id, to_id, action))
            if blocked:
                self.faults_injected += 1
            target = self._nodes.get(to_id)
        if blocked or target is None:
            raise ConnectTransportException(to_id, action)
        release = self._acquire_class(from_id, to_id,
                                      self._class_for(from_id, to_id,
                                                      action))
        try:
            delay = self._delay_of(to_id, action)
            if delay > 0:
                with self._lock:
                    self.faults_injected += 1
                import time as _time
                _time.sleep(delay)
            return self._deliver_framed(from_id, to_id, action, payload)
        finally:
            if release is not None:
                release()

    def _deliver_framed(self, from_id: str, to_id: str, action: str,
                        payload: Any) -> Any:
        with self._lock:
            target = self._nodes.get(to_id)
        if target is None:
            raise ConnectTransportException(to_id, action)
        # per-class byte accounting: the recovery class's counter is how
        # the bench/tests verify throttle compliance on the wire itself
        cls_st = self._class_stats[self._class_for(from_id, to_id, action)]
        wire = json.dumps(_encode(payload))
        with self._lock:
            self.messages_sent += 1
            self.bytes_sent += len(wire)
            cls_st["bytes_sent_total"] += len(wire)
            self.max_message_bytes = max(self.max_message_bytes, len(wire))
        request = _decode(json.loads(wire))
        response = target._handle(from_id, action, request)
        wire_resp = json.dumps(_encode(response))
        with self._lock:
            self.bytes_sent += len(wire_resp)
            cls_st["bytes_sent_total"] += len(wire_resp)
            self.max_message_bytes = max(self.max_message_bytes,
                                         len(wire_resp))
        return _decode(json.loads(wire_resp))


class TransportService:
    """Per-node RPC hub (ref TransportService.java:60). Actions are named
    strings (e.g. "indices:data/write/index[p]"); local sends short-circuit
    the registry but still round-trip the wire format."""

    def __init__(self, node_id: str, network: LocalTransport):
        self.node_id = node_id
        self.network = network
        self._handlers: dict[str, Callable[[str, Any], Any]] = {}
        network.register(self)

    def register_handler(self, action: str,
                         handler: Callable[[str, Any], Any]) -> None:
        """handler(from_node_id, request) -> response (JSON-safe)."""
        self._handlers[action] = handler

    def send(self, node_id: str, action: str, payload: Any = None) -> Any:
        """Synchronous request/response. Raises ConnectTransportException if
        the target is unreachable, RemoteTransportException if its handler
        raised."""
        return self.network.deliver(self.node_id, node_id, action, payload)

    def _handle(self, from_id: str, action: str, request: Any) -> Any:
        handler = self._handlers.get(action)
        if handler is None:
            raise ActionNotFoundTransportException(
                f"no handler for [{action}] on [{self.node_id}]")
        try:
            return handler(from_id, request)
        except TransportException:
            raise
        except Exception as e:  # noqa: BLE001 — serialize like a real wire
            raise RemoteTransportException(
                self.node_id, action, type(e).__name__, str(e)) from e

    def close(self) -> None:
        self.network.unregister(self.node_id)
