"""Versioned cluster state: nodes, metadata, routing table + allocation.

The analog of the reference's ClusterState/MetaData/RoutingTable
(/root/reference/src/main/java/org/elasticsearch/cluster/ClusterState.java:61,
119-131; cluster/metadata/MetaData.java; cluster/routing/RoutingTable.java with
the ShardRouting state machine UNASSIGNED→INITIALIZING→STARTED) and of the
allocator that places shards on nodes
(cluster/routing/allocation/AllocationService.java +
allocator/BalancedShardsAllocator.java — here a count-balanced assignment with
the two invariant deciders that matter: never two copies of a shard on one
node (SameShardAllocationDecider) and only live data nodes).

The state is a plain JSON-safe dict wrapped in helpers — it crosses the
transport seam on every publish, so it must serialize by construction. All
mutation happens copy-on-write inside master state-update tasks (service.py);
readers treat a ClusterState as immutable.
"""

from __future__ import annotations

import copy
from typing import Any, Iterator


class IndexClosedError(Exception):
    """Concrete name targets a CLOSED index (ref ClusterBlockException /
    INDEX_CLOSED_BLOCK)."""

    def __init__(self, name: str):
        super().__init__(f"index [{name}] is closed")
        self.index = name

UNASSIGNED = "UNASSIGNED"
INITIALIZING = "INITIALIZING"
STARTED = "STARTED"
RELOCATING = "RELOCATING"   # still serving; a target copy is initializing


class ClusterState:
    """Immutable-by-convention snapshot. `data` layout:

    {"version": int, "cluster_name": str, "master_node": str|None,
     "nodes": {node_id: {"id", "name"}},
     "metadata": {"indices": {name: {"settings", "mappings", "aliases"}},
                  "templates": {...}},
     "routing": {index: [[{"node": str|None, "primary": bool,
                           "state": str}, ...copies], ...shards]}}
    """

    def __init__(self, data: dict):
        self.data = data

    # -- constructors ------------------------------------------------------

    @staticmethod
    def empty(cluster_name: str = "elasticsearch-tpu") -> "ClusterState":
        return ClusterState({
            "version": 0, "cluster_name": cluster_name, "master_node": None,
            "nodes": {}, "metadata": {"indices": {}, "templates": {}},
            "routing": {}})

    def mutate(self) -> "ClusterState":
        """Deep-copied successor with version+1 — the only way new states are
        born (ref ClusterState.Builder)."""
        data = copy.deepcopy(self.data)
        data["version"] = self.version + 1
        return ClusterState(data)

    # -- accessors ---------------------------------------------------------

    @property
    def version(self) -> int:
        return self.data["version"]

    @property
    def master_node(self) -> str | None:
        return self.data["master_node"]

    @property
    def nodes(self) -> dict[str, dict]:
        return self.data["nodes"]

    @property
    def indices(self) -> dict[str, dict]:
        return self.data["metadata"]["indices"]

    @property
    def routing(self) -> dict[str, list[list[dict]]]:
        return self.data["routing"]

    def index_meta(self, index: str) -> dict | None:
        return self.indices.get(index)

    def resolve_index(self, expr: str) -> list[str]:
        """name / alias / _all / comma list (wildcards via fnmatch).
        CLOSED indices are excluded from wildcard/_all expansion and raise
        when named concretely (ref IndicesOptions + IndexClosedException —
        a closed index has no routing to search)."""
        import fnmatch

        def is_open(n: str) -> bool:
            return (self.indices[n] or {}).get("state") != "close"
        if expr in ("_all", "*", ""):
            return sorted(n for n in self.indices if is_open(n))
        out: list[str] = []
        for part in expr.split(","):
            if part in self.indices:
                if not is_open(part):
                    raise IndexClosedError(part)
                out.append(part)
                continue
            hit = [n for n, m in self.indices.items()
                   if (part in m.get("aliases", [])
                       or fnmatch.fnmatch(n, part)) and is_open(n)]
            out.extend(h for h in hit if h not in out)
        return out

    def shard_copies(self, index: str, shard: int) -> list[dict]:
        return self.routing[index][shard]

    def primary_of(self, index: str, shard: int) -> dict | None:
        for copy_ in self.routing[index][shard]:
            if copy_["primary"]:
                return copy_
        return None

    def started_copies(self, index: str, shard: int) -> list[dict]:
        # a RELOCATING source keeps serving until the handoff completes
        # (ref ShardRouting.relocating() — active includes relocating)
        return [c for c in self.routing[index][shard]
                if c["state"] in (STARTED, RELOCATING)
                and c["node"] is not None]

    def assigned_shards(self, node_id: str) -> Iterator[tuple[str, int, dict]]:
        for index, shards in self.routing.items():
            for sid, copies in enumerate(shards):
                for c in copies:
                    if c["node"] == node_id:
                        yield index, sid, c

    def health(self) -> dict:
        """green = all copies started; yellow = all primaries started;
        red = some primary down (ref cluster/health/ClusterHealthStatus)."""
        active_primary = active = init = unassigned = reloc = 0
        red = yellow = False
        for shards in self.routing.values():
            for copies in shards:
                primary_ok = False
                for c in copies:
                    if c["state"] in (STARTED, RELOCATING):
                        active += 1
                        if c["state"] == RELOCATING:
                            reloc += 1
                        if c["primary"]:
                            primary_ok = True
                            active_primary += 1
                    elif c["state"] == INITIALIZING:
                        init += 1
                        if not c.get("relocation"):
                            yellow = True   # relocation targets are surplus
                    else:
                        unassigned += 1
                        yellow = True
                if not primary_ok:
                    red = True
        return {
            "status": "red" if red else ("yellow" if yellow else "green"),
            "number_of_nodes": len(self.nodes),
            "number_of_data_nodes": len(self.nodes),
            "active_primary_shards": active_primary,
            "active_shards": active,
            "relocating_shards": reloc,
            "initializing_shards": init,
            "unassigned_shards": unassigned,
        }


# -- allocation (ref AllocationService.reroute + BalancedShardsAllocator) ---
#
# `decider` accepts either form:
#   * legacy single decider — can_allocate(node_id) / should_evacuate(
#     node_id) (cluster/info.DiskThresholdDecider, kept for direct use);
#   * a cluster/deciders.DeciderChain — can_allocate_shard(state, index,
#     sid, node_id) / can_remain_shard(...), the composable roster with
#     per-decider verdicts (ref AllocationDeciders.java).


def _is_chain(decider) -> bool:
    return hasattr(decider, "can_allocate_shard")


def next_aid(state: ClusterState) -> int:
    """Fresh allocation id (ref AllocationId.newInitializing): every
    (re)assignment of a copy gets a unique id so a shard-started /
    shard-failed report from a PREVIOUS assignment era — a restarted
    process's stale pull, a late replication-failure notice — can never
    act on the current assignment. The counter lives in the state itself
    so it survives master handoff and stays strictly increasing."""
    seq = state.data.get("aid_seq", 0) + 1
    state.data["aid_seq"] = seq
    return seq


def allocate(state: ClusterState, decider=None) -> bool:
    """Assign UNASSIGNED copies to live nodes, balancing by shard count.
    Mutates `state` in place (call inside a mutate()d successor only).
    Returns True if anything changed. Invariants: a node holds at most one
    copy of a given shard (SameShardAllocationDecider analog); an unassigned
    PRIMARY is only placed where it can recover (fresh index) — primaries of
    lost shards stay unassigned (red) rather than silently reborn empty."""
    chain = decider if _is_chain(decider) else None
    live = set(state.nodes)
    if decider is not None and chain is None:
        live = {n for n in live if decider.can_allocate(n)}
    loads = {n: 0 for n in live}
    for index, shards in state.routing.items():
        for copies in shards:
            for c in copies:
                if c["node"] in loads and c["state"] != UNASSIGNED:
                    loads[c["node"]] += 1
    changed = False
    for index, shards in state.routing.items():
        for sid, copies in enumerate(shards):
            holders = {c["node"] for c in copies
                       if c["node"] is not None and c["state"] != UNASSIGNED}
            has_started_primary = any(
                c["primary"] and c["state"] == STARTED for c in copies)
            for c in copies:
                if c["state"] != UNASSIGNED:
                    continue
                # a replica can only initialize off a started primary
                # (peer recovery needs a source); a fresh primary (never
                # started anywhere, fresh==True) can start empty anywhere
                if not c["primary"] and not has_started_primary:
                    continue
                if c["primary"] and not c.get("fresh", False):
                    continue
                candidates = sorted(
                    (n for n in live if n not in holders),
                    key=lambda n: (loads[n], n))
                if chain is not None:
                    # first candidate every decider allows; a THROTTLE
                    # (falsy, not a veto) defers to a later round
                    candidates = [
                        n for n in candidates
                        if chain.can_allocate_shard(state, index, sid, n)]
                if not candidates:
                    continue
                node = candidates[0]
                c["node"] = node
                c["state"] = INITIALIZING
                c["aid"] = next_aid(state)
                holders.add(node)
                loads[node] += 1
                changed = True
    return changed


def rebalance(state: ClusterState, max_moves: int = 2,
              decider=None) -> bool:
    """Move STARTED copies from overloaded to underloaded nodes via the
    RELOCATING state machine (ref allocator/BalancedShardsAllocator.java +
    ShardRouting RELOCATING): the source keeps serving, a surplus target
    copy initializes via peer recovery, and the handoff completes when the
    target reports started. Runs only on a stable table (no unassigned /
    non-relocation initializing copies) and caps moves per pass so a
    joining node fills up without a thundering herd.
    Legacy `decider` (cluster/info.DiskThresholdDecider): nodes over the
    LOW watermark receive no shards; nodes over the HIGH watermark count
    as maximally loaded so their shards move off first. A DeciderChain
    instead drives a forced-move pass (can_remain_shard NO — filter
    drains, disk high watermark) before the load-balance pass, with every
    destination gated per-shard through can_allocate_shard."""
    chain = decider if _is_chain(decider) else None
    live = set(state.nodes)
    if not live:
        return False
    loads = {n: 0 for n in live}
    for shards in state.routing.values():
        for copies in shards:
            for c in copies:
                if c["state"] in (UNASSIGNED, INITIALIZING) \
                        and not c.get("relocation"):
                    return False      # allocate()'s job first
                if c["state"] == RELOCATING:
                    return False      # one wave at a time
                if c["node"] in loads:
                    loads[c["node"]] += 1

    def start_move(index, sid, c, dst_node):
        c["state"] = RELOCATING
        c["relocating_to"] = dst_node
        state.routing[index][sid].append({
            "node": dst_node, "primary": False,
            "state": INITIALIZING, "relocation": True,
            "aid": next_aid(state),
            "recover_from": c["node"],
            "primary_target": c["primary"]})
        loads[c["node"]] -= 1
        loads[dst_node] += 1

    changed = False
    moves_left = max_moves
    if chain is not None:
        # pass 1 — forced moves: copies a decider says cannot REMAIN
        # (allocation filters, disk high watermark) drain to the least
        # loaded node that accepts them, ahead of any balance moves
        for index, shards in state.routing.items():
            for sid, copies in enumerate(shards):
                if moves_left <= 0:
                    break
                holders = {c["node"] for c in copies
                           if c["node"] is not None}
                for c in copies:
                    if c["state"] != STARTED or c["node"] not in live:
                        continue
                    if chain.can_remain_shard(state, index, sid, c["node"]):
                        continue
                    dsts = sorted(
                        (n for n in live
                         if n not in holders
                         and chain.can_allocate_shard(state, index, sid, n)),
                        key=lambda n: (loads[n], n))
                    if not dsts:
                        continue
                    start_move(index, sid, c, dsts[0])
                    moves_left -= 1
                    changed = True
                    break
        # pass 2 — count balance, destinations gated per shard
        while moves_left > 0:
            src_node = max(loads, key=lambda n: (loads[n], n))
            moved = False
            for index, shards in state.routing.items():
                for sid, copies in enumerate(shards):
                    holders = {c["node"] for c in copies
                               if c["node"] is not None}
                    for c in copies:
                        if c["node"] != src_node \
                                or c["state"] != STARTED:
                            continue
                        dsts = sorted(
                            (n for n in live
                             if n not in holders
                             and loads[src_node] - loads[n] > 1
                             and chain.can_allocate_shard(
                                 state, index, sid, n)),
                            key=lambda n: (loads[n], n))
                        if not dsts:
                            continue
                        start_move(index, sid, c, dsts[0])
                        moves_left -= 1
                        moved = changed = True
                        break
                    if moved:
                        break
                if moved:
                    break
            if not moved:
                break
        return changed

    evac = {n for n in live
            if decider is not None and decider.should_evacuate(n)}
    targets = {n for n in live
               if decider is None or decider.can_allocate(n)}
    for _ in range(max_moves):
        # evacuating nodes drain first; destinations must pass the decider
        src_node = max(loads, key=lambda n: (n in evac, loads[n], n))
        dst_pool = targets - {src_node}
        if not dst_pool:
            break     # nowhere under the watermark to move shards to
        dst_node = min(dst_pool, key=lambda n: (loads[n], n))
        if src_node not in evac \
                and loads[src_node] - loads[dst_node] <= 1:
            break
        if src_node in evac and loads[src_node] == 0:
            break
        moved = False
        for index, shards in state.routing.items():
            for sid, copies in enumerate(shards):
                holders = {c["node"] for c in copies
                           if c["node"] is not None}
                if dst_node in holders:
                    continue
                for c in copies:
                    if c["node"] == src_node and c["state"] == STARTED:
                        start_move(index, sid, c, dst_node)
                        moved = changed = True
                        break
                if moved:
                    break
            if moved:
                break
        if not moved:
            break
    return changed


def finish_relocation(state: ClusterState, index: str, sid: int,
                      target_node: str) -> bool:
    """Target caught up: hand off — the target becomes a normal copy
    (inheriting primaryhood) and the source copy disappears."""
    copies = state.routing[index][sid]
    target = next((c for c in copies if c["node"] == target_node
                   and c.get("relocation")), None)
    if target is None:
        return False
    source = next((c for c in copies if c["state"] == RELOCATING
                   and c.get("relocating_to") == target_node), None)
    target["state"] = STARTED
    # inherit primaryhood from the source AT HANDOFF TIME — the source may
    # have been promoted mid-relocation when the old primary died (a stale
    # snapshot would leave the shard primary-less; code review r5)
    target["primary"] = bool(source["primary"]) if source is not None \
        else bool(target.get("primary_target", False))
    target.pop("primary_target", None)
    target.pop("relocation", None)
    target.pop("recover_from", None)
    if source is not None:
        copies.remove(source)
    else:
        # The source may have been reverted to STARTED by a concurrent
        # cancel_relocations_for (target node died in the same tick as
        # the finish ack) — or failed and reallocated — while still
        # carrying the stale pointer. A STARTED copy with a dangling
        # relocating_to would be double-counted by finish/cancel sweeps
        # forever: clear the zombie pointer (race fix, ISSUE 15).
        for c in copies:
            if c is not target and c.get("relocating_to") == target_node:
                c.pop("relocating_to", None)
    return True


def cancel_relocations_for(state: ClusterState, node_id: str) -> None:
    """A relocation endpoint died: revert sources, drop surplus targets —
    including targets whose RECOVERY SOURCE died (they would retry a dead
    node forever while squatting on their slot; code review r5)."""
    for shards in state.routing.values():
        for copies in shards:
            for c in [c for c in copies
                      if c.get("relocation")
                      and (c["node"] == node_id
                           or c.get("recover_from") == node_id)]:
                copies.remove(c)
            for c in copies:
                if c["state"] == RELOCATING \
                        and c.get("relocating_to") == node_id:
                    c["state"] = STARTED
                    c.pop("relocating_to", None)


def remove_node(state: ClusterState, node_id: str,
                decider=None) -> None:
    """Node-leave: drop it from nodes, promote replicas for its primaries,
    unassign its replicas (ref AllocationService on node departure — the
    elastic-recovery reaction in SURVEY.md §5.3)."""
    state.nodes.pop(node_id, None)
    cancel_relocations_for(state, node_id)
    for index, shards in state.routing.items():
        for copies in shards:
            lost_primary = False
            for c in [c for c in copies if c["node"] == node_id]:
                if c.get("relocation"):
                    copies.remove(c)     # surplus target: just drop it
                    continue
                if c["primary"]:
                    lost_primary = True
                c["node"] = None
                c["state"] = UNASSIGNED
                c["primary"] = False
                c.pop("fresh", None)
                c.pop("relocating_to", None)
            if lost_primary:
                # promote the first started replica (ref
                # RoutingNodes.activePrimary promotion)
                for c in copies:
                    if c["state"] in (STARTED, RELOCATING):
                        c["primary"] = True
                        break
    allocate(state, decider=decider)


def new_index_routing(n_shards: int, n_replicas: int) -> list[list[dict]]:
    """Fresh routing for a new index: primary marked `fresh` (may start
    empty — there is nothing to recover), replicas recover from it."""
    return [[{"node": None, "primary": True, "state": UNASSIGNED,
              "fresh": True}]
            + [{"node": None, "primary": False, "state": UNASSIGNED}
               for _ in range(n_replicas)]
            for _ in range(n_shards)]
