"""Tribe node: a federated read view over multiple clusters.

The analog of /root/reference/src/main/java/org/elasticsearch/tribe/
(TribeService.java:63 — a node that joins N clusters as a client, merges
their cluster states into one view, and serves reads across all of them;
index-name conflicts resolve by preference order, like the reference's
on_conflict: prefer_<cluster> setting).

Reads (search/msearch/get) fan out to the owning cluster; writes are
rejected (the reference's tribe node is read-only on the merged view
unless the index is unambiguous — we keep the stricter, simpler contract).
"""

from __future__ import annotations

import fnmatch
from typing import Any


class TribeWriteException(Exception):
    pass


class TribeNode:
    """members: {cluster_alias: NodeService-like} in PREFERENCE order —
    on index-name conflicts the first member owning the name wins."""

    def __init__(self, members: dict[str, Any]):
        self.members = dict(members)

    # -- merged view -------------------------------------------------------

    def index_owner(self, name: str):
        for alias, node in self.members.items():
            if name in node.indices:
                return alias, node
        return None, None

    def merged_indices(self) -> dict[str, str]:
        """index name -> owning cluster alias (first wins on conflict)."""
        out: dict[str, str] = {}
        for alias, node in self.members.items():
            for n in node.indices:
                out.setdefault(n, alias)
        return out

    def cluster_state(self) -> dict:
        merged = self.merged_indices()
        return {"cluster_name": "tribe",
                "indices": {n: {"cluster": a} for n, a in merged.items()},
                "members": sorted(self.members)}

    def _resolve(self, expr: str) -> dict[Any, list[str]]:
        """index expression -> {owning node: [concrete names]}."""
        merged = self.merged_indices()
        out: dict[Any, list[str]] = {}
        for part in str(expr or "_all").split(","):
            part = part.strip()
            for n, alias in merged.items():
                if part in ("_all", "*", "") or part == n \
                        or ("*" in part and fnmatch.fnmatch(n, part)):
                    node = self.members[alias]
                    out.setdefault(node, [])
                    if n not in out[node]:
                        out[node].append(n)
        return out

    # -- reads -------------------------------------------------------------

    def search(self, index: str, body: dict | None = None) -> dict:
        """Scatter to each owning cluster, merge hit lists by score (the
        coordinator-side reduce the reference runs over its merged view)."""
        by_node = self._resolve(index)
        if not by_node:
            from ..node import IndexMissingException
            raise IndexMissingException(index)
        body = body or {}
        size = int(body.get("size", 10))
        parts = [node.search(",".join(names), dict(body))
                 for node, names in by_node.items()]
        hits: list = []
        total = 0
        max_score = None
        took = 0
        for p in parts:
            total += p["hits"]["total"]
            took = max(took, p.get("took", 0))
            ms = p["hits"]["max_score"]
            if ms is not None:
                max_score = ms if max_score is None else max(max_score, ms)
            hits.extend(p["hits"]["hits"])
        hits.sort(key=lambda h: -(h.get("_score") or 0.0))
        return {"took": took, "timed_out": False,
                "_shards": {"total": sum(p["_shards"]["total"]
                                         for p in parts),
                            "successful": sum(p["_shards"]["successful"]
                                              for p in parts),
                            "failed": sum(p["_shards"]["failed"]
                                          for p in parts)},
                "hits": {"total": total, "max_score": max_score,
                         "hits": hits[:size]}}

    def get_doc(self, index: str, doc_id: str, **kw):
        _, node = self.index_owner(index)
        if node is None:
            from ..node import IndexMissingException
            raise IndexMissingException(index)
        return node.get_doc(index, doc_id, **kw)

    # -- writes: rejected on the merged view ------------------------------

    def index_doc(self, *a, **kw):
        raise TribeWriteException(
            "tribe node is read-only over the merged view "
            "(write to a member cluster directly)")

    delete_doc = index_doc
