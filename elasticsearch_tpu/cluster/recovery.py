"""Peer-recovery rate limiting + process-wide recovery counters.

The analog of the reference's RecoverySettings.rateLimiter
(indices/recovery/RecoverySettings.java — a SimpleRateLimiter fed by
`indices.recovery.max_bytes_per_sec`, default 40mb): every file chunk a
recovery TARGET pulls pays tokens into a per-node token bucket before
the bytes hit disk, so N concurrent recoveries share one node-wide
budget and a relocation wave cannot starve serving traffic of I/O.

Counters live module-level (the qos.record_hedge pattern): one source
of truth feeding /_metrics (`es_recovery_*`), the sampler ring, and the
bench's throttle-compliance check, readable from both the cluster
ClusterNode and the single-node NodeService without plumbing.
"""

from __future__ import annotations

import threading
import time

_UNITS = {"b": 1, "kb": 1 << 10, "mb": 1 << 20, "gb": 1 << 30,
          "tb": 1 << 40}


def parse_bytes(v, default: float = 0.0) -> float:
    """Human byte-size string -> bytes/float. `0`, negative, or unset
    mean unlimited (returned as 0.0). Accepts ints and "40mb" forms."""
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return float(v) if v > 0 else 0.0
    s = str(v).strip().lower()
    if not s:
        return default
    for suffix in ("tb", "gb", "mb", "kb", "b"):
        if s.endswith(suffix):
            try:
                n = float(s[: -len(suffix)])
            except ValueError:
                return default
            n *= _UNITS[suffix]
            return n if n > 0 else 0.0
    try:
        n = float(s)
    except ValueError:
        return default
    return n if n > 0 else 0.0


class RecoveryCancelled(Exception):
    """Raised between chunks when the shard's recovery was cancelled by
    a newer cluster state (cancel_relocations_for / drop)."""


class RecoveryThrottle:
    """Token bucket over `rate_fn() -> bytes/sec` (0 = unlimited).

    The rate is re-read on every acquire so a live settings update takes
    effect mid-stream. Burst capacity is one half second of tokens —
    small enough that a chunk stream can never spike far above the
    configured rate, large enough that one RECOVERY_CHUNK never waits
    at sane rates."""

    def __init__(self, rate_fn):
        self.rate_fn = rate_fn
        self._lock = threading.Lock()
        self._tokens = 0.0
        self._last = time.monotonic()
        self.waits_total = 0
        self.throttled_time_s = 0.0

    def acquire(self, nbytes: int) -> float:
        """Block until `nbytes` of budget is available; returns seconds
        slept (0.0 when the bucket had room)."""
        rate = float(self.rate_fn() or 0.0)
        if rate <= 0 or nbytes <= 0:
            return 0.0
        burst = max(float(nbytes), rate / 2.0)
        slept = 0.0
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    burst, self._tokens + (now - self._last) * rate)
                self._last = now
                if self._tokens >= nbytes:
                    self._tokens -= nbytes
                    if slept > 0.0:
                        self.waits_total += 1
                        self.throttled_time_s += slept
                    return slept
                need = (nbytes - self._tokens) / rate
            wait = min(need, 0.5)
            time.sleep(wait)
            slept += wait


# -- process-wide counters (the qos.record_hedge pattern) -----------------

_LOCK = threading.Lock()
_COUNTER_KEYS = ("bytes_total", "chunks_total", "throttle_waits_total",
                 "retries_total", "cancelled_total", "completed_total")
_STATS = {k: 0 for k in _COUNTER_KEYS}


def record(key: str, n: int = 1) -> None:
    with _LOCK:
        _STATS[key] = _STATS.get(key, 0) + n


def snapshot() -> dict[str, int]:
    with _LOCK:
        return dict(_STATS)


def reset() -> None:
    """Test seam only."""
    with _LOCK:
        for k in list(_STATS):
            _STATS[k] = 0
