"""Multi-node cluster layer: transport seam, versioned cluster state with a
single-writer master, state publish, replicated writes, peer recovery, and
the in-process multi-node test harness (SURVEY.md §2.2/§2.3 — L1/L2)."""

from .harness import TestCluster
from .node import (ClusterNode, NoMasterException,
                   UnavailableShardsException)
from .service import ClusterService
from .state import ClusterState, allocate, new_index_routing, remove_node
from .transport import (ConnectTransportException, LocalTransport,
                        RemoteTransportException, TransportService)

__all__ = [
    "TestCluster", "ClusterNode", "ClusterService", "ClusterState",
    "LocalTransport", "TransportService", "ConnectTransportException",
    "RemoteTransportException", "NoMasterException",
    "UnavailableShardsException", "allocate", "new_index_routing",
    "remove_node",
]
