"""Node-local mesh reduce for the cluster data plane (ISSUE 11 tentpole).

The cluster coordinator used to pay one transport round-trip AND one host
merge per shard — even when a data node co-hosts several shards of the
index, which the single-process mesh lane (parallel/mesh_exec, PRs 6/8)
already knows how to reduce in ONE device program. This module is the
data-node side of `A_QUERY_HOST`: all STARTED shard copies a node
co-hosts for a query execute as one `shard_map` program (blockwise scan
inside when configured, cross-shard `all_gather`+`top_k`/`psum`/`pmax`
reduce, agg partials and IVF kNN included), and the transport carries ONE
pre-reduced message per host instead of one per shard.

The response DECOMPOSES the merged candidate list back into per-shard
wire results — each shard's surviving entries are a PREFIX of the top-k
list that shard's own `_shard_query_phase` would have returned (stable
top_k keeps same-shard candidates in rank order, and a rank-r survivor
implies ranks < r survived), and per-shard totals/max/agg partials ride
the program's gathered outputs. The coordinator's `_reduce` therefore
merges host-reduced and per-shard results identically, bit-for-bit:
"ICI collectives intra-host, DCN only between hosts" (SURVEY §5.8).

SORTED bodies (ISSUE 17) ride the same seam: the data node runs the
group's shards through `mesh_exec.execute_sorted` over the encoded key
columns (search/sort_encode.py, cross-shard keyword vocab included) and
decomposes the merged candidate list with MATERIALIZED per-hit `sort`
arrays — real strings/numbers in the per-shard fan-out's wire format, so
the coordinator's `compare_key` merge across hosts stays bitwise
identical. Sub-agg trees flow through untouched: `mesh_aggs.plan_aggs`
flattens them into composite bins and the agg wire codec already
round-trips nested `subs` partials.

Fallback ladder: anything without a single-program form — unsupported
plan/agg/sort shapes (sort_encode.decline_reason, calendar-interval or
float-keyed sub-agg trees), `_doc` sorts over a non-prefix shard group,
mixed IVF/exact vector lanes, missing DFS stats for term queries,
undersized meshes, any execution error — returns a decline and the
coordinator falls back to the per-shard hedged fan-out for that host's
shards.
"""

from __future__ import annotations

import numpy as np

from ..search.shard_searcher import LOCAL_MASK, SEG_SHIFT

HOST_REDUCE_SETTING = "cluster.search.host_reduce.enable"


def body_eligible(body: dict) -> bool:
    """Coordinator-side pre-flight: body shapes the host reduce can ever
    serve (the data node makes the finer plan-level call). Sorted bodies
    and search_after cursors are eligible since ISSUE 17 — the data node
    declines the encodings the device sort cannot bitwise-reproduce."""
    return (not body.get("rescore")
            and not body.get("suggest")
            and body.get("rank") is None)


def setting_enabled(value) -> bool:
    if isinstance(value, str):
        return value.strip().lower() not in ("false", "0", "no", "off")
    return value is not False


# Process-wide mirror of the pod reduce-plane counters (ISSUE 20
# satellite of ISSUE 19): ClusterNode keeps per-node `host_reduce_stats`,
# but the stats-history ring samples through the single-process
# NodeService, which can't reach the cluster coordinators — this mirror
# aggregates every coordinator in the process so pod dispatch/DCN-hop
# totals land in `.monitoring-es-*` and become watchable.
import threading as _threading  # noqa: E402

_POD_STATS_LOCK = _threading.Lock()
_POD_STATS = {"pod_dispatches_total": 0, "dcn_hops_total": 0}


def note_pod_dispatch() -> None:
    with _POD_STATS_LOCK:
        _POD_STATS["pod_dispatches_total"] += 1


def note_dcn_hop() -> None:
    with _POD_STATS_LOCK:
        _POD_STATS["dcn_hops_total"] += 1


def pod_reduce_snapshot() -> dict:
    with _POD_STATS_LOCK:
        return dict(_POD_STATS)


def try_host_reduce(node, index: str, sids: list[int], body: dict,
                    k: int, dfs: dict | None):
    """Execute the co-hosted shards' query phase as one mesh program.

    -> {"shards": {str(sid): per-shard wire result}} or (None, reason) as
    a decline. `node` is the ClusterNode; `sids` arrive in target order
    (ascending), which becomes the mesh shard-row order — the same
    tie-break order the coordinator's ti-ordered merge uses."""
    from ..common.device_stats import lane_chosen, lane_decline
    from ..parallel import mesh_exec
    from ..search.aggs.aggregators import parse_aggs

    def _declined(reason: str):
        lane_decline("cluster_reduce", "host_reduce", reason)
        return None, reason

    searchers = []
    for sid in sids:
        holder = node._shards.get((index, sid))
        if holder is None or holder.engine is None:
            return _declined("shard_unavailable")
        searchers.append(node._searcher(index, sid, holder))
    if mesh_exec.mesh_for(len(searchers),
                          pool=getattr(node, "device_pool", None)) is None:
        return _declined("no_mesh")

    knn = body.get("knn")
    agg_specs = parse_aggs(body.get("aggs") or body.get("aggregations")) \
        if (body.get("aggs") or body.get("aggregations")) else None
    sort_specs = None
    if body.get("sort") is not None:
        from ..search.sort import parse_sort
        try:
            sort_specs = parse_sort(body["sort"],
                                    [node._mappers[index]])
        except Exception:  # noqa: BLE001 — the per-shard phase reports
            return _declined("sort_parse")
    if body.get("search_after") and sort_specs is None:
        # the per-shard phase raises the user-facing error; keep the
        # error on that path instead of swallowing it here
        return _declined("search_after_no_sort")

    if knn is not None:
        if agg_specs:
            return _declined("knn_aggs")
        if sort_specs is not None:
            return _declined("knn_sort")
        out = _knn_host_reduce(node, index, sids, searchers, knn, k)
        agg_specs = None
    else:
        out = _query_host_reduce(node, index, sids, searchers, body,
                                 agg_specs, k, dfs, sort_specs)
    if isinstance(out, tuple) and out[0] is None:
        return _declined(out[1])
    keys, shard_of, scores, totals, mxs, agg_parts = out
    lane_chosen("cluster_reduce", "host_reduce")
    track = bool(body.get("track_scores", False)) \
        if sort_specs is not None else True
    return _decompose(searchers, sids, keys, shard_of, scores, totals,
                      mxs, agg_parts, agg_specs, sort_specs=sort_specs,
                      track_scores=track), None


def _index_setting(node, index: str):
    meta = node.cluster.current().indices.get(index) or {}
    settings = meta.get("settings") or {}

    def get_s(key, default):
        return settings.get(f"index.{key}", settings.get(key, default))
    return get_s


def _mesh_group_name(index: str, sids: list[int]) -> str:
    """Cache key prefix: the mesh stack of a shard GROUP is keyed by the
    group, not just the index — a node may serve different subsets over
    time as shards move."""
    return f"{index}::{','.join(str(s) for s in sids)}"


def _query_host_reduce(node, index, sids, searchers, body, agg_specs,
                       k, dfs, sort_specs=None):
    from . import node as cluster_node_mod
    from ..parallel import mesh_exec
    from ..search.query_dsl import contains_joins

    get_s = _index_setting(node, index)
    if not setting_enabled(get_s("search.mesh.enable", True)):
        return None, "index_opt_out"
    query = body.get("query") or {"match_all": {}}
    try:
        tree = searchers[0].parse([query])
    except Exception:  # noqa: BLE001 — the per-shard phase will report
        return None, "parse"
    if contains_joins(tree):
        return None, "joins"
    if not mesh_exec.plan_types_supported(tree):
        return None, "plan"
    stats = cluster_node_mod._stats_from_wire(dfs)
    if stats is None:
        # a term-less tree never consults stats; term queries without a
        # DFS round would score with host-local stats and diverge from
        # the per-shard path (which uses its own shard-local stats)
        terms: dict[str, set] = {}
        tree.collect_terms(terms)
        if any(terms.values()):
            return None, "no_dfs"
        from ..search.query_dsl import CollectionStats
        stats = CollectionStats(doc_count=1, field_sum_dl={},
                                doc_freqs={})
    blockwise = setting_enabled(get_s("search.blockwise.enable", True))
    try:
        block_docs = int(get_s("search.block_docs", 0)) or None
    except (TypeError, ValueError):
        block_docs = None
    from ..search.blockwise import DEFAULT_BLOCK_DOCS
    stack = node._host_mesh_stacks.get_or_build(
        _mesh_group_name(index, sids), 0,
        [list(s.segments) for s in searchers],
        pool=getattr(node, "device_pool", None))
    if stack is None:
        return None, "stack"
    if sort_specs is not None:
        from ..search.sort import DOC
        if any(sp.field == DOC for sp in sort_specs) \
                and list(sids) != list(range(len(sids))):
            # `_doc` encoded keys (and cursors) embed the mesh ROW as
            # the shard id; rows only coincide with real shard ids when
            # the group is exactly shards 0..n-1 of the index
            return None, "doc_sort_rows"
        out = mesh_exec.execute_sorted(
            stack, tree, stats, sort_specs,
            body.get("search_after") or None, k=k, Q=1,
            agg_specs=agg_specs)
        if out is None:
            return None, "sorted_lane"
        return out
    out = mesh_exec.execute(
        stack, tree, stats, k=k, Q=1,
        block_docs=(block_docs or DEFAULT_BLOCK_DOCS) if blockwise
        else None,
        agg_specs=agg_specs)
    if out is None:
        return None, "plan_shape"
    return out


def _knn_host_reduce(node, index, sids, searchers, knn, k):
    from ..parallel import mesh_knn

    get_s = _index_setting(node, index)
    if not setting_enabled(get_s("search.mesh.enable", True)):
        return None, "index_opt_out"
    field = knn.get("field")
    qv = knn.get("query_vector")
    if field is None or qv is None:
        return None, "knn_shape"
    raw_np = knn.get("nprobe")
    nprobe = int(raw_np) if raw_np is not None else None
    exact = bool(knn.get("exact", False))
    knn_k = int(knn.get("k", k))
    vstack = node._host_vector_stacks.get_or_build(
        _mesh_group_name(index, sids), 0, field,
        [list(s.segments) for s in searchers],
        pool=getattr(node, "device_pool", None))
    if vstack is None:
        return None, "vstack"
    fnode = None
    fstack = None
    if knn.get("filter"):
        fnode = searchers[0].parse([knn["filter"]])
        fstack = node._host_mesh_stacks.get_or_build(
            _mesh_group_name(index, sids), 0,
            [list(s.segments) for s in searchers],
            pool=getattr(node, "device_pool", None))
        if fstack is None:
            return None, "stack"
    out = mesh_knn.execute(
        vstack, [qv], k=knn_k, metric=knn.get("metric", "cosine"),
        knn_opts=searchers[0].knn_opts, nprobe=nprobe, exact=exact,
        quantization=knn.get("quantization"),
        acquire_ivf=lambda si, seg, vc: searchers[si]._acquire_ivf(
            seg, vc, field, nprobe, exact),
        acquire_quant=lambda si, seg, vc, ivf, mode:
            searchers[si]._acquire_quant(seg, vc, field, ivf, mode),
        filter_node=fnode, filter_stack=fstack)
    if out is None:
        return None, "knn_lane"
    keys, shard_of, scores, totals, mxs, _used_ivf, _used_quant = out
    return keys, shard_of, scores, totals, mxs, None


def _decompose(searchers, sids, keys, shard_of, scores, totals, mxs,
               agg_parts, agg_specs, sort_specs=None,
               track_scores=True) -> dict:
    """Merged device outputs -> per-shard wire results. Entries keep
    their per-shard rank order (a prefix of each shard's own top-k), so
    the coordinator's (score, target, pos) merge order is preserved.
    Sorted bodies additionally materialize each hit's user-facing `sort`
    array (real strings/numbers, the REAL shard id for `_doc`) so the
    coordinator's compare_key merge sees the per-shard fan-out's exact
    wire values."""
    from .node import _jsonval
    from ..search import sort as sort_mod

    out: dict[str, dict] = {}
    for pos, sid in enumerate(sids):
        mx = float(mxs[pos, 0])
        if sort_specs is not None and not track_scores:
            # the sorted loop reports NaN max_score unless track_scores
            mxv = None
        else:
            mxv = mx if np.isfinite(mx) else None
        out[str(sid)] = {"ids": [], "scores": [],
                        "sort": [] if sort_specs is not None else None,
                        "total": int(totals[pos, 0]),
                        "max_score": mxv}
    row_k, row_sh, row_s = keys[0], shard_of[0], scores[0]
    for j in range(row_k.shape[0]):
        key = int(row_k[j])
        if key < 0:
            continue
        pos = int(row_sh[j])
        seg = searchers[pos].segments[key >> SEG_SHIFT]
        wire = out[str(sids[pos])]
        # doc IDS cross the seam, not positional keys (the same safety
        # contract as _shard_query_phase: fetch may race a flush/merge)
        wire["ids"].append(seg.ids[key & LOCAL_MASK])
        sc = float(row_s[j])
        if sort_specs is not None:
            sc = sc if track_scores else float("nan")
            wire["sort"].append(_jsonval(sort_mod.materialize(
                seg, sort_specs, key & LOCAL_MASK, sc, key, sids[pos])))
        wire["scores"].append(None if sc != sc else sc)
    if agg_parts is not None and agg_specs is not None:
        from ..search.aggs.wire import partials_to_wire
        for pos, sid in enumerate(sids):
            out[str(sid)]["aggs"] = partials_to_wire(agg_specs,
                                                     agg_parts[pos])
    return {"shards": out}
