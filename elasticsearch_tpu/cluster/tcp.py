"""TCP transport: the real inter-process wire.

The analog of the reference's Netty transport
(/root/reference/src/main/java/org/elasticsearch/transport/netty/NettyTransport.java:98,180-184
— framed TCP with a fixed header, optional compression, connection pools per
node pair; transport/netty/NettyHeader.java:30 — 'E''S' magic + size +
requestId + status byte + wire version). This module speaks a versioned
binary frame protocol over plain sockets so two *processes* (or machines)
can form a cluster — the capability LocalTransport structurally lacks.

Frame layout (big-endian):

    magic   2s   b"ET"
    version u16  wire protocol version (connection rejected on major
                 mismatch, like the reference's Version.readVersion check)
    status  u8   bit0 = response, bit1 = error response, bit2 = payload
                 zlib-compressed (the reference's LZF option)
    req_id  u64  client-assigned id; responses echo it (multiplexing many
                 in-flight requests over one connection)
    length  u32  payload byte length
    -- requests only --
    from_id u16-prefixed utf8
    action  u16-prefixed utf8
    -- then `length` payload bytes --

Payloads are the same tagged-JSON encoding as transport.py (`_encode`), so
every message that crosses LocalTransport in tests crosses this wire
byte-identically — one serialization discipline, two media.

`TcpTransport` duck-types LocalTransport (register / unregister /
connected_nodes / deliver / disconnect / partition / heal + wire stats), so
ClusterNode and the disruption tests run unchanged over real sockets.
Cross-process discovery: a node dials seed addresses and issues the
handshake action, learning {node_id: address} maps gossip-style (ref
discovery/zen/ping/unicast/UnicastZenPing.java — seed-list ping).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import zlib
from typing import Any

from .transport import (ActionNotFoundTransportException,
                        ConnectTransportException, RemoteTransportException,
                        TransportException, _decode, _encode)

WIRE_VERSION = 1
MAGIC = b"ET"
_HDR = struct.Struct(">2sHBQI")          # magic, version, status, req_id, len
ST_RESPONSE = 1
ST_ERROR = 2
ST_COMPRESSED = 4
COMPRESS_MIN = 1024                       # compress payloads above 1 KiB
A_HANDSHAKE = "internal:tcp/handshake"


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _encode_payload(payload: Any) -> tuple[bytes, int]:
    raw = json.dumps(_encode(payload)).encode("utf-8")
    if len(raw) >= COMPRESS_MIN:
        comp = zlib.compress(raw, 1)
        if len(comp) < len(raw):
            return comp, ST_COMPRESSED
    return raw, 0


def _decode_payload(data: bytes, status: int) -> Any:
    if status & ST_COMPRESSED:
        data = zlib.decompress(data)
    return _decode(json.loads(data.decode("utf-8")))


class _Connection:
    """One pooled client connection: a send lock, a reader thread, and a
    req_id -> waiter map (the multiplexing the reference gets from Netty
    channel handlers)."""

    def __init__(self, addr: tuple[str, int]):
        self.sock = socket.create_connection(addr, timeout=10.0)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._next_id = 0
        self._waiters: dict[int, dict] = {}
        self.broken = False
        t = threading.Thread(target=self._read_loop, daemon=True)
        t.start()

    def _read_loop(self) -> None:
        try:
            while True:
                magic, ver, status, req_id, length = _HDR.unpack(
                    _read_exact(self.sock, _HDR.size))
                if magic != MAGIC:
                    raise ConnectionError(f"bad magic {magic!r}")
                data = _read_exact(self.sock, length) if length else b""
                with self._lock:
                    w = self._waiters.pop(req_id, None)
                if w is not None:
                    w["status"] = status
                    w["data"] = data
                    w["event"].set()
        except (ConnectionError, OSError):
            self._fail_all()

    def _fail_all(self) -> None:
        self.broken = True
        with self._lock:
            waiters, self._waiters = dict(self._waiters), {}
        for w in waiters.values():
            w["event"].set()
        try:
            self.sock.close()
        except OSError:
            pass

    def request(self, from_id: str, action: str, payload: Any,
                timeout: float = 60.0) -> tuple[int, bytes]:
        data, cflag = _encode_payload(payload)
        with self._lock:
            self._next_id += 1
            req_id = self._next_id
            w = {"event": threading.Event(), "status": None, "data": b""}
            self._waiters[req_id] = w
        frame = (_HDR.pack(MAGIC, WIRE_VERSION, cflag, req_id, len(data))
                 + _pack_str(from_id) + _pack_str(action) + data)
        try:
            with self._send_lock:
                self.sock.sendall(frame)
        except OSError as e:
            self._fail_all()
            raise ConnectionError(str(e)) from e
        if not w["event"].wait(timeout):
            with self._lock:
                self._waiters.pop(req_id, None)
            raise ConnectionError(f"timeout waiting for [{action}]")
        if self.broken and w["status"] is None:
            raise ConnectionError("connection reset mid-request")
        return w["status"], w["data"]

    def close(self) -> None:
        self._fail_all()


class TcpTransport:
    """The socket 'network'. One instance per process; each registered
    TransportService gets its own listening socket, so even same-process
    node pairs exchange real frames over loopback."""

    def __init__(self, host: str = "127.0.0.1",
                 seeds: list[tuple[str, int]] | None = None,
                 dispatcher=None):
        self.host = host
        self._lock = threading.RLock()
        self._local: dict[str, dict] = {}        # node_id -> {service, srv,
                                                 #   port, threads}
        self._addrs: dict[str, tuple[str, int]] = {}
        self._conns: dict[tuple[str, str], _Connection] = {}
        self._disconnected: set[tuple[str | None, str]] = set()
        # fault-seam parity with LocalTransport (ISSUE 14): action-prefix
        # drop rules, delivery delays, and the injected-faults counter, so
        # the chaos scheme drives the TCP cluster with the same API
        self._drop_rules: set[tuple[str | None, str, str]] = set()
        self._delays: dict[tuple[str, str], float] = {}
        self.faults_injected = 0
        self._seeds = list(seeds or [])
        # optional bounded executor for inbound dispatch (common.threadpool);
        # None = thread-per-request
        self._dispatcher = dispatcher
        self.messages_sent = 0
        self.bytes_sent = 0
        self.max_message_bytes = 0
        self.closed = False

    # -- LocalTransport surface -------------------------------------------

    def register(self, service) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, 0))
        srv.listen(64)
        port = srv.getsockname()[1]
        with self._lock:
            self._local[service.node_id] = {"service": service, "srv": srv,
                                            "port": port}
            self._addrs[service.node_id] = (self.host, port)
        t = threading.Thread(target=self._accept_loop,
                             args=(service.node_id, srv), daemon=True)
        t.start()
        # seed-list handshake: learn the seeds' node ids + their peers
        for addr in self._seeds:
            try:
                self._handshake(service.node_id, addr)
            except (OSError, ConnectionError, TransportException):
                pass                      # dead seed — zen ping tolerates

    def unregister(self, node_id: str) -> None:
        with self._lock:
            ent = self._local.pop(node_id, None)
            self._addrs.pop(node_id, None)
            conns = [c for (frm, _), c in self._conns.items() if frm == node_id]
            for key in [k for k in self._conns if k[0] == node_id]:
                self._conns.pop(key)
        if ent:
            try:
                ent["srv"].close()
            except OSError:
                pass
        for c in conns:
            c.close()

    def connected_nodes(self) -> list[str]:
        with self._lock:
            return sorted(set(self._local) | set(self._addrs))

    def address_of(self, node_id: str) -> tuple[str, int] | None:
        with self._lock:
            return self._addrs.get(node_id)

    # -- fault injection (parity with LocalTransport) ---------------------

    def disconnect(self, node_id: str, from_id: str | None = None) -> None:
        with self._lock:
            self._disconnected.add((from_id, node_id))

    def reconnect(self, node_id: str, from_id: str | None = None) -> None:
        with self._lock:
            self._disconnected.discard((from_id, node_id))

    def partition(self, side_a: list[str], side_b: list[str]) -> None:
        with self._lock:
            for a in side_a:
                for b in side_b:
                    self._disconnected.add((a, b))
                    self._disconnected.add((b, a))

    def heal(self) -> None:
        with self._lock:
            self._disconnected.clear()
            self._drop_rules.clear()
            self._delays.clear()

    def add_rule(self, node_id: str, action_prefix: str = "",
                 from_id: str | None = None) -> None:
        """Drop messages TO node_id whose action starts with action_prefix
        (same contract as LocalTransport.add_rule — a scoped kill that
        leaves the rest of the link healthy)."""
        with self._lock:
            self._drop_rules.add((from_id, node_id, action_prefix))

    def clear_rule(self, node_id: str, action_prefix: str = "",
                   from_id: str | None = None) -> None:
        with self._lock:
            self._drop_rules.discard((from_id, node_id, action_prefix))

    def clear_rules(self) -> None:
        with self._lock:
            self._drop_rules.clear()

    def _rule_dropped(self, from_id: str, to_id: str, action: str) -> bool:
        # caller holds the lock
        if not self._drop_rules:
            return False
        return any(nid == to_id and (frm is None or frm == from_id)
                   and action.startswith(pfx)
                   for frm, nid, pfx in self._drop_rules)

    def add_delay(self, node_id: str, action_prefix: str,
                  seconds: float) -> None:
        """Inject delivery latency into every message TO node_id whose
        action starts with action_prefix (slow-replica injection over the
        real wire — applied client-side, before the frame is sent)."""
        with self._lock:
            self._delays[(node_id, action_prefix)] = float(seconds)

    def clear_delay(self, node_id: str, action_prefix: str) -> None:
        with self._lock:
            self._delays.pop((node_id, action_prefix), None)

    def _delay_of(self, to_id: str, action: str) -> float:
        with self._lock:
            if not self._delays:
                return 0.0
            return max((s for (nid, pfx), s in self._delays.items()
                        if nid == to_id and action.startswith(pfx)),
                       default=0.0)

    def fault_stats(self) -> dict:
        with self._lock:
            return {"faults_injected_total": self.faults_injected,
                    "disconnected_links": len(self._disconnected),
                    "drop_rules": len(self._drop_rules),
                    "delay_rules": len(self._delays)}

    # -- server side -------------------------------------------------------

    def _accept_loop(self, node_id: str, srv: socket.socket) -> None:
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return                    # server socket closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn,
                             args=(node_id, conn), daemon=True).start()

    def _serve_conn(self, node_id: str, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        try:
            while True:
                magic, ver, status, req_id, length = _HDR.unpack(
                    _read_exact(conn, _HDR.size))
                if magic != MAGIC:
                    raise ConnectionError(f"bad magic {magic!r}")
                if ver != WIRE_VERSION:
                    # answer with a versioned error, then drop the connection
                    self._respond(conn, send_lock, req_id, ST_ERROR, {
                        "error_type": "IllegalStateException",
                        "message": f"wire version mismatch "
                                   f"(got {ver}, want {WIRE_VERSION})"})
                    raise ConnectionError("wire version mismatch")
                flen = struct.unpack(">H", _read_exact(conn, 2))[0]
                from_id = _read_exact(conn, flen).decode("utf-8")
                alen = struct.unpack(">H", _read_exact(conn, 2))[0]
                action = _read_exact(conn, alen).decode("utf-8")
                data = _read_exact(conn, length) if length else b""

                def run(req_id=req_id, status=status, from_id=from_id,
                        action=action, data=data):
                    self._dispatch(node_id, conn, send_lock, req_id,
                                   status, from_id, action, data)
                if self._dispatcher is not None:
                    self._dispatcher(run)
                else:
                    threading.Thread(target=run, daemon=True).start()
        except (ConnectionError, OSError):
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, node_id: str, conn: socket.socket, send_lock,
                  req_id: int, status: int, from_id: str, action: str,
                  data: bytes) -> None:
        try:
            payload = _decode_payload(data, status)
            with self._lock:
                ent = self._local.get(node_id)
                blocked = ((from_id, node_id) in self._disconnected
                           or (None, node_id) in self._disconnected
                           or self._rule_dropped(from_id, node_id, action))
                if blocked:
                    self.faults_injected += 1
            if ent is None or blocked:
                raise ConnectTransportException(node_id, action)
            if action == A_HANDSHAKE:
                resp = self._on_handshake(node_id, payload)
            else:
                resp = ent["service"]._handle(from_id, action, payload)
            self._respond(conn, send_lock, req_id, ST_RESPONSE, resp)
        except Exception as e:  # noqa: BLE001 — serialize like a real wire
            err = {"error_type": type(e).__name__, "message": str(e),
                   "node_id": node_id, "action": action}
            if isinstance(e, RemoteTransportException):
                err["error_type"] = e.error_type
                err["message"] = e.error_message
            try:
                self._respond(conn, send_lock, req_id,
                              ST_RESPONSE | ST_ERROR, err)
            except (ConnectionError, OSError):
                pass

    def _respond(self, conn: socket.socket, send_lock, req_id: int,
                 status: int, payload: Any) -> None:
        data, cflag = _encode_payload(payload)
        frame = _HDR.pack(MAGIC, WIRE_VERSION, status | cflag, req_id,
                          len(data)) + data
        with send_lock:
            conn.sendall(frame)
        with self._lock:
            self.bytes_sent += len(frame)
            self.max_message_bytes = max(self.max_message_bytes, len(frame))

    # -- handshake / address gossip ---------------------------------------

    def _on_handshake(self, node_id: str, payload: Any) -> dict:
        """Exchange node_id + known peer addresses (unicast zen ping)."""
        if isinstance(payload, dict):
            peer_id = payload.get("node_id")
            addr = payload.get("address")
            with self._lock:
                if peer_id and addr and peer_id not in self._local:
                    self._addrs[peer_id] = (addr[0], int(addr[1]))
        with self._lock:
            known = {nid: list(a) for nid, a in self._addrs.items()}
        return {"node_id": node_id, "peers": known}

    def _handshake(self, from_id: str, addr: tuple[str, int]) -> str:
        """Dial a seed address, learn its node id and peer map."""
        conn = _Connection(addr)
        try:
            my_addr = self.address_of(from_id)
            status, data = conn.request(
                from_id, A_HANDSHAKE,
                {"node_id": from_id,
                 "address": list(my_addr) if my_addr else None})
            resp = _decode_payload(data, status)
            if status & ST_ERROR:
                raise TransportException(resp.get("message", "handshake"))
            with self._lock:
                for nid, a in (resp.get("peers") or {}).items():
                    if nid not in self._local:
                        self._addrs[nid] = (a[0], int(a[1]))
                self._addrs[resp["node_id"]] = addr
            return resp["node_id"]
        finally:
            conn.close()

    def ping_seeds(self, from_id: str) -> list[str]:
        """Re-run the seed handshake; -> discovered node ids (ref unicast
        zen ping round)."""
        found = []
        for addr in self._seeds:
            try:
                found.append(self._handshake(from_id, addr))
            except (OSError, ConnectionError, TransportException):
                pass
        return found

    # -- client side -------------------------------------------------------

    def _conn_for(self, from_id: str, to_id: str) -> _Connection:
        key = (from_id, to_id)
        with self._lock:
            c = self._conns.get(key)
            addr = self._addrs.get(to_id)
        if c is not None and not c.broken:
            return c
        if addr is None:
            raise ConnectTransportException(to_id)
        try:
            c = _Connection(addr)
        except OSError as e:
            raise ConnectTransportException(to_id) from e
        with self._lock:
            old = self._conns.get(key)
            if old is not None and not old.broken:
                c.close()
                return old
            self._conns[key] = c
        return c

    def deliver(self, from_id: str, to_id: str, action: str,
                payload: Any) -> Any:
        with self._lock:
            blocked = ((from_id, to_id) in self._disconnected
                       or (None, to_id) in self._disconnected
                       or self._rule_dropped(from_id, to_id, action))
            if blocked:
                self.faults_injected += 1
        if blocked:
            raise ConnectTransportException(to_id, action)
        delay = self._delay_of(to_id, action)
        if delay > 0:
            with self._lock:
                self.faults_injected += 1
            import time as _time
            _time.sleep(delay)
        try:
            conn = self._conn_for(from_id, to_id)
            status, data = conn.request(from_id, action, payload)
        except (ConnectionError, OSError) as e:
            raise ConnectTransportException(to_id, action) from e
        with self._lock:
            self.messages_sent += 1
            self.bytes_sent += len(data) + _HDR.size
            self.max_message_bytes = max(self.max_message_bytes,
                                         len(data) + _HDR.size)
        resp = _decode_payload(data, status)
        if status & ST_ERROR:
            etype = resp.get("error_type", "Exception")
            if etype == "ConnectTransportException":
                raise ConnectTransportException(to_id, action)
            if etype == "ActionNotFoundTransportException":
                raise ActionNotFoundTransportException(resp.get("message"))
            raise RemoteTransportException(
                resp.get("node_id", to_id), action, etype,
                resp.get("message", ""))
        return resp

    def close(self) -> None:
        with self._lock:
            self.closed = True
            locals_, self._local = dict(self._local), {}
            conns, self._conns = list(self._conns.values()), {}
        for ent in locals_.values():
            try:
                ent["srv"].close()
            except OSError:
                pass
        for c in conns:
            c.close()
