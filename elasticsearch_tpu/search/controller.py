"""Search-phase controller: the cross-shard reduce for the 2-phase protocol.

Analog of /root/reference/src/main/java/org/elasticsearch/search/controller/
SearchPhaseController.java — sortDocs (:147,233) merges per-shard top-k,
merge (:282-399) combines hits + aggregation reduce into the final response.

On a packed mesh the same reduce runs on-device as collectives
(parallel/distributed_search.py); this host-side controller serves the
engine-per-shard path (local multi-shard node, and later the DCN
coordinator between pods).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..common.metrics import current_profiler
from . import sort as sort_mod
from .shard_searcher import QuerySearchResult, ShardSearcher, FetchedHit


@dataclass
class ReducedDocs:
    """Winner list after the query-phase reduce: which docs to fetch where."""
    shard_order: list[int]          # shard id per result slot (len <= size)
    doc_keys: list[int]             # doc key per result slot
    scores: list[float]
    sort_values: list[list] | None  # materialized per-key values per slot
    total_hits: int
    max_score: float


def sort_docs(results: list[QuerySearchResult], *, from_: int, size: int,
              sort=None, query_row: int = 0) -> ReducedDocs:
    """Merge per-shard top-k into the global winner list
    (ref SearchPhaseController.sortDocs — TopDocs.merge semantics: score
    desc / sort-key asc, shard index breaks ties like the reference's
    shard-ordinal tie-break). Field sorts compare MATERIALIZED values
    (strings/numbers), never ordinals — see search/sort.py."""
    t0 = time.perf_counter()
    from ..common.metrics import record_host_merge
    record_host_merge()
    sort = sort_mod.normalize(sort)
    entries = []   # (primary_key, shard_idx, pos, doc_key, score, sort_val)
    total = 0
    max_score = float("-inf")
    for si, r in enumerate(results):
        total += int(r.total_hits[query_row])
        ms = float(r.max_score[query_row])
        if not np.isnan(ms):
            max_score = max(max_score, ms)
        keys = r.doc_keys[query_row]
        for pos in range(keys.shape[0]):
            key = int(keys[pos])
            if key < 0:
                continue
            score = float(r.scores[query_row][pos])
            if sort is None:
                primary = -score if not np.isnan(score) else float("inf")
                sv = None
            else:
                sv = r.sort_values[query_row][pos]
                primary = sort_mod.compare_key(sv, sort)
            entries.append((primary, si, pos, key, score, sv))
    entries.sort(key=lambda e: (e[0], e[1], e[2]))
    window = entries[from_: from_ + size]
    prof = current_profiler()
    if prof is not None:
        prof.record_phase("reduce", (time.perf_counter() - t0) * 1000)
    return ReducedDocs(
        shard_order=[e[1] for e in window],
        doc_keys=[e[3] for e in window],
        scores=[e[4] for e in window],
        sort_values=[e[5] for e in window] if sort is not None else None,
        total_hits=total,
        max_score=max_score if max_score > float("-inf") else float("nan"))


def fetch_and_merge(reduced: ReducedDocs, searchers: list[ShardSearcher],
                    source_filter=None, fields_spec=None) -> list[dict]:
    """Fetch phase fan-out to winning shards only + final hit assembly
    (ref FetchPhase + SearchPhaseController.merge). `searchers` is aligned
    with the results list passed to sort_docs."""
    t0 = time.perf_counter()
    # group result slots by shard (the docIdsToLoad structure)
    by_shard: dict[int, list[int]] = {}
    for slot, si in enumerate(reduced.shard_order):
        by_shard.setdefault(si, []).append(slot)
    hits_by_slot: dict[int, FetchedHit] = {}
    for si, slots in by_shard.items():
        keys = [reduced.doc_keys[s] for s in slots]
        scores = np.asarray([reduced.scores[s] for s in slots], np.float32)
        svs = [reduced.sort_values[s] for s in slots] \
            if reduced.sort_values is not None else None
        fetched = searchers[si].execute_fetch_phase(keys, scores, svs)
        for slot, hit in zip(slots, fetched):
            hits_by_slot[slot] = hit
    out = []
    for slot in range(len(reduced.doc_keys)):
        h = hits_by_slot[slot]
        src = h.source
        if source_filter is not None:
            src = source_filter(src)
        entry = {
            "_index": None,   # filled by the caller
            "_type": h.type_name,
            "_id": h.doc_id,
            "_score": None if np.isnan(h.score) else float(h.score),
        }
        if fields_spec is not None:
            # body `fields`: dot-path extraction from source, values as
            # lists; _source omitted unless listed (ref
            # search/fetch/fieldvisitor + FetchPhase stored-fields contract)
            flds = {}
            for f in fields_spec:
                if f == "_source":
                    continue
                v = _path_get(h.source, f)
                if v is not None:
                    flds[f] = v if isinstance(v, list) else [v]
            if flds:
                entry["fields"] = flds
            if "_source" not in fields_spec:
                src = None
        if src is not None:     # None = `_source: false` (key omitted)
            entry["_source"] = src
        if reduced.sort_values is not None:
            entry["sort"] = h.sort_value
        out.append(entry)
    prof = current_profiler()
    if prof is not None:
        prof.record_phase("fetch", (time.perf_counter() - t0) * 1000)
    return out


def _path_get(obj, path: str):
    for part in path.split("."):
        if not isinstance(obj, dict) or part not in obj:
            return None
        obj = obj[part]
    return obj
