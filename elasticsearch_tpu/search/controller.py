"""Search-phase controller: the cross-shard reduce for the 2-phase protocol.

Analog of /root/reference/src/main/java/org/elasticsearch/search/controller/
SearchPhaseController.java — sortDocs (:147,233) merges per-shard top-k,
merge (:282-399) combines hits + aggregation reduce into the final response.

On a packed mesh the same reduce runs on-device as collectives
(parallel/distributed_search.py); this host-side controller serves the
engine-per-shard path (local multi-shard node, and later the DCN
coordinator between pods).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..common.metrics import current_profiler
from . import sort as sort_mod
from .shard_searcher import QuerySearchResult, ShardSearcher, FetchedHit


@dataclass
class ReducedDocs:
    """Winner list after the query-phase reduce: which docs to fetch where."""
    shard_order: list[int]          # shard id per result slot (len <= size)
    doc_keys: list[int]             # doc key per result slot
    scores: list[float]
    sort_values: list[list] | None  # materialized per-key values per slot
    total_hits: int
    max_score: float


def sort_docs(results: list[QuerySearchResult], *, from_: int, size: int,
              sort=None, query_row: int = 0) -> ReducedDocs:
    """Merge per-shard top-k into the global winner list
    (ref SearchPhaseController.sortDocs — TopDocs.merge semantics: score
    desc / sort-key asc, shard index breaks ties like the reference's
    shard-ordinal tie-break). Field sorts compare MATERIALIZED values
    (strings/numbers), never ordinals — see search/sort.py."""
    t0 = time.perf_counter()
    from ..common.device_stats import lane_chosen
    from ..common.metrics import record_host_merge
    record_host_merge()
    # the fan-out's coordinator-side reduce: when the mesh lane serves, no
    # host merge runs at all — this note marks which reduce path the
    # request actually rode
    lane_chosen("reduce", "host_merge")
    sort = sort_mod.normalize(sort)
    entries = []   # (primary_key, shard_idx, pos, doc_key, score, sort_val)
    total = 0
    max_score = float("-inf")
    for si, r in enumerate(results):
        total += int(r.total_hits[query_row])
        ms = float(r.max_score[query_row])
        if not np.isnan(ms):
            max_score = max(max_score, ms)
        keys = r.doc_keys[query_row]
        for pos in range(keys.shape[0]):
            key = int(keys[pos])
            if key < 0:
                continue
            score = float(r.scores[query_row][pos])
            if sort is None:
                primary = -score if not np.isnan(score) else float("inf")
                sv = None
            else:
                sv = r.sort_values[query_row][pos]
                primary = sort_mod.compare_key(sv, sort)
            entries.append((primary, si, pos, key, score, sv))
    entries.sort(key=lambda e: (e[0], e[1], e[2]))
    window = entries[from_: from_ + size]
    prof = current_profiler()
    if prof is not None:
        prof.record_phase("reduce", (time.perf_counter() - t0) * 1000)
    return ReducedDocs(
        shard_order=[e[1] for e in window],
        doc_keys=[e[3] for e in window],
        scores=[e[4] for e in window],
        sort_values=[e[5] for e in window] if sort is not None else None,
        total_hits=total,
        max_score=max_score if max_score > float("-inf") else float("nan"))


def fuse_hybrid(text_results: list[QuerySearchResult],
                knn_results: list[QuerySearchResult], spec, *,
                from_: int, size: int, query_row: int = 0) -> ReducedDocs:
    """First-class BM25 + vector fusion (the body's `"rank"` section,
    search/query_parser.RankSpec): each retriever's per-shard lists merge
    into a GLOBAL ranked list first (sort_docs — RRF ranks are global, as
    in the reference's coordinator-level RRF), then the two lists fuse on
    device (ops/ann.rrf_fuse / weighted_fuse) over compact candidate ids
    and the winners come back as an ordinary ReducedDocs."""
    import numpy as _np
    import jax.numpy as _jnp

    from ..ops import ann as ann_ops

    def width(results):
        return sum(r.doc_keys.shape[1] for r in results) or 1

    text_red = sort_docs(text_results, from_=0, size=width(text_results),
                         query_row=query_row)
    knn_red = sort_docs(knn_results, from_=0, size=width(knn_results),
                        query_row=query_row)
    # compact (shard, doc_key) -> small int ids so the device kernel
    # matches candidates with an exact integer-equality plane
    id_of: dict[tuple[int, int], int] = {}

    def ids_for(red):
        return [id_of.setdefault((si, dk), len(id_of))
                for si, dk in zip(red.shard_order, red.doc_keys)]

    ids_a, ids_b = ids_for(text_red), ids_for(knn_red)
    rev = {v: k for k, v in id_of.items()}
    Ka, Kb = max(len(ids_a), 1), max(len(ids_b), 1)
    keys_a = _np.full((1, Ka), -1, _np.int64)
    keys_a[0, : len(ids_a)] = ids_a
    keys_b = _np.full((1, Kb), -1, _np.int64)
    keys_b[0, : len(ids_b)] = ids_b
    w = _jnp.asarray([spec.query_weight, spec.knn_weight], _jnp.float32)
    k = max(from_ + size, 1)
    if spec.mode == "rrf":
        top, keys = ann_ops.rrf_fuse(
            _jnp.asarray(keys_a), _jnp.asarray(keys_b), w,
            _jnp.float32(spec.rank_constant), k=k)
    else:
        sc_a = _np.full((1, Ka), -_np.inf, _np.float32)
        sc_a[0, : len(ids_a)] = _np.nan_to_num(
            _np.asarray(text_red.scores, _np.float32))
        sc_b = _np.full((1, Kb), -_np.inf, _np.float32)
        sc_b[0, : len(ids_b)] = _np.nan_to_num(
            _np.asarray(knn_red.scores, _np.float32))
        top, keys = ann_ops.weighted_fuse(
            _jnp.asarray(keys_a), _jnp.asarray(sc_a),
            _jnp.asarray(keys_b), _jnp.asarray(sc_b), w, k=k,
            normalize=spec.normalize)
    top = _np.asarray(top)[0]
    keys = _np.asarray(keys)[0]
    slots = [(rev[int(kid)], float(s))
             for s, kid in zip(top, keys)
             if _np.isfinite(s) and kid >= 0][from_: from_ + size]
    return ReducedDocs(
        shard_order=[sh for (sh, _dk), _s in slots],
        doc_keys=[dk for (_sh, dk), _s in slots],
        scores=[s for _key, s in slots],
        sort_values=None,
        total_hits=max(text_red.total_hits, knn_red.total_hits),
        max_score=slots[0][1] if slots else float("nan"))


def fetch_and_merge(reduced: ReducedDocs, searchers: list[ShardSearcher],
                    source_filter=None, fields_spec=None) -> list[dict]:
    """Fetch phase fan-out to winning shards only + final hit assembly
    (ref FetchPhase + SearchPhaseController.merge). `searchers` is aligned
    with the results list passed to sort_docs."""
    t0 = time.perf_counter()
    # group result slots by shard (the docIdsToLoad structure)
    by_shard: dict[int, list[int]] = {}
    for slot, si in enumerate(reduced.shard_order):
        by_shard.setdefault(si, []).append(slot)
    hits_by_slot: dict[int, FetchedHit] = {}
    for si, slots in by_shard.items():
        keys = [reduced.doc_keys[s] for s in slots]
        scores = np.asarray([reduced.scores[s] for s in slots], np.float32)
        svs = [reduced.sort_values[s] for s in slots] \
            if reduced.sort_values is not None else None
        fetched = searchers[si].execute_fetch_phase(keys, scores, svs)
        for slot, hit in zip(slots, fetched):
            hits_by_slot[slot] = hit
    out = []
    for slot in range(len(reduced.doc_keys)):
        h = hits_by_slot[slot]
        src = h.source
        if source_filter is not None:
            src = source_filter(src)
        entry = {
            "_index": None,   # filled by the caller
            "_type": h.type_name,
            "_id": h.doc_id,
            "_score": None if np.isnan(h.score) else float(h.score),
        }
        if fields_spec is not None:
            # body `fields`: dot-path extraction from source, values as
            # lists; _source omitted unless listed (ref
            # search/fetch/fieldvisitor + FetchPhase stored-fields contract)
            flds = {}
            for f in fields_spec:
                if f == "_source":
                    continue
                v = _path_get(h.source, f)
                if v is not None:
                    flds[f] = v if isinstance(v, list) else [v]
            if flds:
                entry["fields"] = flds
            if "_source" not in fields_spec:
                src = None
        if src is not None:     # None = `_source: false` (key omitted)
            entry["_source"] = src
        if reduced.sort_values is not None:
            entry["sort"] = h.sort_value
        out.append(entry)
    prof = current_profiler()
    if prof is not None:
        prof.record_phase("fetch", (time.perf_counter() - t0) * 1000)
    return out


def _path_get(obj, path: str):
    for part in path.split("."):
        if not isinstance(obj, dict) or part not in obj:
            return None
        obj = obj[part]
    return obj
