"""Query DSL JSON → Node AST, plus batch merging of same-shape queries.

The analog of the reference's *QueryParser classes + IndexQueryParserService
(/root/reference/src/main/java/org/elasticsearch/index/query/IndexQueryParserService.java).
Each query body parses to a Q=1 tree; `merge_query_batch` fuses trees with an
identical plan shape into one tree with Q rows so the whole batch compiles to
a single device program (this batching is where the TPU QPS win comes from,
SURVEY.md §7).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import re
from dataclasses import dataclass
from typing import Any

from ..mapping.mapper import MapperService, DATE, KEYWORD, TEXT, parse_date_millis
from .query_dsl import (
    BoolNode, BoostingNode, CommonTermsNode, ConstantScoreNode, DisMaxNode,
    ExistsNode, FunctionScoreNode, GeoDistanceNode, HasChildNode,
    HasParentNode, IdsNode, MatchAllNode, MatchNode, MatchNoneNode,
    NestedNode, Node, QueryParsingException, RangeNode,
    SpanFirstNode, SpanNearNode, TermFilterNode,
)

# shared geo vocabulary lives in search/geo.py (re-exported here for the
# sort module and external callers)
from .geo import parse_distance, parse_geo_point  # noqa: E402,F401

_DATE_MATH_RE = re.compile(
    r"^now(?P<ops>([+-]\d+[yMwdhHms])*)(?:/(?P<round>[yMwdhHms]))?$")
_UNIT_MILLIS = {"s": 1000, "m": 60_000, "h": 3_600_000, "H": 3_600_000,
                "d": 86_400_000, "w": 604_800_000}


def eval_date_math(expr: str, now_millis: int | None = None) -> int:
    """'now-7d/d' style date math (ref common/joda DateMathParser)."""
    if now_millis is None:
        now_millis = int(_dt.datetime.now(_dt.timezone.utc).timestamp() * 1000)
    m = _DATE_MATH_RE.match(expr.strip())
    if not m:
        return parse_date_millis(expr)
    t = now_millis
    ops = m.group("ops") or ""
    for om in re.finditer(r"([+-])(\d+)([yMwdhHms])", ops):
        sign = 1 if om.group(1) == "+" else -1
        n = int(om.group(2))
        unit = om.group(3)
        if unit == "y":
            delta = n * 365 * 86_400_000
        elif unit == "M":
            delta = n * 30 * 86_400_000
        else:
            delta = n * _UNIT_MILLIS[unit]
        t += sign * delta
    rnd = m.group("round")
    if rnd:
        dt = _dt.datetime.fromtimestamp(t / 1000.0, tz=_dt.timezone.utc)
        if rnd == "y":
            dt = dt.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
        elif rnd == "M":
            dt = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        elif rnd in ("d", "w"):
            dt = dt.replace(hour=0, minute=0, second=0, microsecond=0)
        elif rnd in ("h", "H"):
            dt = dt.replace(minute=0, second=0, microsecond=0)
        elif rnd == "m":
            dt = dt.replace(second=0, microsecond=0)
        elif rnd == "s":
            dt = dt.replace(microsecond=0)
        t = int(dt.timestamp() * 1000)
    return t


class QueryParser:
    """Parses one query body dict into a Node tree (Q=1)."""

    def __init__(self, mappers: MapperService):
        self.mappers = mappers

    def _sim_kw(self, field: str) -> dict:
        """Per-field similarity knobs for scored text nodes (the mapping's
        "similarity" resolved through the index's SimilarityService, which
        IndexService attaches to the MapperService; ref index/similarity/
        SimilarityService.java:36)."""
        svc = getattr(self.mappers, "similarity", None)
        if svc is None:
            return {}
        from ..index.similarity import sim_tag
        sim = svc.for_field(self.mappers, field)
        return {"sim": sim_tag(sim), "k1": sim.k1, "b": sim.b,
                "mu": sim.mu, "lam": sim.lam}

    def parse(self, body: dict | None) -> Node:
        if body is None or body == {}:
            return MatchAllNode()
        if not isinstance(body, dict) or len(body) != 1:
            raise QueryParsingException(f"query must have a single root key, got {body!r}")
        (kind, spec), = body.items()
        handler = getattr(self, f"_parse_{kind}", None)
        if handler is None:
            raise QueryParsingException(f"unsupported query type [{kind}]")
        return handler(spec)

    # -- leaf parsers ------------------------------------------------------

    def _analyze(self, field: str, text: Any) -> list[str]:
        ft = self.mappers.field_type(field)
        if ft is not None and ft.type != TEXT:
            return [str(text)]
        # use the field's search analyzer; unmapped fields use standard
        for m in self.mappers._mappers.values():
            if field in m.fields:
                return m.search_analyzer_for(field)(str(text))
        from ..analysis.analyzers import BUILTIN_ANALYZERS
        return BUILTIN_ANALYZERS["standard"](str(text))

    def _parse_match_all(self, spec) -> Node:
        return MatchAllNode(boost=float((spec or {}).get("boost", 1.0)))

    def _parse_match_none(self, spec) -> Node:
        return MatchNoneNode()

    def _parse_match(self, spec: dict) -> Node:
        (field, params), = spec.items()
        if not isinstance(params, dict):
            params = {"query": params}
        if params.get("type") in ("phrase", "phrase_prefix"):
            # ES 2.x match { type: phrase } form (MatchQueryParser.java)
            return self._phrase_node(field, params,
                                     prefix=params["type"] == "phrase_prefix")
        terms = self._analyze(field, params["query"])
        if not terms:
            return MatchNoneNode()
        msm = _parse_msm(params.get("minimum_should_match"), len(terms))
        return MatchNode(
            boost=float(params.get("boost", 1.0)), field_name=field,
            terms_per_query=[terms],
            operator=str(params.get("operator", "or")).lower(),
            minimum_should_match=msm, **self._sim_kw(field))

    def _parse_match_phrase(self, spec: dict) -> Node:
        (field, params), = spec.items()
        if not isinstance(params, dict):
            params = {"query": params}
        return self._phrase_node(field, params)

    def _parse_match_phrase_prefix(self, spec: dict) -> Node:
        (field, params), = spec.items()
        if not isinstance(params, dict):
            params = {"query": params}
        return self._phrase_node(field, params, prefix=True)

    def _phrase_node(self, field: str, params: dict, prefix: bool = False) -> Node:
        terms = self._analyze(field, params["query"])
        if not terms:
            return MatchNoneNode()
        from .query_dsl import PhraseNode, _POS_BIAS
        if len(terms) >= _POS_BIAS:
            raise QueryParsingException(
                f"match_phrase supports at most {_POS_BIAS - 1} terms")
        return PhraseNode(
            field_name=field, terms_per_query=[terms],
            slop=int(params.get("slop", 0)),
            boost=float(params.get("boost", 1.0)),
            last_prefix=prefix,
            max_expansions=int(params.get("max_expansions", 50)))

    def _parse_multi_match(self, spec: dict) -> Node:
        fields = spec.get("fields", [])
        text = spec["query"]
        mm_type = spec.get("type", "best_fields")
        subs: list[Node] = []
        for f in fields:
            boost = 1.0
            if "^" in f:
                f, b = f.split("^", 1)
                boost = float(b)
            terms = self._analyze(f, text)
            if terms:
                subs.append(MatchNode(field_name=f, terms_per_query=[terms],
                                      boost=boost, **self._sim_kw(f)))
        if not subs:
            return MatchNoneNode()
        if mm_type == "most_fields":
            return BoolNode(should=subs)
        return DisMaxNode(queries=subs, tie_breaker=float(spec.get("tie_breaker", 0.0)))

    def _parse_term(self, spec: dict) -> Node:
        (field, params), = spec.items()
        value = params.get("value") if isinstance(params, dict) else params
        boost = float(params.get("boost", 1.0)) if isinstance(params, dict) else 1.0
        return self._term_node(field, [value], boost)

    def _parse_terms(self, spec: dict) -> Node:
        spec = dict(spec)
        spec.pop("minimum_should_match", None)
        boost = float(spec.pop("boost", 1.0))
        (field, values), = spec.items()
        return self._term_node(field, list(values), boost)

    def _term_node(self, field: str, values: list, boost: float) -> Node:
        if field in ("_id", "_uid"):
            # metadata-field term query == ids query (ref IdFieldMapper
            # termQuery delegating to the _uid lookup)
            return IdsNode(
                ids_per_query=[[str(v).split("#", 1)[-1] for v in values]],
                boost=boost)
        ft = self.mappers.field_type(field)
        if ft is not None and ft.type == DATE:
            values = [eval_date_math(str(v)) if isinstance(v, str) else v for v in values]
        if ft is not None and ft.type == TEXT:
            # term query on an analyzed field matches the exact token
            return ConstantScoreNode(
                boost=boost,
                inner=MatchNode(field_name=field,
                                terms_per_query=[[str(v) for v in values]]))
        return TermFilterNode(field_name=field, values_per_query=[values], boost=boost)

    def _parse_range(self, spec: dict) -> Node:
        (field, params), = spec.items()
        lo = params.get("gte", params.get("from"))
        hi = params.get("lte", params.get("to"))
        inc_lo, inc_hi = True, True
        if "gt" in params:
            lo, inc_lo = params["gt"], False
        if "lt" in params:
            hi, inc_hi = params["lt"], False
        ft = self.mappers.field_type(field)
        is_date = ft is not None and ft.type == DATE
        if is_date:
            lo = eval_date_math(str(lo)) if lo is not None else None
            hi = eval_date_math(str(hi)) if hi is not None else None
        return RangeNode(field_name=field, bounds_per_query=[(lo, hi, inc_lo, inc_hi)],
                         is_date=is_date, boost=float(params.get("boost", 1.0)))

    def _span_clause(self, clause: dict) -> tuple[str, list[str]]:
        """-> (field, OR-terms) from a span_term / span_or clause
        (ref SpanTermQueryParser, SpanOrQueryParser)."""
        (kind, spec), = clause.items()
        if kind == "span_term":
            (field, params), = spec.items()
            value = params.get("value") if isinstance(params, dict) \
                else params
            return field, [str(value)]
        if kind == "span_or":
            fields = set()
            terms: list[str] = []
            for sub in spec.get("clauses", []):
                f, ts = self._span_clause(sub)
                fields.add(f)
                terms += ts
            if len(fields) != 1:
                raise QueryParsingException(
                    "span_or clauses must target one field")
            return fields.pop(), terms
        raise QueryParsingException(
            f"unsupported span clause [{kind}] (span_term/span_or only)")

    def _parse_span_term(self, spec: dict) -> Node:
        field, terms = self._span_clause({"span_term": spec})
        return SpanNearNode(field_name=field, clause_terms=[terms],
                            slop=0, **self._sim_kw(field))

    def _parse_span_or(self, spec: dict) -> Node:
        field, terms = self._span_clause({"span_or": spec})
        return SpanNearNode(field_name=field, clause_terms=[terms],
                            slop=0, **self._sim_kw(field))

    def _parse_span_near(self, spec: dict) -> Node:
        clauses = [self._span_clause(c) for c in spec.get("clauses", [])]
        if not clauses:
            raise QueryParsingException("span_near requires clauses")
        fields = {f for f, _ in clauses}
        if len(fields) != 1:
            raise QueryParsingException(
                "span_near clauses must target one field")
        field = fields.pop()
        return SpanNearNode(
            field_name=field, clause_terms=[ts for _, ts in clauses],
            slop=int(spec.get("slop", 0)),
            in_order=bool(spec.get("in_order", True)),
            boost=float(spec.get("boost", 1.0)), **self._sim_kw(field))

    def _parse_span_first(self, spec: dict) -> Node:
        field, terms = self._span_clause(spec["match"])
        return SpanFirstNode(field_name=field, terms=terms,
                             end=int(spec.get("end", 1)),
                             boost=float(spec.get("boost", 1.0)),
                             **self._sim_kw(field))

    def _parse_span_not(self, spec: dict) -> Node:
        """span_not (ref SpanNotQueryParser): include-spans minus docs where
        the exclude span matches. DOC-level subtraction — the reference
        subtracts only OVERLAPPING spans; for the common single-occurrence
        case the two agree, and the deviation is documented here."""
        inc = self.parse(spec["include"])
        exc = self.parse(spec["exclude"])
        from .query_dsl import BoolNode
        return BoolNode(must=[inc], must_not=[exc],
                        boost=float(spec.get("boost", 1.0)))

    def _parse_span_multi(self, spec: dict) -> Node:
        """span_multi (ref SpanMultiTermQueryParser): a multi-term query
        (prefix/wildcard/fuzzy/regexp/range) lifted into span context.
        Standalone span_multi matches exactly the docs its inner query
        matches, so it parses to the inner node directly; embedding inside
        other span clauses is not supported."""
        inner = spec.get("match")
        if not isinstance(inner, dict):
            raise QueryParsingException("span_multi requires a [match] "
                                        "multi-term query")
        return self.parse(inner)

    def _parse_script(self, spec: dict) -> Node:
        from .query_dsl import ScriptQueryNode
        script = spec.get("script") or spec.get("inline") \
            or spec.get("source")
        if script is None:
            raise QueryParsingException("script query requires a script")
        return ScriptQueryNode(script=script, params=spec.get("params"),
                               boost=float(spec.get("boost", 1.0)))

    def _parse_geo_shape(self, spec: dict) -> Node:
        spec = {k: v for k, v in spec.items()
                if k not in ("_name", "ignore_unmapped")}
        boost = float(spec.pop("boost", 1.0))
        if len(spec) != 1:
            raise QueryParsingException(
                "geo_shape needs exactly one shape field")
        (field, params), = spec.items()
        shape = params.get("shape")
        if shape is None:
            if params.get("indexed_shape"):
                raise QueryParsingException(
                    "indexed_shape references are not supported; inline "
                    "the shape in the query")
            raise QueryParsingException("geo_shape requires a [shape]")
        from ..mapping.mapper import DocumentMapper
        from .query_dsl import GeoShapeNode
        try:
            box = DocumentMapper.shape_bbox(shape)
        except (ValueError, TypeError, KeyError, IndexError) as e:
            raise QueryParsingException(
                f"unparseable shape {shape!r}: {e}") from e
        if box is None:
            raise QueryParsingException(f"unparseable shape {shape!r}")
        return GeoShapeNode(
            field_name=field, box=tuple(float(x) for x in box),
            relation=str(params.get("relation", "intersects")).lower(),
            boost=boost)

    def _parse_geo_polygon(self, spec: dict) -> Node:
        spec = {k: v for k, v in spec.items()
                if k not in ("_name", "coerce", "ignore_malformed",
                             "validation_method")}
        boost = float(spec.pop("boost", 1.0))
        if len(spec) != 1:
            raise QueryParsingException(
                "geo_polygon needs exactly one geo field")
        (field, params), = spec.items()
        from .geo import parse_geo_point
        from .query_dsl import GeoPolygonNode
        pts = tuple(parse_geo_point(p) for p in params.get("points", []))
        if len(pts) < 3:
            raise QueryParsingException(
                "geo_polygon requires at least 3 points")
        return GeoPolygonNode(field_name=field, points=pts, boost=boost)

    def _parse_geo_distance(self, spec: dict) -> Node:
        spec = {k: v for k, v in spec.items()
                if k not in ("distance_type", "optimize_bbox", "_name",
                             "coerce", "ignore_malformed",
                             "validation_method")}
        unit = spec.pop("unit", "m")
        distance = parse_distance(spec.pop("distance"), default_unit=unit)
        if len(spec) != 1:
            raise QueryParsingException(
                f"geo_distance needs exactly one geo field, got "
                f"{sorted(spec)}")
        (field, point), = spec.items()
        lat, lon = parse_geo_point(point)
        return GeoDistanceNode(field_name=field, lat=lat, lon=lon,
                               distance_m=distance)

    def _parse_geo_bounding_box(self, spec: dict) -> Node:
        """Rewritten to columnar range filters over the stored
        <field>.lat / <field>.lon doc values (ref index/query/
        GeoBoundingBoxFilterParser — 'indexed' execution mode). Boxes
        crossing the antimeridian split into two longitude ranges."""
        spec = {k: v for k, v in spec.items()
                if k not in ("type", "coerce", "ignore_malformed", "_name",
                             "validation_method")}
        (field, box), = spec.items()
        if "top_left" in box:
            top, left = parse_geo_point(box["top_left"])
            bottom, right = parse_geo_point(box["bottom_right"])
        else:
            top, bottom = float(box["top"]), float(box["bottom"])
            left, right = float(box["left"]), float(box["right"])
        lat_rng = RangeNode(field_name=field + ".lat",
                            bounds_per_query=[(bottom, top, True, True)])
        if left <= right:
            lon_node: Node = RangeNode(
                field_name=field + ".lon",
                bounds_per_query=[(left, right, True, True)])
        else:
            # dateline crossing: lon in [left, 180] OR [-180, right]
            lon_node = BoolNode(should=[
                RangeNode(field_name=field + ".lon",
                          bounds_per_query=[(left, 180.0, True, True)]),
                RangeNode(field_name=field + ".lon",
                          bounds_per_query=[(-180.0, right, True, True)]),
            ])
        return BoolNode(filter=[lat_rng, lon_node])

    def _parse_common(self, spec: dict) -> Node:
        (field, params), = spec.items()
        if not isinstance(params, dict):
            params = {"query": params}
        terms = self._analyze(field, params["query"])
        if not terms:
            return MatchNoneNode()
        msm = params.get("minimum_should_match", 0)
        if isinstance(msm, dict):
            msm = msm.get("low_freq", 0)
        return CommonTermsNode(
            field_name=field, terms=terms,
            cutoff_frequency=float(params.get("cutoff_frequency", 0.01)),
            low_freq_operator=str(params.get("low_freq_operator",
                                             "or")).lower(),
            high_freq_operator=str(params.get("high_freq_operator",
                                              "or")).lower(),
            minimum_should_match=msm,   # resolved vs the low-freq group
            boost=float(params.get("boost", 1.0)),
            **self._sim_kw(field))

    _parse_common_terms = _parse_common

    def _parse_template(self, spec: dict) -> Node:
        """template query: render the mustache-lite template then parse the
        result (ref index/query/TemplateQueryParser)."""
        import json as _json

        from .templates import render_template
        rendered = render_template(spec, getattr(self.mappers,
                                                 "search_templates", None))
        if isinstance(rendered, dict) and list(rendered) == ["query"]:
            rendered = rendered["query"]
        if isinstance(rendered, str):
            # the template body may itself be a JSON string
            # (TemplateQueryParser's string form)
            rendered = _json.loads(rendered)
        return self.parse(rendered)

    def _parse_exists(self, spec: dict) -> Node:
        return ExistsNode(field_name=spec["field"])

    def _parse_missing(self, spec: dict) -> Node:
        return BoolNode(must_not=[ExistsNode(field_name=spec["field"])])

    def _parse_ids(self, spec: dict) -> Node:
        return IdsNode(ids_per_query=[[str(v) for v in spec.get("values", [])]])

    def _parse_prefix(self, spec: dict) -> Node:
        (field, params), = spec.items()
        value = params.get("value", params.get("prefix")) if isinstance(params, dict) else params
        return MultiTermExpandNode(field_name=field, kind="prefix", pattern=str(value))

    def _parse_wildcard(self, spec: dict) -> Node:
        (field, params), = spec.items()
        value = params.get("value", params.get("wildcard")) if isinstance(params, dict) else params
        return MultiTermExpandNode(field_name=field, kind="wildcard", pattern=str(value))

    def _parse_regexp(self, spec: dict) -> Node:
        (field, params), = spec.items()
        value = params.get("value") if isinstance(params, dict) else params
        return MultiTermExpandNode(field_name=field, kind="regexp", pattern=str(value))

    def _parse_fuzzy(self, spec: dict) -> Node:
        (field, params), = spec.items()
        value = params.get("value") if isinstance(params, dict) else params
        fuzz = params.get("fuzziness", "AUTO") if isinstance(params, dict) else "AUTO"
        return MultiTermExpandNode(field_name=field, kind="fuzzy", pattern=str(value),
                                   fuzziness=str(fuzz))

    def _parse_bool(self, spec: dict) -> Node:
        def as_list(x):
            if x is None:
                return []
            return x if isinstance(x, list) else [x]

        msm = spec.get("minimum_should_match")
        n_should = len(as_list(spec.get("should")))
        return BoolNode(
            must=[self.parse(q) for q in as_list(spec.get("must"))],
            should=[self.parse(q) for q in as_list(spec.get("should"))],
            must_not=[self.parse(q) for q in as_list(spec.get("must_not"))],
            filter=[self.parse(q) for q in as_list(spec.get("filter"))],
            minimum_should_match=_parse_msm(msm, n_should) if msm is not None else None,
            boost=float(spec.get("boost", 1.0)))

    def _parse_nested(self, spec: dict) -> Node:
        # ref index/query/NestedQueryParser.java
        path = spec.get("path")
        if not path:
            raise QueryParsingException("nested requires a path")
        inner = spec.get("query", spec.get("filter"))
        if inner is None:
            raise QueryParsingException("nested requires a query")
        return NestedNode(path=str(path), inner=self.parse(inner),
                          score_mode=str(spec.get("score_mode", "avg")),
                          boost=float(spec.get("boost", 1.0)))

    def _parse_has_child(self, spec: dict) -> Node:
        # ref index/query/HasChildQueryParser.java
        ctype = spec.get("type", spec.get("child_type"))
        if not ctype:
            raise QueryParsingException("has_child requires a type")
        inner = spec.get("query", spec.get("filter"))
        if inner is None:
            raise QueryParsingException("has_child requires a query")
        return HasChildNode(child_type=str(ctype), inner=self.parse(inner),
                            score_mode=str(spec.get("score_mode", "none")),
                            min_children=int(spec.get("min_children", 0)),
                            max_children=int(spec.get("max_children", 0)),
                            boost=float(spec.get("boost", 1.0)))

    def _parse_has_parent(self, spec: dict) -> Node:
        # ref index/query/HasParentQueryParser.java
        ptype = spec.get("parent_type", spec.get("type"))
        if not ptype:
            raise QueryParsingException("has_parent requires a parent_type")
        inner = spec.get("query", spec.get("filter"))
        if inner is None:
            raise QueryParsingException("has_parent requires a query")
        score_mode = spec.get("score_mode")
        if score_mode is None:
            score_mode = "score" if spec.get("score") else "none"
        return HasParentNode(parent_type=str(ptype), inner=self.parse(inner),
                             score_mode=str(score_mode),
                             boost=float(spec.get("boost", 1.0)))

    def _parse_constant_score(self, spec: dict) -> Node:
        inner = spec.get("filter", spec.get("query"))
        return ConstantScoreNode(inner=self.parse(inner),
                                 boost=float(spec.get("boost", 1.0)))

    def _parse_filtered(self, spec: dict) -> Node:
        # ES 2.x `filtered` query (ref index/query/FilteredQueryParser.java)
        return BoolNode(must=[self.parse(spec.get("query", {}))],
                        filter=[self.parse(spec.get("filter", {}))])

    def _parse_dis_max(self, spec: dict) -> Node:
        return DisMaxNode(queries=[self.parse(q) for q in spec.get("queries", [])],
                          tie_breaker=float(spec.get("tie_breaker", 0.0)),
                          boost=float(spec.get("boost", 1.0)))

    def _parse_boosting(self, spec: dict) -> Node:
        return BoostingNode(positive=self.parse(spec["positive"]),
                            negative=self.parse(spec["negative"]),
                            negative_boost=float(spec.get("negative_boost", 0.5)))

    def _parse_function_score(self, spec: dict) -> Node:
        inner = self.parse(spec.get("query", {"match_all": {}}))
        functions = []
        if "functions" in spec:
            for f in spec["functions"]:
                functions.append(self._parse_function(f))
        else:
            single = {k: v for k, v in spec.items()
                      if k in ("field_value_factor", "script_score", "random_score",
                               "cosine", "gauss", "exp", "linear", "weight")}
            if single:
                functions.append(self._parse_function(single))
        return FunctionScoreNode(
            inner=inner, functions=functions,
            score_mode=spec.get("score_mode", "multiply"),
            boost_mode=spec.get("boost_mode", "multiply"),
            boost=float(spec.get("boost", 1.0)),
            mappers=self.mappers)

    def _parse_function(self, f: dict) -> dict:
        out: dict[str, Any] = {}
        if "weight" in f:
            out["weight"] = float(f["weight"])
        for decay_kind in ("gauss", "exp", "linear"):
            if decay_kind in f:
                (field, p), = f[decay_kind].items()
                ft = self.mappers.field_type(field)
                origin = p["origin"]
                scale = p["scale"]
                offset = p.get("offset", 0)
                if ft is not None and ft.type == DATE:
                    origin = eval_date_math(str(origin))
                    scale = _duration_millis(str(scale))
                    offset = _duration_millis(str(offset)) if offset else 0
                out["decay"] = {"function": decay_kind, "field": field,
                                "origin": origin, "scale": scale,
                                "decay": p.get("decay", 0.5), "offset": offset}
                return out
        if "field_value_factor" in f:
            out["field_value_factor"] = f["field_value_factor"]
        elif "random_score" in f:
            out["random_score"] = f.get("random_score") or {}
        elif "script_score" in f:
            # passed through raw: vector query_vectors specs ride the cosine
            # kernel; expression bodies compile via script/jax_compile (no
            # Groovy sandbox — SURVEY.md §7 M6), declining to the host
            # evaluator when outside the grammar
            out["script_score"] = f["script_score"]
        elif "cosine" in f:
            out["cosine"] = f["cosine"]
        elif "weight" in f and len(f) == 1:
            pass
        return out

    def _parse_query_string(self, spec: dict) -> Node:
        if not isinstance(spec, dict):
            spec = {"query": spec}
        qs = str(spec.get("query", "*"))
        default_field = spec.get("default_field", spec.get("df", "_all"))
        return self._query_string_node(qs, default_field,
                                       spec.get("default_operator", "or").lower())

    def _parse_simple_query_string(self, spec: dict) -> Node:
        fields = spec.get("fields", ["_all"])
        return self._query_string_node(str(spec.get("query", "")), fields[0],
                                       spec.get("default_operator", "or").lower())

    def _query_string_node(self, qs: str, default_field: str, default_op: str) -> Node:
        """Simplified Lucene query-string syntax: field:term, quoted phrases,
        AND/OR/NOT, +/- prefixes, * wildcard-in-term."""
        if qs.strip() in ("*", "*:*", ""):
            return MatchAllNode()
        # field:"quoted phrase" must stay one token
        tokens = re.findall(r'[^\s:]+:"[^"]*"|"[^"]*"|\S+', qs)
        # clauses as (node, neg, req); AND is binary — it requires BOTH its
        # operands (Lucene parses 'a AND b' as +a +b), so it retroactively
        # promotes the previous clause too.
        clauses: list[list] = []
        op_and = default_op == "and"
        pending_not = False
        pending_and = False
        for tok in tokens:
            if tok.upper() == "AND":
                pending_and = True
                if clauses and not clauses[-1][1]:
                    clauses[-1][2] = True
                continue
            if tok.upper() == "OR":
                continue
            if tok.upper() == "NOT":
                pending_not = True
                continue
            neg = pending_not
            req = pending_and or op_and
            pending_not = pending_and = False
            if tok.startswith("-"):
                neg, tok = True, tok[1:]
            elif tok.startswith("+"):
                req, tok = True, tok[1:]
            if ":" in tok and not tok.startswith('"'):
                field, val = tok.split(":", 1)
            else:
                field, val = default_field, tok
            quoted = val.startswith('"') and val.endswith('"') and len(val) > 1
            val = val.strip('"')
            ft = self.mappers.field_type(field)
            if "*" in val or "?" in val:
                node: Node = MultiTermExpandNode(field_name=field, kind="wildcard",
                                                 pattern=val)
            elif ft is not None and ft.type != TEXT:
                node = self._term_node(field, [val], 1.0)
            elif quoted:
                # "quoted phrase" -> positions-verified phrase
                node = self._phrase_node(field, {"query": val})
            else:
                terms = self._analyze(field, val)
                node = MatchNode(field_name=field, terms_per_query=[terms]) if terms \
                    else MatchNoneNode()
            clauses.append([node, neg, req])
        must = [n for n, neg, req in clauses if not neg and req]
        should = [n for n, neg, req in clauses if not neg and not req]
        must_not = [n for n, neg, _ in clauses if neg]
        if not should and not must and not must_not:
            return MatchAllNode()
        return BoolNode(must=must, should=should, must_not=must_not)


def _parse_msm(msm, n_clauses: int) -> int:
    if msm is None:
        return 0
    s = str(msm)
    if s.endswith("%"):
        pct = float(s[:-1])
        if pct < 0:
            return max(n_clauses - int(n_clauses * -pct / 100.0), 0)
        return int(n_clauses * pct / 100.0)
    v = int(s)
    return v if v >= 0 else max(n_clauses + v, 0)


def _duration_millis(s: str) -> float:
    m = re.match(r"^(\d+(?:\.\d+)?)([yMwdhms]|ms)$", s.strip())
    if not m:
        return float(s)
    n = float(m.group(1))
    unit = m.group(2)
    table = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
             "d": 86_400_000, "w": 604_800_000, "M": 2_592_000_000,
             "y": 31_536_000_000}
    return n * table[unit]


# ---------------------------------------------------------------------------
# Multi-term expansion node (prefix/wildcard/regexp/fuzzy)
# ---------------------------------------------------------------------------

import dataclasses as _dc

import jax.numpy as jnp
import numpy as np

from ..ops import bm25 as _bm25
from .query_dsl import SegmentContext, _false, _zeros


@_dc.dataclass
class MultiTermExpandNode(Node):
    """Constant-score multi-term query: expands the pattern against each
    segment's term dictionary at execute time — mirroring Lucene's per-segment
    MultiTermQuery rewrite (ref org.apache.lucene.search.MultiTermQuery used
    by index/query/{Prefix,Wildcard,Regexp,Fuzzy}QueryParser.java)."""
    field_name: str = ""
    kind: str = "prefix"            # prefix | wildcard | regexp | fuzzy
    pattern: str = ""
    fuzziness: str = "AUTO"
    max_expansions: int = 1024

    def _expand(self, ctx: SegmentContext) -> list[str]:
        seg = ctx.segment
        fx = seg.text.get(self.field_name)
        kc = seg.keywords.get(self.field_name)
        vocab: list[str]
        if fx is not None:
            vocab = list(fx.terms)
        elif kc is not None:
            vocab = kc.values
        else:
            return []
        pat = self.pattern
        if self.kind == "prefix":
            return [t for t in vocab if t.startswith(pat)][: self.max_expansions]
        if self.kind == "wildcard":
            rx = re.compile("^" + re.escape(pat).replace(r"\*", ".*").replace(r"\?", ".") + "$")
            return [t for t in vocab if rx.match(t)][: self.max_expansions]
        if self.kind == "regexp":
            rx = re.compile("^" + pat + "$")
            return [t for t in vocab if rx.match(t)][: self.max_expansions]
        # fuzzy: Damerau-Levenshtein within edit distance
        max_ed = _auto_fuzz(pat, self.fuzziness)
        return [t for t in vocab if abs(len(t) - len(pat)) <= max_ed
                and _edit_distance_le(pat, t, max_ed)][: self.max_expansions]

    def execute(self, ctx: SegmentContext):
        seg = ctx.segment
        terms = self._expand(ctx)
        if not terms:
            return _zeros(ctx), _false(ctx)
        fx = seg.text.get(self.field_name)
        if fx is not None:
            starts = np.zeros((1, len(terms)), np.int32)
            lens = np.zeros((1, len(terms)), np.int32)
            for ti, t in enumerate(terms):
                s, ln, _ = fx.lookup(t)
                starts[0, ti] = s
                lens[0, ti] = ln
            from .query_dsl import _pow2_window
            hits = _bm25.term_match_mask(fx.doc_ids, jnp.asarray(starts),
                                         jnp.asarray(lens),
                                         W=_pow2_window(lens), n_pad=ctx.n_pad)
            match = jnp.broadcast_to(hits, (ctx.Q, ctx.n_pad))
        else:
            kc = seg.keywords[self.field_name]
            ord_targets = np.asarray([kc.ord_of(t) for t in terms], np.int32)
            match = jnp.isin(kc.ords, jnp.asarray(ord_targets))[None, :]
            match = jnp.broadcast_to(match, (ctx.Q, ctx.n_pad))
        return jnp.where(match, jnp.float32(self.boost), 0.0), match

    def plan_key(self):
        return ("multi_term", self.field_name, self.kind, self.pattern)


def _auto_fuzz(term: str, fuzz: str) -> int:
    if fuzz.upper() == "AUTO":
        if len(term) <= 2:
            return 0
        if len(term) <= 5:
            return 1
        return 2
    return int(float(fuzz))


def _edit_distance_le(a: str, b: str, k: int) -> bool:
    """Damerau-Levenshtein (with transpositions, Lucene's fuzzy default —
    ref index/query/FuzzyQueryParser.java transpositions=true) <= k."""
    if k == 0:
        return a == b
    prev2: list[int] | None = None
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        row_min = i
        for j, cb in enumerate(b, 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb))
            if prev2 is not None and i > 1 and j > 1 \
                    and ca == b[j - 2] and a[i - 2] == cb:
                cur[j] = min(cur[j], prev2[j - 2] + 1)
            row_min = min(row_min, cur[j])
        if row_min > k:
            return False
        prev2, prev = prev, cur
    return prev[-1] <= k


# ---------------------------------------------------------------------------
# Hybrid ranking — the body's top-level "rank" section (first-class
# BM25 + vector fusion; search/controller.fuse_hybrid + ops/ann kernels)
# ---------------------------------------------------------------------------

@dataclass
class RankSpec:
    """Parsed `"rank"` section:
      {"rrf": {"rank_constant": 60, "window_size": 100,
               "query_weight": 1.0, "knn_weight": 1.0}}
      {"weighted": {"query_weight": .7, "knn_weight": .3,
                    "normalize": "minmax" | "none", "window_size": 100}}
    """
    mode: str                  # "rrf" | "weighted"
    rank_constant: float = 60.0
    window_size: int = 0       # 0 = derived from size+from_ by the caller
    query_weight: float = 1.0
    knn_weight: float = 1.0
    normalize: str = "minmax"


def parse_rank(spec: Any) -> RankSpec | None:
    """Parse + validate the body's `rank` section; None when absent."""
    if spec is None:
        return None
    if not isinstance(spec, dict) or len(spec) != 1:
        raise QueryParsingException(
            'rank takes exactly one mode: {"rrf": {...}} or '
            '{"weighted": {...}}')
    (mode, params), = spec.items()
    params = params or {}
    if mode not in ("rrf", "weighted"):
        raise QueryParsingException(f"unsupported rank mode [{mode}]")
    norm = str(params.get("normalize", "minmax"))
    if norm not in ("minmax", "none"):
        raise QueryParsingException(f"unsupported rank normalize [{norm}]")
    return RankSpec(
        mode=mode,
        rank_constant=float(params.get("rank_constant", 60.0)),
        window_size=int(params.get("window_size", 0)),
        query_weight=float(params.get("query_weight", 1.0)),
        knn_weight=float(params.get("knn_weight", 1.0)),
        normalize=norm)


# ---------------------------------------------------------------------------
# Batch merging
# ---------------------------------------------------------------------------

_PER_QUERY_FIELDS = ("terms_per_query", "values_per_query", "bounds_per_query",
                     "ids_per_query")


def merge_query_batch(nodes: list[Node]) -> Node:
    """Fuse same-shape Q=1 trees into one tree with Q rows. All trees must
    share plan_key(); leaves concatenate their per-query rows."""
    if len(nodes) == 1:
        return nodes[0]
    first = nodes[0]
    key = first.plan_key()
    for n in nodes[1:]:
        if n.plan_key() != key:
            raise QueryParsingException("cannot batch queries with different shapes")
    return _merge(nodes)


def _merge(nodes: list[Node]) -> Node:
    first = nodes[0]
    kwargs = {}
    for f in dataclasses.fields(first):
        vals = [getattr(n, f.name) for n in nodes]
        v0 = vals[0]
        if f.name in _PER_QUERY_FIELDS:
            merged: list = []
            for v in vals:
                merged.extend(v)
            kwargs[f.name] = merged
        elif isinstance(v0, Node):
            kwargs[f.name] = _merge(vals)
        elif isinstance(v0, list) and v0 and isinstance(v0[0], Node):
            kwargs[f.name] = [_merge([v[i] for v in vals]) for i in range(len(v0))]
        elif f.name == "functions":
            kwargs[f.name] = _merge_functions(vals)
        else:
            # scalar params (boost, k1, b, ...) are tree-wide, not per-row:
            # merging trees that differ would silently apply the first
            # query's value to every row (wrong _score scaling)
            if any(v != v0 for v in vals[1:]):
                raise QueryParsingException(
                    f"cannot batch queries differing in [{f.name}]")
            kwargs[f.name] = v0
    return type(first)(**kwargs)


def _merge_functions(fn_lists: list[list[dict]]) -> list[dict]:
    """function_score specs may carry per-query vectors (query_vectors)."""
    out = []
    for i in range(len(fn_lists[0])):
        spec = dict(fn_lists[0][i])
        for key in ("cosine", "script_score"):
            if key in spec and "query_vectors" in spec[key]:
                merged_vecs = []
                for fns in fn_lists:
                    merged_vecs.extend(fns[i][key]["query_vectors"])
                spec[key] = dict(spec[key], query_vectors=merged_vecs)
        out.append(spec)
    return out
