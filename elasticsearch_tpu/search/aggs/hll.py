"""HyperLogLog for the cardinality aggregation.

The mergeable-sketch analog of the reference's HyperLogLogPlusPlus
(/root/reference/src/main/java/org/elasticsearch/search/aggregations/metrics/
cardinality/HyperLogLogPlusPlus.java): per-shard sketches reduce by
register-wise max, exactly like InternalCardinality.reduce merges shard
sketches. Dense registers only (the reference's sparse/LC mode is a memory
optimization for tiny sets; dense is always correct), with the standard
HLL bias-corrected estimator + linear counting for small ranges.
"""

from __future__ import annotations

import numpy as np

DEFAULT_PRECISION = 14      # 2^14 registers ≈ 0.8% relative error


def _splitmix64(v: np.ndarray) -> np.ndarray:
    v = (v + np.uint64(0x9E3779B97F4A7C15))
    v ^= v >> np.uint64(30)
    v *= np.uint64(0xBF58476D1CE4E5B9)
    v ^= v >> np.uint64(27)
    v *= np.uint64(0x94D049BB133111EB)
    v ^= v >> np.uint64(31)
    return v


def _hash64(values) -> np.ndarray:
    """Process-stable 64-bit hashes (sketches must merge across nodes, so no
    PYTHONHASHSEED-randomized builtin hash; floats hash by BIT pattern, not
    truncated value, so 0.1 != 0.2)."""
    if isinstance(values, np.ndarray) and values.dtype.kind in "iuf":
        if values.dtype.kind == "f":
            bits = values.astype(np.float64, copy=False).view(np.uint64)
        else:
            bits = values.astype(np.int64, copy=False).view(np.uint64)
        return _splitmix64(bits)
    import hashlib
    out = np.empty(len(values), np.uint64)
    for i, x in enumerate(values):
        h = hashlib.blake2b(str(x).encode("utf-8"), digest_size=8).digest()
        out[i] = int.from_bytes(h, "little")
    return out


class HyperLogLog:
    def __init__(self, precision: int = DEFAULT_PRECISION,
                 registers: np.ndarray | None = None):
        self.p = precision
        self.m = 1 << precision
        self.registers = registers if registers is not None \
            else np.zeros(self.m, np.uint8)

    def add_hashes(self, h: np.ndarray) -> None:
        if h.size == 0:
            return
        idx = (h >> np.uint64(64 - self.p)).astype(np.int64)
        rest = h << np.uint64(self.p)
        # rank = (leading zeros of the remaining 64-p bits) + 1, computed with
        # an exact binary-step clz (float log2 rounds wrong near 2^k)
        x = rest.copy()
        lz = np.zeros(h.shape, np.int64)
        for shift in (32, 16, 8, 4, 2, 1):
            top_clear = x < (np.uint64(1) << np.uint64(64 - shift))
            lz += np.where(top_clear, shift, 0)
            x = np.where(top_clear, x << np.uint64(shift), x)
        lz = np.where(rest == 0, 64, lz)
        rank = (np.minimum(lz, 64 - self.p) + 1).astype(np.uint8)
        np.maximum.at(self.registers, idx, rank)

    def add(self, values) -> None:
        self.add_hashes(_hash64(values))

    def merge(self, other: "HyperLogLog") -> "HyperLogLog":
        assert self.p == other.p
        return HyperLogLog(self.p, np.maximum(self.registers, other.registers))

    def cardinality(self) -> int:
        m = float(self.m)
        inv = np.exp2(-self.registers.astype(np.float64))
        est = (0.7213 / (1 + 1.079 / m)) * m * m / inv.sum()
        zeros = int((self.registers == 0).sum())
        if est <= 2.5 * m and zeros:
            est = m * np.log(m / zeros)          # linear counting
        return int(round(est))
