"""Aggregations: composable analytics tree over columnar fielddata.

The analog of the reference aggregation framework
(/root/reference/src/main/java/org/elasticsearch/search/aggregations/ —
Aggregator collect-per-doc -> InternalAggregation reduce-across-shards,
AggregationPhase.java:45,70-95). Execution model here is tensor-native
instead of per-doc collectors:

  collect  — per segment, the query's match mask (bool[n_pad], the same mask
             the scoring pass produced) gates vectorized column reductions:
             bucket assignment is one vectorized expression, counts/sums are
             np.bincount / ufunc.at over the whole column at once.
  partial  — a small, host-side, *mergeable* summary per shard, mirroring
             InternalAggregation's wire objects (sum/count/min/max pairs,
             HLL registers, t-digest centroids, bucket->count maps).
  reduce   — partials merge associatively across segments and shards
             (ref InternalAggregations.reduce via SearchPhaseController
             .merge:282-399); in the mesh data plane these merges ride
             collectives (counts psum) — host merge is the DCN fallback.
  render   — ES 2.0 response JSON shapes (buckets / value / values).

Bucket aggs: terms, histogram, date_histogram, range, date_range, filter,
filters, global, missing. Metric aggs: min, max, sum, avg, value_count,
stats, extended_stats, cardinality (HLL), percentiles (t-digest).
Sub-aggregations nest arbitrarily under bucket aggs.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field as dc_field
from datetime import datetime, timezone
from typing import Any, Callable

import numpy as np

from ...index.segment import Segment
from .hll import HyperLogLog, _hash64
from .tdigest import TDigest

BUCKET_TYPES = {"terms", "histogram", "date_histogram", "range", "date_range",
                "filter", "filters", "global", "missing",
                "significant_terms", "nested", "reverse_nested", "children",
                "geohash_grid", "geo_distance", "sampler", "composite"}
METRIC_TYPES = {"min", "max", "sum", "avg", "value_count", "stats",
                "extended_stats", "cardinality", "percentiles", "top_hits",
                "geo_bounds", "scripted_metric"}
# Pipeline aggregations (ref search/aggregations/pipeline/): computed
# HOST-SIDE at render time over the already-reduced bucket list, so every
# serving lane (loop/stacked/blockwise/mesh/host-reduce) feeds them the
# same merged partials and the outputs are identical by construction.
PIPELINE_TYPES = {"derivative", "moving_avg", "cumulative_sum",
                  "bucket_script"}
# which parents may carry which pipelines: the sequential pipelines need
# an ordered bucket axis (histogram family); bucket_script only needs
# per-bucket values, so terms qualifies too
_PIPELINE_PARENTS = {
    "derivative": ("histogram", "date_histogram"),
    "moving_avg": ("histogram", "date_histogram"),
    "cumulative_sum": ("histogram", "date_histogram"),
    "bucket_script": ("histogram", "date_histogram", "terms"),
}


def has_top_hits(specs: list["AggSpec"]) -> bool:
    """top_hits needs per-doc scores, which only the dense scoring path
    materializes — the sparse lane checks this before taking an agg tree."""
    return any(s.type == "top_hits" or has_top_hits(s.subs) for s in specs)


class AggregationParsingException(Exception):
    pass


@dataclass
class AggSpec:
    name: str
    type: str
    params: dict
    subs: list["AggSpec"] = dc_field(default_factory=list)
    # pipeline children live OUTSIDE `subs`: they never collect per doc
    # (render-time host math only), so a leaf parent stays eligible for
    # the batched device collect and the mesh planner never sees them
    pipelines: list["AggSpec"] = dc_field(default_factory=list)


def parse_aggs(spec: dict | None, *, _nested: bool = False) -> list[AggSpec]:
    """Parse the request's "aggs"/"aggregations" tree
    (ref search/aggregations/AggregatorParsers.java)."""
    if not spec:
        return []
    out = []
    for name, body in spec.items():
        subs = []
        agg_type = None
        params: dict = {}
        for key, val in body.items():
            if key in ("aggs", "aggregations"):
                subs = parse_aggs(val, _nested=True)
            elif key in BUCKET_TYPES or key in METRIC_TYPES \
                    or key in PIPELINE_TYPES:
                agg_type, params = key, (val if isinstance(val, dict) else {})
            else:
                raise AggregationParsingException(
                    f"unknown aggregation type [{key}] under [{name}]")
        if agg_type is None:
            raise AggregationParsingException(f"no type for aggregation [{name}]")
        if subs and agg_type in METRIC_TYPES:
            raise AggregationParsingException(
                f"metric aggregation [{name}] cannot have sub-aggregations")
        if subs and agg_type in PIPELINE_TYPES:
            raise AggregationParsingException(
                f"pipeline aggregation [{name}] cannot have sub-aggregations")
        pipelines = [s for s in subs if s.type in PIPELINE_TYPES]
        subs = [s for s in subs if s.type not in PIPELINE_TYPES]
        if agg_type == "composite":
            _validate_composite(name, params, subs)
        for ps in pipelines:
            _validate_pipeline(agg_type, ps)
        out.append(AggSpec(name=name, type=agg_type, params=params,
                           subs=subs, pipelines=pipelines))
    if not _nested:
        for s in out:
            if s.type in PIPELINE_TYPES:
                raise AggregationParsingException(
                    f"pipeline aggregation [{s.name}] must be a sibling "
                    f"inside a bucket aggregation's [aggs], not top-level")
    return out


def _validate_pipeline(parent_type: str, ps: "AggSpec") -> None:
    allowed = _PIPELINE_PARENTS[ps.type]
    if parent_type not in allowed:
        raise AggregationParsingException(
            f"pipeline aggregation [{ps.name}] of type [{ps.type}] requires "
            f"a parent of type {sorted(allowed)}, got [{parent_type}]")
    bp = ps.params.get("buckets_path")
    if ps.type == "bucket_script":
        if not isinstance(bp, dict) or not bp:
            raise AggregationParsingException(
                f"bucket_script [{ps.name}] needs a buckets_path map")
        if not ps.params.get("script"):
            raise AggregationParsingException(
                f"bucket_script [{ps.name}] needs a script")
    elif not isinstance(bp, str) or not bp:
        raise AggregationParsingException(
            f"pipeline aggregation [{ps.name}] needs a buckets_path string")


def _validate_composite(name: str, params: dict, subs: list) -> None:
    """composite scope for this tier: leaf-only (no sub-aggregations),
    ascending sources of terms/histogram/date_histogram — the exact slice
    the after-key disjoint-cover guarantee is proven for."""
    if subs:
        raise AggregationParsingException(
            f"composite aggregation [{name}] does not support "
            f"sub-aggregations")
    sources = params.get("sources")
    if not isinstance(sources, list) or not sources:
        raise AggregationParsingException(
            f"composite aggregation [{name}] needs a non-empty sources list")
    for src in sources:
        if not isinstance(src, dict) or len(src) != 1:
            raise AggregationParsingException(
                f"composite [{name}]: each source is one {{name: spec}}")
        sname, sbody = next(iter(src.items()))
        if not isinstance(sbody, dict) or len(sbody) != 1:
            raise AggregationParsingException(
                f"composite [{name}] source [{sname}]: one source type")
        stype, sp = next(iter(sbody.items()))
        if stype not in ("terms", "histogram", "date_histogram"):
            raise AggregationParsingException(
                f"composite [{name}] source [{sname}]: unsupported source "
                f"type [{stype}]")
        if not isinstance(sp, dict) or not sp.get("field"):
            raise AggregationParsingException(
                f"composite [{name}] source [{sname}] needs a field")
        if str(sp.get("order", "asc")) != "asc":
            raise AggregationParsingException(
                f"composite [{name}] source [{sname}]: only ascending "
                f"order is supported")
        if stype == "histogram" and "interval" not in sp:
            raise AggregationParsingException(
                f"composite [{name}] source [{sname}] needs an interval")


def _composite_sources(spec: AggSpec) -> list[tuple[str, str, dict]]:
    """-> [(source_name, source_type, source_params)], in request order
    (the composite key's lexicographic significance order)."""
    out = []
    for src in spec.params.get("sources", []):
        sname, sbody = next(iter(src.items()))
        stype, sp = next(iter(sbody.items()))
        out.append((sname, stype, sp))
    return out


# ---------------------------------------------------------------------------
# Column access
# ---------------------------------------------------------------------------

def _numeric_column(seg: Segment, field: str):
    """-> (vals [N] in the column's NATIVE dtype, valid bool[N]) or None.
    i64 stays i64: casting to float64 would collapse distinct longs > 2^53
    (snowflake ids) in terms/cardinality buckets."""
    nc = seg.numerics.get(field)
    if nc is None:
        return None
    return np.asarray(nc.vals), ~np.asarray(nc.missing)


def _text_present_mask(seg: Segment, field: str) -> np.ndarray | None:
    """bool[n_pad]: docs with at least one posting in an analyzed field."""
    fx = seg.text.get(field)
    if fx is None:
        return None
    present = np.zeros(seg.n_pad, bool)
    present[np.asarray(fx.doc_ids)[:fx.n_postings]] = True
    return present


def _keyword_column(seg: Segment, field: str):
    kc = seg.keywords.get(field)
    if kc is None:
        return None
    return np.asarray(kc.ords), kc.values


# ---------------------------------------------------------------------------
# Collect: per-segment vectorized partials
# ---------------------------------------------------------------------------

class MaskView:
    """A query-match mask that stays DEVICE-resident until a collector
    genuinely needs host numpy. The hot collectors (keyword terms, numeric
    metrics) consume `.dev` through ops/aggs kernels — one fused device
    reduction per (segment, agg), downloading a tiny partial instead of a
    bool[n_pad] mask. Everything else falls back to `.np` (downloaded once,
    cached)."""

    __slots__ = ("_dev", "_np")

    def __init__(self, m):
        if isinstance(m, np.ndarray):
            self._np = m
            self._dev = None
        else:
            self._dev = m
            self._np = None

    @property
    def dev(self):
        return self._dev

    @property
    def np(self) -> np.ndarray:
        if self._np is None:
            self._np = np.asarray(self._dev)
        return self._np


def _mv(m) -> MaskView:
    return m if isinstance(m, MaskView) else MaskView(m)


_BATCHED_LEAF_TYPES = ("terms", "histogram", "date_histogram", "range",
                       "date_range", "min", "max", "sum", "avg",
                       "value_count", "stats", "extended_stats")


def collect_shards_batched(specs: list[AggSpec], by_shard: dict,
                           extra_devs=()) -> tuple[dict | None, list]:
    """Row-batched collect for a WHOLE msearch group across ALL shards:
    by_shard[i] = (segments, device bool[Q, n_pad] masks). One device
    program per (agg, segment), then ONE device_get for everything — on a
    tunneled chip the whole analytics batch costs a single round-trip, not
    one per program (perf r5: the agg leg was RTT-bound at ~8 syncs/batch).

    `extra_devs` rides the same fetch (the count-only totals). Returns
    ({shard: per-row partials} | None if any spec needs the general path,
    extra_host_values)."""
    import jax
    eligible = all(not spec.subs and spec.type in _BATCHED_LEAF_TYPES
                   for spec in specs)
    launches: list = []          # (shard_idx, spec_idx, dev, finish)
    if eligible:
        for i, (segments, masks) in by_shard.items():
            for si, spec in enumerate(specs):
                for seg, mask in zip(segments, masks):
                    if seg.n_docs == 0:
                        continue
                    lr = _launch_one_batched(spec, seg, mask)
                    if lr is None:
                        eligible = False
                        break
                    launches.append((i, si, lr[0], lr[1]))
                if not eligible:
                    break
            if not eligible:
                break
    if not eligible:
        extra_host = jax.device_get(list(extra_devs)) if extra_devs else []
        return None, extra_host
    fetched = jax.device_get(list(extra_devs)
                             + [d for _, _, d, _ in launches])
    extra_host = fetched[:len(extra_devs)]
    host_vals = fetched[len(extra_devs):]
    out: dict[int, list] = {}
    for (i, si, _, finish), hv in zip(launches, host_vals):
        rows = finish(hv)
        per_shard = out.setdefault(i, {})
        cur = per_shard.get(si)
        per_shard[si] = rows if cur is None else \
            [merge_partial(specs[si], a, b) for a, b in zip(cur, rows)]
    result: dict[int, list] = {}
    for i, (segments, masks) in by_shard.items():
        q = int(masks[0].shape[0]) if masks else 1
        per_shard = out.get(i, {})
        rows_q = None
        out_rows = [dict() for _ in range(q)]
        for si, spec in enumerate(specs):
            per_seg_rows = per_shard.get(si) \
                or [_empty_partial(spec) for _ in range(q)]
            rows_q = len(per_seg_rows)
            for row, part in zip(out_rows, per_seg_rows):
                row[spec.name] = part
        result[i] = out_rows[:rows_q] if rows_q else out_rows
    return result, extra_host


def collect_shard_batched(specs: list[AggSpec], segments: list[Segment],
                          masks: list) -> list[dict] | None:
    """Single-shard convenience wrapper over collect_shards_batched."""
    rows_by_shard, _ = collect_shards_batched(specs, {0: (segments, masks)})
    return None if rows_by_shard is None else rows_by_shard[0]


def _launch_one_batched(spec: AggSpec, seg: Segment, mask):
    """Launch one leaf agg's device program over one segment.
    -> (device_array, finish(host_array) -> per-row partials) or None when
    the spec needs the general path. The device array is NOT synced here."""
    t = spec.type
    p = spec.params
    field = p.get("field")
    if t == "terms":
        kc = seg.keywords.get(field)
        if kc is None:
            return None
        from ...ops.aggs import masked_bincount_q
        dev = masked_bincount_q(kc.ords, mask, n_bins=len(kc.values))

        def fin_terms(counts, kc=kc):
            return [{"buckets": {kc.values[o]: {"doc_count": int(c[o])}
                                 for o in np.nonzero(c)[0]},
                     "other_doc_count": 0, "error_bound": 0}
                    for c in counts]
        return dev, fin_terms
    nc = seg.numerics.get(field) if field else None
    if nc is None:
        return None
    if t in ("min", "max", "sum", "avg", "value_count", "stats",
             "extended_stats"):
        from ...ops.aggs import masked_stats_q
        dev = masked_stats_q(nc.vals, nc.missing, mask)

        def fin_stats(st):
            return [{"count": int(r[0]), "sum": float(r[1]),
                     "sum_sq": float(r[2]),
                     "min": float(r[3]) if r[0] else math.inf,
                     "max": float(r[4]) if r[0] else -math.inf}
                    for r in st]
        return dev, fin_stats
    if t in ("histogram", "date_histogram"):
        if t == "histogram":
            interval = float(p["interval"])
        else:
            interval = _fixed_interval_ms(p.get("interval", "1d"))
            if interval is None:
                return None       # calendar intervals: host path
        if interval <= 0:
            return None
        mn, mx = _col_minmax(seg, field, nc)
        if not np.isfinite(mn) or not np.isfinite(mx):
            nrows = int(mask.shape[0])
            return (np.zeros(0),
                    lambda _hv, n=nrows: [{"buckets": {}}
                                          for _ in range(n)])
        base = math.floor(mn / interval) * interval
        n_bins = int((mx - base) // interval) + 1
        if n_bins > _MAX_DEVICE_BINS:
            return None
        from ...ops.aggs import masked_histogram_q
        dev = masked_histogram_q(nc.vals, nc.missing, mask, base,
                                 float(interval), n_bins=n_bins)

        def fin_hist(counts, base=base, interval=interval):
            return [{"buckets": {float(base + i * interval):
                                 {"doc_count": int(c[i])}
                                 for i in np.nonzero(c)[0]}}
                    for c in counts]
        return dev, fin_hist
    if t in ("range", "date_range"):
        bounds = _range_bounds(p, is_date=(t == "date_range"))
        if bounds is None:
            return None
        keys, los, his = bounds
        from ...ops.aggs import masked_ranges_q
        dev = masked_ranges_q(nc.vals, nc.missing, mask, los, his)

        def fin_ranges(counts, keys=keys):
            return [{"buckets": {key: {"doc_count": int(row[ri]),
                                       "from": lo, "to": hi}
                                 for ri, (key, lo, hi) in enumerate(keys)}}
                    for row in counts]
        return dev, fin_ranges
    return None


def _range_bounds(p: dict, is_date: bool):
    """Shared range-spec resolution for the solo and row-batched device
    collects — ONE place derives (keys, los, his) so the lanes can't
    diverge (code review r5)."""
    keys, los, his = [], [], []
    for rr in p.get("ranges", []):
        key, lo, hi = _resolve_range(rr, is_date=is_date)
        keys.append((key, lo, hi))
        los.append(-np.inf if lo is None else float(lo))
        his.append(np.inf if hi is None else float(hi))
    if not keys:
        return None
    return keys, np.asarray(los, np.float64), np.asarray(his, np.float64)


class _ShardScopedParser:
    """Wraps the query parser so filter/filters agg queries that contain
    parent/child joins resolve against the WHOLE shard's segments (the join
    spans segments; per-segment execution of an unresolved HasChildNode
    raises — code review r5)."""

    def __init__(self, qp, segments):
        self._qp = qp
        self._segments = segments
        self.mappers = qp.mappers

    def parse(self, spec):
        node = self._qp.parse(spec)
        from ..query_dsl import contains_joins
        if contains_joins(node):
            from ..joins import resolve_joins
            node = resolve_joins(node, self._segments, self.mappers, 1)
        return node


def collect_shard(specs: list[AggSpec], segments: list[Segment],
                  masks: list,
                  query_parser=None, scores: list | None = None) -> dict:
    """Collect the agg tree over one shard's segments.
    masks[i]: bool[n_pad] — (match & live) for segment i from the query
    phase; either host numpy or a device array (kept on device, MaskView).
    scores[i]: optional f32[n_pad] score row per segment (top_hits needs it).
    query_parser: compiles filter/filters sub-queries (avoids circular import).
    """
    if query_parser is not None \
            and not isinstance(query_parser, _ShardScopedParser):
        query_parser = _ShardScopedParser(query_parser, segments)
    masks = [_mv(m) for m in masks]
    if scores is None:
        scores = [None] * len(segments)
    partials = {}
    for spec in specs:
        if spec.type == "terms":
            partials[spec.name] = _collect_terms_shard(
                spec, segments, masks, query_parser, scores)
            continue
        if spec.type == "significant_terms":
            partials[spec.name] = _collect_sig_terms_shard(
                spec, segments, masks, query_parser, scores)
            continue
        if spec.type == "children":
            partials[spec.name] = _collect_children_shard(
                spec, segments, masks, query_parser, scores)
            continue
        segs_partials = [
            _collect_one(spec, seg, mask, query_parser, scores_row=sc)
            for seg, mask, sc in zip(segments, masks, scores)]
        merged = segs_partials[0] if segs_partials else _empty_partial(spec)
        for p in segs_partials[1:]:
            merged = merge_partial(spec, merged, p)
        partials[spec.name] = merged
    return partials


def _collect_children_shard(spec: AggSpec, segments: list[Segment],
                            masks: list, qp,
                            scores: list | None = None) -> dict:
    """children agg (ref search/aggregations/bucket/children/
    ParentToChildrenAggregator): parent docs in the bucket -> their child
    docs of `type`. The p/c join spans segments (children landed wherever
    their own rows did), so it is a shard-level two-pass: collect parent
    ids, then mask children per segment via the _parent ordinal column.
    Supported at the top of the agg tree (per-bucket sub-agg joins would
    need the cross-segment bucket context)."""
    ctype = str(spec.params.get("type", ""))
    if scores is None:
        scores = [None] * len(segments)
    parent_ids: set = set()
    for seg, mask in zip(segments, masks):
        m = _mv(mask).np
        for r in np.flatnonzero(m[: seg.n_docs]):
            parent_ids.add(seg.ids[r])
    merged = None
    for seg, sc in zip(segments, scores):
        kc = seg.keywords.get("_parent")
        if kc is None:
            continue
        in_set = np.array([v in parent_ids for v in kc.values] + [False])
        ords = np.asarray(kc.ords)
        cmask = in_set[np.where(ords >= 0, ords, len(kc.values))]
        cmask &= np.array(
            [t == ctype for t in seg.types]
            + [False] * (seg.n_pad - seg.n_docs), bool)
        cmask &= seg.live_host
        part = _bucket_entry(spec, seg, cmask, qp, sc)
        merged = part if merged is None else _merge_entry(spec, merged, part)
    if merged is None:
        merged = {"doc_count": 0}
    return {"buckets": {"_children": merged}}


def _merge_entry(spec: AggSpec, a: dict, b: dict) -> dict:
    out = {"doc_count": a["doc_count"] + b["doc_count"]}
    if spec.subs:
        out["subs"] = {s.name: merge_partial(s, a["subs"][s.name],
                                             b["subs"][s.name])
                       for s in spec.subs}
    return out


def _collect_sig_terms_shard(spec: AggSpec, segments: list[Segment],
                             masks: list, qp,
                             scores: list | None = None) -> dict:
    """significant_terms (ref search/aggregations/bucket/significant/
    SignificantTermsAggregator + JLHScore): per-key FOREGROUND counts over
    the query matches and BACKGROUND counts over the whole index travel in
    the partial; the score is computed at render over the merged totals."""
    if scores is None:
        scores = [None] * len(segments)
    fg: dict = {}
    fg_total = 0
    bg_total = 0
    for seg, mask in zip(segments, masks):
        for key, c in _terms_counts(spec, seg, mask).items():
            fg[key] = fg.get(key, 0) + c
        mv = _mv(mask)
        if mv.dev is not None:
            from ...ops.aggs import count_mask
            fg_total += int(np.asarray(count_mask(mv.dev)))
        else:
            fg_total += int(mv.np.sum())
        bg_total += seg.root_live_count
    size = int(spec.params.get("size", 10)) or len(fg) or 1
    shard_size = int(spec.params.get("shard_size", size * 3 + 10))
    top = sorted(fg.items(), key=lambda kv: (-kv[1], str(kv[0])))[:shard_size]
    buckets: dict = {}
    for key, c in top:
        bg = 0
        sub_parts: dict = {}
        # ONE key-mask computation per (key, segment) feeds both the
        # background count and the sub-agg collect
        for seg, mask, sc in zip(segments, masks, scores):
            m_key = _terms_key_mask(spec, seg, key)
            if m_key is None:
                continue
            bg += int((m_key[: seg.n_pad]
                       & seg.root_live_host[: len(m_key)]).sum())
            if spec.subs:
                m = m_key & _mv(mask).np
                for s in spec.subs:
                    part = _collect_one(s, seg, m, qp, scores_row=sc)
                    prev = sub_parts.get(s.name)
                    sub_parts[s.name] = part if prev is None \
                        else merge_partial(s, prev, part)
        entry: dict = {"doc_count": int(c), "bg_count": bg}
        if spec.subs:
            entry["subs"] = {s.name: sub_parts.get(s.name, _empty_partial(s))
                             for s in spec.subs}
        buckets[key] = entry
    return {"buckets": buckets, "fg_total": fg_total, "bg_total": bg_total}


def terms_partial_from_counts(spec: AggSpec, counts: dict) -> dict:
    """Shard-level terms partial from merged per-key counts: shard_size
    truncation + other_doc_count/error_bound accounting. The ONE place the
    truncation order lives — shared by the per-segment collect below and
    the mesh lane's gathered count tensors (parallel/mesh_aggs.py), so the
    two paths can never disagree on which keys a shard reports."""
    size = int(spec.params.get("size", 10)) or len(counts) or 1
    shard_size = int(spec.params.get("shard_size", size * 3 + 10))
    items = sorted(counts.items(), key=lambda kv: (-kv[1], str(kv[0])))
    top = items[:shard_size]
    dropped = items[shard_size:]
    return {"buckets": {key: {"doc_count": int(c)} for key, c in top},
            "other_doc_count": int(sum(c for _, c in dropped)),
            "error_bound": int(top[-1][1]) if dropped else 0}


def _collect_terms_shard(spec: AggSpec, segments: list[Segment],
                         masks: list[np.ndarray], qp,
                         scores: list | None = None) -> dict:
    """Two-pass terms collection with correct shard_size semantics (ref
    bucket/terms/TermsAggregator shard_size over-collection): pass 1 counts
    every key across ALL segments (vectorized, cheap), the top shard_size
    keys are chosen from the MERGED counts, and only for those keys — and
    only if there are sub-aggs — does pass 2 build per-key doc masks.
    Truncation is accounted: other_doc_count + error_bound travel in the
    partial so the coordinator's reduce can report them."""
    counts: dict = {}
    for seg, mask in zip(segments, masks):
        for key, c in _terms_counts(spec, seg, mask).items():
            counts[key] = counts.get(key, 0) + c
    if not spec.subs:
        return terms_partial_from_counts(spec, counts)
    size = int(spec.params.get("size", 10)) or len(counts) or 1
    shard_size = int(spec.params.get("shard_size", size * 3 + 10))
    items = sorted(counts.items(), key=lambda kv: (-kv[1], str(kv[0])))
    top = items[:shard_size]
    dropped = items[shard_size:]
    buckets: dict = {}
    for key, c in top:
        entry: dict = {"doc_count": int(c)}
        if spec.subs:
            if scores is None:
                scores = [None] * len(segments)
            sub_parts: dict = {}
            for seg, mask, sc in zip(segments, masks, scores):
                m = _terms_key_mask(spec, seg, key)
                if m is None:
                    continue
                m = m & _mv(mask).np
                for s in spec.subs:
                    part = _collect_one(s, seg, m, qp, scores_row=sc)
                    prev = sub_parts.get(s.name)
                    sub_parts[s.name] = part if prev is None \
                        else merge_partial(s, prev, part)
            entry["subs"] = {s.name: sub_parts.get(s.name, _empty_partial(s))
                             for s in spec.subs}
        buckets[key] = entry
    return {"buckets": buckets,
            "other_doc_count": int(sum(c for _, c in dropped)),
            "error_bound": int(top[-1][1]) if dropped else 0}


def _terms_counts(spec: AggSpec, seg: Segment, mask) -> dict:
    """Pass 1: key -> doc_count for one segment, fully vectorized. Device
    masks take the fused masked-bincount kernel (ops/aggs.py) — only the
    [V] counts vector crosses to host."""
    mask = _mv(mask)
    field = spec.params["field"]
    kc = seg.keywords.get(field)
    if kc is not None:
        if mask.dev is not None:
            from ...ops.aggs import masked_bincount
            counts = np.asarray(masked_bincount(
                kc.ords, mask.dev, n_bins=len(kc.values)))
        else:
            ords, values = _keyword_column(seg, field)
            sel = mask.np & (ords >= 0)
            counts = np.bincount(ords[sel], minlength=len(values))
        return {kc.values[o]: int(counts[o]) for o in np.nonzero(counts)[0]}
    col = _numeric_column(seg, field)
    if col is not None:
        vals, valid = col
        sel = mask.np & valid[: len(mask.np)]
        uniq, ucounts = np.unique(vals[sel], return_counts=True)
        if vals.dtype.kind == "i":
            return {int(u): int(c) for u, c in zip(uniq, ucounts)}
        return {(int(u) if float(u).is_integer() else float(u)): int(c)
                for u, c in zip(uniq, ucounts)}
    # analyzed text: token counts via the postings lists (fielddata-on-
    # analyzed-string behavior, ref index/fielddata/)
    fx = seg.text.get(field)
    if fx is None:
        return {}
    P = fx.n_postings
    doc_of = np.asarray(fx.doc_ids)[:P]
    term_of = np.repeat(np.arange(len(fx.term_lens)), fx.term_lens)
    hit = mask.np[np.minimum(doc_of, len(mask.np) - 1)]
    counts = np.bincount(term_of[hit], minlength=len(fx.term_lens))
    terms_sorted = list(fx.terms)
    return {terms_sorted[t]: int(counts[t]) for t in np.nonzero(counts)[0]}


def _terms_key_mask(spec: AggSpec, seg: Segment, key) -> np.ndarray | None:
    """Pass 2: bool[n_pad] of docs holding `key` (pre-query-mask)."""
    field = spec.params["field"]
    kw = _keyword_column(seg, field)
    if kw is not None:
        ords, _ = kw
        kc = seg.keywords[field]
        o = kc.ord_of(str(key))
        if o < 0:
            return None
        return ords == o
    col = _numeric_column(seg, field)
    if col is not None:
        vals, valid = col
        return (vals == key) & valid
    fx = seg.text.get(field)
    if fx is None:
        return None
    s, ln, tid = fx.lookup(str(key))
    if tid < 0:
        return None
    m = np.zeros(seg.n_pad, bool)
    m[np.asarray(fx.doc_ids)[s:s + ln]] = True
    return m


def _empty_partial(spec: AggSpec) -> dict:
    if spec.type == "terms":
        return {"buckets": {}, "other_doc_count": 0, "error_bound": 0}
    if spec.type == "significant_terms":
        return {"buckets": {}, "fg_total": 0, "bg_total": 0}
    if spec.type in BUCKET_TYPES:
        return {"buckets": {}}
    if spec.type == "top_hits":
        return {"total": 0, "top": []}
    if spec.type == "geo_bounds":
        return {"top": -math.inf, "bottom": math.inf,
                "left": math.inf, "right": -math.inf}
    if spec.type == "scripted_metric":
        return {"states": []}
    return _metric_collect(spec, np.zeros(0), np.zeros(0, bool))


def _collect_one(spec: AggSpec, seg: Segment, mask,
                 qp=None, scores_row=None) -> dict:
    if spec.type == "top_hits":
        return _top_hits_segment(spec, seg, _mv(mask).np, scores_row)
    if spec.type == "terms":               # as a sub-aggregation
        return _collect_terms_shard(spec, [seg], [mask], qp, [scores_row])
    if spec.type == "significant_terms":   # as a sub-aggregation
        return _collect_sig_terms_shard(spec, [seg], [mask], qp,
                                        [scores_row])
    if spec.type in METRIC_TYPES:
        return _metric_segment(spec, seg, mask)
    return _bucket_segment(spec, seg, _mv(mask), qp, scores_row)


def _top_hits_segment(spec: AggSpec, seg: Segment, mask: np.ndarray,
                      scores_row) -> dict:
    """top_hits (ref metrics/tophits/TopHitsAggregator): the top-scoring
    matched docs of the enclosing bucket, as real hit dicts so partials
    merge across segments and shards by score."""
    size = int(spec.params.get("size", 3))
    sel = np.flatnonzero(mask[: seg.n_pad])
    sel = sel[sel < seg.n_docs]
    if scores_row is not None and len(sel):
        sc = np.asarray(scores_row)[sel].astype(np.float64)
        order = np.argsort(-sc, kind="stable")[:size]
    else:
        sc = None
        order = np.arange(min(size, len(sel)))
    hits = []
    for j in order:
        d = int(sel[j])
        hits.append({"_id": seg.ids[d], "_type": seg.types[d],
                     "_score": float(sc[j]) if sc is not None else None,
                     "_source": seg.stored[d]})
    return {"total": int(mask.sum()), "top": hits}


# -- metric aggs ------------------------------------------------------------

_DEVICE_STATS_TYPES = {"min", "max", "sum", "avg", "value_count", "stats",
                       "extended_stats"}


def _metric_segment(spec: AggSpec, seg: Segment, mask) -> dict:
    mask = _mv(mask)
    field = spec.params.get("field")
    if spec.type in _DEVICE_STATS_TYPES and field and mask.dev is not None:
        nc = seg.numerics.get(field)
        if nc is not None:
            # one fused device program -> a 5-scalar partial
            from ...ops.aggs import masked_stats
            cnt, s, ss, mn, mx = np.asarray(
                masked_stats(nc.vals, nc.missing, mask.dev))
            return {"count": int(cnt), "sum": float(s), "sum_sq": float(ss),
                    "min": float(mn) if cnt else math.inf,
                    "max": float(mx) if cnt else -math.inf}
    mask = mask.np
    if spec.type == "geo_bounds" and field:
        # ref search/aggregations/metrics/geobounds/GeoBoundsAggregator
        la = _numeric_column(seg, f"{field}.lat")
        lo = _numeric_column(seg, f"{field}.lon")
        if la is None or lo is None:
            return {"top": -math.inf, "bottom": math.inf,
                    "left": math.inf, "right": -math.inf}
        sel = mask & la[1][:len(mask)] & lo[1][:len(mask)]
        if not sel.any():
            return {"top": -math.inf, "bottom": math.inf,
                    "left": math.inf, "right": -math.inf}
        lats = la[0][sel]
        lons = lo[0][sel]
        return {"top": float(lats.max()), "bottom": float(lats.min()),
                "left": float(lons.min()), "right": float(lons.max())}
    if spec.type == "scripted_metric":
        # ref search/aggregations/metrics/scripted/ScriptedMetricAggregator:
        # init/map per doc (AST-whitelisted dialect, script/engine.py),
        # combine per segment; partials carry per-segment states for the
        # final reduce_script at render time
        from ...script.engine import run_agg_script
        params = dict(spec.params.get("params") or {})
        agg: dict = {}
        if spec.params.get("init_script"):
            run_agg_script(spec.params["init_script"], {"_agg": agg},
                           params)
        map_src = spec.params.get("map_script")
        if map_src:
            from ...script.engine import doc_values_view
            for d in np.flatnonzero(mask[: seg.n_docs]):
                d = int(d)
                if not seg.live_host[d] or seg.types[d].startswith("__"):
                    continue
                # same doc['field'].value accessor view as script queries
                # and script_fields — one dialect everywhere
                run_agg_script(
                    map_src,
                    {"_agg": agg, "doc": doc_values_view(seg.stored[d]),
                     "_source": seg.stored[d]}, params)
        state = agg
        if spec.params.get("combine_script"):
            out = run_agg_script(spec.params["combine_script"],
                                 {"_agg": agg}, params)
            if out is not None:
                state = out
        return {"states": [state]}
    if spec.type == "cardinality" and field:
        kw = _keyword_column(seg, field)
        if kw is not None:
            ords, values = kw
            sel = mask & (ords >= 0)
            uniq = np.unique(ords[sel])
            hll = HyperLogLog()
            hll.add([values[o] for o in uniq])
            return {"hll": hll}
        if field in seg.text:   # distinct tokens among matched docs
            fx = seg.text[field]
            doc_of = np.asarray(fx.doc_ids)[:fx.n_postings]
            term_of = np.repeat(np.arange(len(fx.term_lens)), fx.term_lens)
            hit = mask[np.minimum(doc_of, len(mask) - 1)]
            terms_sorted = list(fx.terms)
            hll = HyperLogLog()
            hll.add([terms_sorted[t] for t in np.unique(term_of[hit])])
            return {"hll": hll}
    col = _numeric_column(seg, field) if field else None
    if col is None:
        return _metric_collect(spec, np.zeros(0), np.zeros(0, bool))
    vals, valid = col
    n = min(len(mask), len(valid))
    return _metric_collect(spec, vals[:n], valid[:n] & mask[:n])


def _metric_collect(spec: AggSpec, vals: np.ndarray, sel: np.ndarray) -> dict:
    v = vals[sel] if len(vals) else vals
    if spec.type == "cardinality":
        hll = HyperLogLog()
        hll.add_hashes(_hash64(v))
        return {"hll": hll}
    if spec.type == "percentiles":
        td = TDigest()
        td.add(v)
        return {"tdigest": td,
                "percents": spec.params.get("percents",
                                            [1, 5, 25, 50, 75, 95, 99])}
    count = int(v.size)
    vf = v.astype(np.float64, copy=False)   # stats in f64 (i64*i64 overflows)
    return {"count": count, "sum": float(vf.sum()) if count else 0.0,
            "min": float(vf.min()) if count else math.inf,
            "max": float(vf.max()) if count else -math.inf,
            "sum_sq": float((vf * vf).sum()) if count else 0.0}


# -- bucket aggs ------------------------------------------------------------

def _col_minmax(seg: Segment, field: str, nc) -> tuple[float, float]:
    """Cached (min, max) of a numeric column — one device reduction per
    immutable segment, reused by every histogram over it."""
    cache = getattr(seg, "_minmax_cache", None)
    if cache is None:
        cache = {}
        seg._minmax_cache = cache
    if field not in cache:
        from ...ops.aggs import col_minmax
        mn, mx = np.asarray(col_minmax(nc.vals, nc.missing))
        cache[field] = (float(mn), float(mx))
    return cache[field]


_FIXED_INTERVAL_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
                      "d": 86_400_000, "w": 7 * 86_400_000}
_MAX_DEVICE_BINS = 1 << 14


def _fixed_interval_ms(interval: str) -> float | None:
    m = re.match(r"^(\d+)?\s*(ms|s|m|h|d|w|second|minute|hour|day|week)$",
                 str(interval).strip())
    if not m:
        return None
    mult = int(m.group(1) or 1)
    unit = {"second": "s", "minute": "m", "hour": "h", "day": "d",
            "week": "w"}.get(m.group(2), m.group(2))
    return float(mult * _FIXED_INTERVAL_MS[unit])


def _device_histogram(spec: AggSpec, seg: Segment, mv: "MaskView",
                      nc, interval: float) -> dict | None:
    """Leaf histogram collect fused on device (VERDICT r4 #3): bucket id =
    affine transform of the column, ONE bincount per (segment, agg); only
    the counts vector crosses to host. None -> host fallback (sub-aggs
    need per-bucket masks; huge ranges exceed the bin cap)."""
    if spec.subs or mv.dev is None or interval <= 0:
        return None
    mn, mx = _col_minmax(seg, spec.params["field"], nc)
    if not np.isfinite(mn) or not np.isfinite(mx):
        return {"buckets": {}}
    base = math.floor(mn / interval) * interval
    n_bins = int((mx - base) // interval) + 1
    if n_bins > _MAX_DEVICE_BINS:
        return None
    from ...ops.aggs import masked_histogram
    counts = np.asarray(masked_histogram(
        nc.vals, nc.missing, mv.dev, base, float(interval), n_bins=n_bins))
    out = {}
    for i in np.nonzero(counts)[0]:
        out[float(base + i * interval)] = {"doc_count": int(counts[i])}
    return {"buckets": out}


def _bucket_segment(spec: AggSpec, seg: Segment, mask,
                    qp=None, scores_row=None) -> dict:
    """Compute per-doc bucket keys, then vectorized counts + sub-collects.
    Leaf histogram/date_histogram/range over numeric columns collect ON
    DEVICE (ops/aggs.py kernels) when the query mask is device-resident."""
    t = spec.type
    p = spec.params
    n = seg.n_pad
    mv = _mv(mask)

    if t in ("histogram", "date_histogram", "range", "date_range") \
            and mv.dev is not None and not spec.subs:
        field = p.get("field")
        nc = seg.numerics.get(field) if field else None
        if nc is not None:
            if t == "histogram":
                r = _device_histogram(spec, seg, mv, nc,
                                      float(p["interval"]))
                if r is not None:
                    return r
            elif t == "date_histogram":
                iv = _fixed_interval_ms(p.get("interval", "1d"))
                if iv is not None:
                    r = _device_histogram(spec, seg, mv, nc, iv)
                    if r is not None:
                        return r
            else:   # range / date_range: all ranges in one device program
                bounds = _range_bounds(p, is_date=(t == "date_range"))
                if bounds is not None:
                    keys, los, his = bounds
                    from ...ops.aggs import masked_ranges
                    counts = np.asarray(masked_ranges(
                        nc.vals, nc.missing, mv.dev, los, his))
                    out = {}
                    for (key, lo, hi), cnt in zip(keys, counts):
                        out[key] = {"doc_count": int(cnt),
                                    "from": lo, "to": hi}
                    return {"buckets": out}

    mask = mv.np

    if t == "composite":
        return _composite_segment(spec, seg, mask)

    if t == "global":   # ignores the query: all live docs (ref bucket/global/)
        live = np.asarray(seg.live)
        return {"buckets": {"_global": _bucket_entry(
            spec, seg, live, qp, scores_row)}}

    if t == "nested":
        # switch the doc set from ROOT rows to this path's nested block
        # rows whose root is in the current bucket (ref search/aggregations/
        # bucket/nested/NestedAggregator.java — child-doc iteration becomes
        # one parent-gather over the block-join column)
        path = str(p.get("path", ""))
        kc = seg.keywords.get("_nested_path")
        child = np.zeros(n, bool)
        if kc is not None and seg.parent_of is not None:
            o = kc.ord_of(path)
            if o >= 0:
                is_child = (np.asarray(kc.ords) == o) \
                    & seg.live_host & (seg.parent_of >= 0)
                child = is_child & mask[np.maximum(seg.parent_of, 0)]
        return {"buckets": {"_nested": _bucket_entry(spec, seg, child, qp,
                                                     scores_row)}}

    if t == "reverse_nested":
        # back out of nested context to the root docs (ref bucket/nested/
        # ReverseNestedAggregator.java; path-targeted variants reduce to
        # the root here because parent_of always points at the root row)
        roots = np.zeros(n, bool)
        if seg.parent_of is not None:
            sel = np.flatnonzero(mask & (seg.parent_of >= 0))
            roots[seg.parent_of[sel]] = True
            roots &= seg.root_live_host
        return {"buckets": {"_reverse": _bucket_entry(spec, seg, roots, qp,
                                                      scores_row)}}

    if t == "filter":
        sub_mask = _filter_mask(p, seg, qp)
        m = mask & sub_mask
        return {"buckets": {"_filter": _bucket_entry(spec, seg, m, qp,
                                                     scores_row)}}

    if t == "filters":
        out = {}
        flt = p.get("filters", {})
        for fname, fspec in flt.items():
            m = mask & _filter_mask_query(fspec, seg, qp)
            out[fname] = _bucket_entry(spec, seg, m, qp, scores_row)
        return {"buckets": out}

    if t == "missing":
        field = p["field"]
        col = _numeric_column(seg, field)
        kw = _keyword_column(seg, field)
        txt = _text_present_mask(seg, field)
        if col is not None:
            miss = ~col[1]
        elif kw is not None:
            miss = kw[0] < 0
        elif txt is not None:
            miss = ~txt   # analyzed field: "has it" == any posting
        else:
            miss = np.ones(n, bool)
        m = mask & miss[:len(mask)]
        return {"buckets": {"_missing": _bucket_entry(spec, seg, m, qp,
                                                      scores_row)}}

    if t in ("histogram", "date_histogram"):
        field = p["field"]
        col = _numeric_column(seg, field)
        if col is None:
            return {"buckets": {}}
        vals, valid = col
        sel = mask & valid[:len(mask)]
        if t == "histogram":
            interval = float(p["interval"])
            if vals.dtype.kind == "i" and interval.is_integer():
                step = int(interval)   # exact int bucketing for longs
                keys = (vals // step) * step
            else:
                keys = np.floor(vals.astype(np.float64) / interval) * interval
        else:
            keys = _date_round(vals, str(p.get("interval", "1d")))
        out = {}
        for u in np.unique(keys[sel]):
            m = sel & (keys == u)
            out[float(u)] = _bucket_entry(spec, seg, m, qp, scores_row)
        return {"buckets": out}

    if t in ("range", "date_range"):
        field = p["field"]
        col = _numeric_column(seg, field)
        if col is None:
            return {"buckets": {}}
        vals, valid = col
        sel = mask & valid[:len(mask)]
        out = {}
        for r in p.get("ranges", []):
            key, lo, hi = _resolve_range(r, is_date=(t == "date_range"))
            m = sel.copy()
            if lo is not None:
                m &= vals >= float(lo)
            if hi is not None:
                m &= vals < float(hi)
            e = _bucket_entry(spec, seg, m, qp, scores_row)
            e["from"] = lo
            e["to"] = hi
            out[key] = e
        return {"buckets": out}

    if t == "geohash_grid":
        # ref search/aggregations/bucket/geogrid/GeoHashGridAggregator:
        # bucket key = the doc's geohash cell at `precision`
        field = p["field"]
        la = _numeric_column(seg, f"{field}.lat")
        lo = _numeric_column(seg, f"{field}.lon")
        if la is None or lo is None:
            return {"buckets": {}}
        from ..geo import encode_geohash
        precision = int(p.get("precision", 5))
        sel = mask & la[1][:len(mask)] & lo[1][:len(mask)]
        idx = np.flatnonzero(sel)
        keys = np.array([encode_geohash(float(la[0][d]), float(lo[0][d]),
                                        precision) for d in idx])
        out = {}
        for u in np.unique(keys) if len(idx) else []:
            m = np.zeros(n, bool)
            m[idx[keys == u]] = True
            out[str(u)] = _bucket_entry(spec, seg, m, qp, scores_row)
        return {"buckets": out}

    if t == "geo_distance":
        # ref search/aggregations/bucket/range/geodistance/
        # GeoDistanceParser: range buckets over haversine distance from an
        # origin point, in the requested unit
        from ..geo import parse_geo_point, unit_meters
        field = p["field"]
        la = _numeric_column(seg, f"{field}.lat")
        lo = _numeric_column(seg, f"{field}.lon")
        if la is None or lo is None:
            return {"buckets": {}}
        from ..geo import haversine_m
        olat, olon = parse_geo_point(p["origin"])
        unit = unit_meters(str(p.get("unit", "m")))
        dist = np.asarray(haversine_m(olat, olon, la[0], lo[0])) / unit
        sel = mask & la[1][:len(mask)] & lo[1][:len(mask)]
        out = {}
        for r in p.get("ranges", []):
            lo_v = r.get("from")
            hi_v = r.get("to")
            key = r.get("key") or (
                f"{'*' if lo_v is None else float(lo_v)}-"
                f"{'*' if hi_v is None else float(hi_v)}")
            m = sel.copy()
            if lo_v is not None:
                m &= dist >= float(lo_v)
            if hi_v is not None:
                m &= dist < float(hi_v)
            e = _bucket_entry(spec, seg, m, qp, scores_row)
            e["from"] = None if lo_v is None else float(lo_v)
            e["to"] = None if hi_v is None else float(hi_v)
            out[key] = e
        return {"buckets": out}

    if t == "sampler":
        # ref search/aggregations/bucket/sampler/SamplerAggregator: sub-aggs
        # run over only the TOP-scoring shard_size matched docs
        shard_size = int(p.get("shard_size", 100))
        sel = np.flatnonzero(mask)
        if scores_row is not None and len(sel) > shard_size:
            sc = np.asarray(scores_row)[sel].astype(np.float64)
            keep = sel[np.argsort(-sc, kind="stable")[:shard_size]]
        else:
            keep = sel[:shard_size]
        m = np.zeros(n, bool)
        m[keep] = True
        return {"buckets": {"_sample": _bucket_entry(spec, seg, m, qp,
                                                     scores_row)}}

    if t == "children":
        raise AggregationParsingException(
            "children aggregation is supported at the top of the agg tree "
            "(the parent/child join needs cross-segment bucket context)")
    raise AggregationParsingException(f"unsupported bucket agg [{t}]")


def _bucket_entry(spec: AggSpec, seg: Segment, mask: np.ndarray, qp,
                  scores_row=None) -> dict:
    entry = {"doc_count": int(mask.sum())}
    if spec.subs:
        entry["subs"] = {
            s.name: _collect_one(s, seg, mask, qp, scores_row=scores_row)
            for s in spec.subs}
    return entry


# -- composite agg ----------------------------------------------------------

def _comp_norm(v) -> int | float:
    """Normalize a numeric composite key element to a plain python value —
    ints stay exact ints (snowflake ids, epoch millis), integral floats
    collapse to int so the after-key round-trips through JSON unchanged."""
    if isinstance(v, (int, np.integer)):
        return int(v)
    f = float(v)
    return int(f) if f.is_integer() else f


def _composite_segment(spec: AggSpec, seg: Segment, mask: np.ndarray) -> dict:
    """composite collect over one segment (ref search/aggregations/bucket/
    composite/CompositeAggregator, backported to the 2.0 framework): each
    source produces a per-doc key column; docs missing ANY source value
    drop (ES composite default); the per-source columns factorize via
    np.unique and combine into one packed code, so the whole segment's
    tuple counting is a single bincount — no per-bucket python loop.
    Partial: {"buckets": {key_tuple: {"doc_count": n}}} — tuples are
    hashable, so the generic cross-segment/shard merge applies as-is."""
    n = seg.n_pad
    sel = mask[:n].copy()
    cols: list[tuple[str, np.ndarray, Any]] = []   # (kind, per-doc, vocab)
    for _sname, stype, sp in _composite_sources(spec):
        field = sp.get("field")
        if stype == "terms":
            kw = seg.keywords.get(field)
            if kw is not None:
                ords = np.asarray(kw.ords)[:n]
                sel &= ords >= 0
                cols.append(("kw", ords, kw.values))
                continue
        col = _numeric_column(seg, field)
        if col is None:
            return {"buckets": {}}
        vals, valid = col
        vals, valid = vals[:n], valid[:n]
        if stype == "terms":
            keys = vals
        elif stype == "histogram":
            interval = float(sp["interval"])
            if vals.dtype.kind == "i" and interval.is_integer():
                keys = (vals // int(interval)) * int(interval)
            else:
                keys = np.floor(vals.astype(np.float64)
                                / interval) * interval
        else:   # date_histogram
            keys = _date_round(vals, str(sp.get("interval", "1d")))
        sel &= valid[: len(sel)]
        cols.append(("num", keys, None))
    idx = np.flatnonzero(sel)
    if not len(idx):
        return {"buckets": {}}
    codes = np.zeros(len(idx), np.int64)
    uniqs: list[tuple[str, np.ndarray, Any]] = []
    for kind, arr, vocab in cols:
        u, inv = np.unique(arr[idx], return_inverse=True)
        uniqs.append((kind, u, vocab))
        codes = codes * np.int64(len(u)) + inv
    cu, ccounts = np.unique(codes, return_counts=True)
    buckets: dict = {}
    for code, cnt in zip(cu, ccounts):
        parts = []
        c = int(code)
        for kind, u, vocab in reversed(uniqs):
            c, i = divmod(c, len(u))
            v = u[i]
            parts.append(str(vocab[int(v)]) if kind == "kw"
                         else _comp_norm(v))
        buckets[tuple(reversed(parts))] = {"doc_count": int(cnt)}
    return {"buckets": buckets}


def _comp_sort_key(key: tuple) -> tuple:
    """Total order over composite key tuples: per element, strings sort
    among strings and numbers among numbers (type tag first), so mixed
    after-key inputs from JSON can never raise on comparison."""
    return tuple(("s", v) if isinstance(v, str) else ("n", float(v))
                 for v in key)


def _render_composite(spec: AggSpec, p: dict) -> dict:
    """Render after the global merge: sort the merged bucket space
    ascending, drop everything <= `after`, truncate to `size`, and emit
    `after_key` = the last returned bucket. Because the sort runs over the
    FULLY merged partials (every lane funnels through the same reduce),
    consecutive pages are a disjoint exact cover of the bucket space and
    identical on every serving lane."""
    names = [s[0] for s in _composite_sources(spec)]
    size = int(spec.params.get("size", 10))
    items = sorted(p.get("buckets", {}).items(),
                   key=lambda kv: _comp_sort_key(kv[0]))
    after = spec.params.get("after")
    if after:
        missing = [nm for nm in names if nm not in after]
        if missing:
            raise AggregationParsingException(
                f"composite [{spec.name}]: after key is missing sources "
                f"{missing}")
        ak = _comp_sort_key(tuple(after[nm] for nm in names))
        items = [kv for kv in items if _comp_sort_key(kv[0]) > ak]
    page = items[:size]
    out: dict = {"buckets": [
        {"key": dict(zip(names, k)), "doc_count": e["doc_count"]}
        for k, e in page]}
    if page:
        out["after_key"] = dict(zip(names, page[-1][0]))
    return out


def _filter_mask(params: dict, seg: Segment, qp) -> np.ndarray:
    return _filter_mask_query(params, seg, qp)


def _filter_mask_query(query_spec: dict, seg: Segment, qp) -> np.ndarray:
    """Compile + run a filter query against one segment -> bool[n_pad]."""
    if qp is None:
        raise AggregationParsingException(
            "filter aggregation requires a query parser")
    from ..query_dsl import SegmentContext, CollectionStats
    node = qp.parse(query_spec)
    terms_by_field: dict[str, set] = {}
    node.collect_terms(terms_by_field)
    stats = CollectionStats.from_segments([seg], terms_by_field)
    _, match = node.execute(SegmentContext(seg, 1, stats))
    return np.asarray(match)[0] & np.asarray(seg.live)


def _range_key(lo, hi) -> str:
    fmt = lambda x: "*" if x is None else (  # noqa: E731
        str(int(x)) if float(x).is_integer() else str(float(x)))
    return f"{fmt(lo)}-{fmt(hi)}"


def _resolve_range(r: dict, is_date: bool) -> tuple[str, float | None, float | None]:
    """Resolve a range spec's bounds (date-math for date_range) and its
    bucket key — the SINGLE place keys are derived, used by both collect and
    render so they can never disagree."""
    lo, hi = r.get("from"), r.get("to")
    if is_date:
        from ..query_parser import eval_date_math
        lo = eval_date_math(str(lo)) if isinstance(lo, str) else lo
        hi = eval_date_math(str(hi)) if isinstance(hi, str) else hi
    return r.get("key", _range_key(lo, hi)), lo, hi


# -- date rounding ----------------------------------------------------------

_FIXED_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
             "d": 86_400_000}
_DAY = 86_400_000


def _date_round(ms: np.ndarray, interval: str) -> np.ndarray:
    """Round epoch-millis to bucket starts. Fixed units on the value array;
    calendar units (week/month/quarter/year) via exact calendar math
    (ref common/rounding/TimeZoneRounding.java, UTC only)."""
    iv = interval.strip()
    m = re.match(r"^(\d+)?\s*(ms|s|m|h|d|w|M|q|y|minute|hour|day|week|month|"
                 r"quarter|year|second)$", iv)
    if not m:
        raise AggregationParsingException(f"bad interval [{interval}]")
    n = int(m.group(1) or 1)
    unit = {"second": "s", "minute": "m", "hour": "h", "day": "d",
            "week": "w", "month": "M", "quarter": "q", "year": "y"}.get(
                m.group(2), m.group(2))
    if unit in _FIXED_MS:
        step = n * _FIXED_MS[unit]
        return np.floor_divide(ms, step) * step
    days = np.floor_divide(ms, _DAY).astype(np.int64)
    if unit == "w":
        # 1970-01-01 is a Thursday; ISO weeks start Monday
        dow = (days + 3) % 7
        start = (days - dow) * _DAY
        return start.astype(np.float64)
    d64 = days.astype("datetime64[D]")
    if unit == "M":
        mo = d64.astype("datetime64[M]")
        if n > 1:
            mo_i = mo.astype(np.int64)
            mo = (np.floor_divide(mo_i, n) * n).astype("datetime64[M]")
        return mo.astype("datetime64[ms]").astype(np.int64).astype(np.float64)
    if unit == "q":
        mo_i = d64.astype("datetime64[M]").astype(np.int64)
        q = np.floor_divide(mo_i, 3) * 3
        return q.astype("datetime64[M]").astype("datetime64[ms]") \
            .astype(np.int64).astype(np.float64)
    # year
    y = d64.astype("datetime64[Y]")
    if n > 1:
        y_i = y.astype(np.int64)
        y = (np.floor_divide(y_i, n) * n).astype("datetime64[Y]")
    return y.astype("datetime64[ms]").astype(np.int64).astype(np.float64)


# ---------------------------------------------------------------------------
# Reduce: merge partials (segments, then shards)
# ---------------------------------------------------------------------------

def merge_partial(spec: AggSpec, a: dict, b: dict) -> dict:
    if spec.type in METRIC_TYPES:
        return _merge_metric(spec, a, b)
    out = dict(a)
    if spec.type == "terms":
        out["other_doc_count"] = a.get("other_doc_count", 0) \
            + b.get("other_doc_count", 0)
        out["error_bound"] = a.get("error_bound", 0) + b.get("error_bound", 0)
    if spec.type == "significant_terms":
        out["fg_total"] = a.get("fg_total", 0) + b.get("fg_total", 0)
        out["bg_total"] = a.get("bg_total", 0) + b.get("bg_total", 0)
    buckets = dict(a.get("buckets", {}))
    for key, eb in b.get("buckets", {}).items():
        ea = buckets.get(key)
        if ea is None:
            buckets[key] = eb
        else:
            merged = {"doc_count": ea["doc_count"] + eb["doc_count"]}
            if "bg_count" in ea or "bg_count" in eb:
                merged["bg_count"] = ea.get("bg_count", 0) \
                    + eb.get("bg_count", 0)
            for extra in ("from", "to"):
                if extra in ea:
                    merged[extra] = ea[extra]
            if spec.subs:
                merged["subs"] = {
                    s.name: merge_partial(s, ea["subs"][s.name],
                                          eb["subs"][s.name])
                    for s in spec.subs}
            buckets[key] = merged
    out["buckets"] = buckets
    return out


def _merge_metric(spec: AggSpec, a: dict, b: dict) -> dict:
    if spec.type == "top_hits":
        size = int(spec.params.get("size", 3))
        merged = a.get("top", []) + b.get("top", [])
        merged.sort(key=lambda h: -(h["_score"]
                                    if h["_score"] is not None else -1e300))
        return {"total": a.get("total", 0) + b.get("total", 0),
                "top": merged[:size]}
    if spec.type == "cardinality":
        return {"hll": a["hll"].merge(b["hll"])}
    if spec.type == "percentiles":
        return {"tdigest": a["tdigest"].merge(b["tdigest"]),
                "percents": a.get("percents", b.get("percents"))}
    if spec.type == "geo_bounds":
        return {"top": max(a["top"], b["top"]),
                "bottom": min(a["bottom"], b["bottom"]),
                "left": min(a["left"], b["left"]),
                "right": max(a["right"], b["right"])}
    if spec.type == "scripted_metric":
        return {"states": a.get("states", []) + b.get("states", [])}
    return {"count": a["count"] + b["count"], "sum": a["sum"] + b["sum"],
            "min": min(a["min"], b["min"]), "max": max(a["max"], b["max"]),
            "sum_sq": a["sum_sq"] + b["sum_sq"]}


def merge_shard_partials(specs: list[AggSpec], shard_partials: list[dict]) -> dict:
    """The cross-shard aggregation reduce
    (ref SearchPhaseController.merge:282-399 InternalAggregations.reduce)."""
    out: dict = {}
    for spec in specs:
        parts = [sp[spec.name] for sp in shard_partials if spec.name in sp]
        if not parts:
            out[spec.name] = _empty_partial(spec)
            continue
        merged = parts[0]
        for p in parts[1:]:
            merged = merge_partial(spec, merged, p)
        out[spec.name] = merged
    return out


# ---------------------------------------------------------------------------
# Render: ES 2.0 response shapes
# ---------------------------------------------------------------------------

def _decimal_format(pattern: str, v) -> str:
    """Minimal Java DecimalFormat: literal prefix/suffix around a numeric
    pattern of #/0/,/. — fraction digits from the 0s/#s after the point
    (ref org.elasticsearch.search.aggregations ValueFormatter.Number)."""
    import re as _re
    m = _re.search(r"[#0][#0,.]*", pattern)
    if not m:
        return pattern
    num = m.group(0)
    prefix, suffix = pattern[:m.start()], pattern[m.end():]
    if "." in num:
        frac = num.split(".", 1)[1]
        min_frac = frac.count("0")
        max_frac = len(frac)
        s = f"{float(v):.{max_frac}f}"
        if max_frac > min_frac:
            # strip OPTIONAL (#) fraction digits only, never below min_frac
            ip, fp = s.split(".")
            fp = fp[:min_frac] + fp[min_frac:].rstrip("0")
            s = ip + ("." + fp if fp else "")
    else:
        s = str(int(round(float(v))))
    if "," in num:
        parts = s.split(".")
        parts[0] = f"{int(parts[0]):,}"
        s = ".".join(parts)
    return prefix + s + suffix


def _iso(ms: float) -> str:
    return datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc) \
        .strftime("%Y-%m-%dT%H:%M:%S.") + f"{int(ms) % 1000:03d}Z"


def render(specs: list[AggSpec], partials: dict) -> dict:
    return {spec.name: _render_one(spec, partials[spec.name])
            for spec in specs}


def _render_one(spec: AggSpec, p: dict) -> dict:
    t = spec.type
    if t in METRIC_TYPES:
        return _render_metric(spec, p)

    if t == "composite":
        return _render_composite(spec, p)

    buckets = p.get("buckets", {})

    def rb(key, entry, key_field=True):
        b: dict = {}
        if key_field:
            b["key"] = key
        b["doc_count"] = entry["doc_count"]
        for extra in ("from", "to"):
            if extra in entry and entry[extra] is not None:
                b[extra] = entry[extra]
        for s in spec.subs:
            b[s.name] = _render_one(s, entry.get("subs", {}).get(
                s.name, _empty_partial(s)))
        return b

    if t == "terms":
        size = int(spec.params.get("size", 10)) or len(buckets)
        order = spec.params.get("order", {"_count": "desc"})
        if isinstance(order, list):       # ES list form: primary key first
            order = order[0] if order else {"_count": "desc"}
        if not isinstance(order, dict) or not order:
            order = {"_count": "desc"}
        okey, odir = next(iter(order.items()))
        reverse = odir == "desc"
        items = list(buckets.items())
        if okey == "_term":
            items.sort(key=lambda kv: str(kv[0]), reverse=reverse)
        else:
            # _count ties break by term ascending, like the reference's
            # InternalTerms comparator — otherwise equal-count buckets come
            # out in shard-merge order, nondeterministic across layouts
            # tie-break is term ASCENDING in both directions (ref
            # InternalOrder CompoundOrder always appends term(true))
            items.sort(key=lambda kv: str(kv[0]))
            items.sort(key=lambda kv: kv[1]["doc_count"], reverse=reverse)
        top = items[:size]
        other = sum(e["doc_count"] for _, e in items[size:]) \
            + p.get("other_doc_count", 0)
        return {"doc_count_error_upper_bound": p.get("error_bound", 0),
                "sum_other_doc_count": other,
                "buckets": _apply_pipelines(
                    spec, [rb(k, e) for k, e in top])}

    if t == "significant_terms":
        # JLH score (ref bucket/significant/heuristics/JLHScore.java):
        # (fgp - bgp) * (fgp / bgp), only for fgp > bgp
        fg_total = max(p.get("fg_total", 0), 1)
        bg_total = max(p.get("bg_total", 0), 1)
        size = int(spec.params.get("size", 10)) or len(buckets)
        scored = []
        for k, e in buckets.items():
            fgp = e["doc_count"] / fg_total
            bgp = max(e.get("bg_count", e["doc_count"]), 1) / bg_total
            if fgp <= bgp:
                continue
            score = (fgp - bgp) * (fgp / bgp)
            scored.append((score, k, e))
        scored.sort(key=lambda x: (-x[0], str(x[1])))
        out_buckets = []
        for score, k, e in scored[:size]:
            b = rb(k, e)
            b["score"] = score
            b["bg_count"] = e.get("bg_count", 0)
            out_buckets.append(b)
        return {"doc_count": p.get("fg_total", 0), "buckets": out_buckets}

    if t == "histogram":
        items = sorted(buckets.items(), key=lambda kv: kv[0])
        min_count = int(spec.params.get("min_doc_count", 1))
        fmt = spec.params.get("format")
        out = []
        for k, e in items:
            if e["doc_count"] < min_count:
                continue
            b = rb(k, e)
            if fmt:
                b["key_as_string"] = _decimal_format(fmt, k)
            out.append(b)
        return {"buckets": _apply_pipelines(spec, out)}

    if t == "date_histogram":
        items = sorted(buckets.items(), key=lambda kv: kv[0])
        min_count = int(spec.params.get("min_doc_count", 1))
        out = []
        for k, e in items:
            if e["doc_count"] < min_count:
                continue
            b = rb(int(k), e)
            b["key_as_string"] = _iso(k)
            out.append(b)
        return {"buckets": _apply_pipelines(spec, out)}

    if t in ("range", "date_range"):
        ordered = []
        for r in spec.params.get("ranges", []):
            key, _, _ = _resolve_range(r, is_date=(t == "date_range"))
            if key in buckets:
                ordered.append((key, buckets[key]))
        return {"buckets": [rb(k, e) for k, e in ordered]}

    if t == "filters":
        return {"buckets": {k: rb(k, e, key_field=False)
                            for k, e in buckets.items()}}

    if t == "geohash_grid":
        size = int(spec.params.get("size", 10_000)) or len(buckets)
        items = sorted(buckets.items(), key=lambda kv: str(kv[0]))
        items.sort(key=lambda kv: kv[1]["doc_count"], reverse=True)
        return {"buckets": [rb(k, e) for k, e in items[:size]]}

    if t == "geo_distance":
        ordered = []
        for r in spec.params.get("ranges", []):
            lo_v, hi_v = r.get("from"), r.get("to")
            key = r.get("key") or (
                f"{'*' if lo_v is None else float(lo_v)}-"
                f"{'*' if hi_v is None else float(hi_v)}")
            if key in buckets:
                ordered.append((key, buckets[key]))
        return {"buckets": [rb(k, e) for k, e in ordered]}

    # filter / global / missing / sampler: single anonymous bucket
    entry = next(iter(buckets.values()), {"doc_count": 0})
    out = {"doc_count": entry["doc_count"]}
    for s in spec.subs:
        out[s.name] = _render_one(s, entry.get("subs", {}).get(
            s.name, _empty_partial(s)))
    return out


def _render_metric(spec: AggSpec, p: dict) -> dict:
    t = spec.type
    if t == "top_hits":
        hits = p.get("top", [])
        scores = [h["_score"] for h in hits if h["_score"] is not None]
        return {"hits": {"total": p.get("total", 0),
                         "max_score": max(scores) if scores else None,
                         "hits": hits}}
    if t == "cardinality":
        return {"value": p["hll"].cardinality()}
    if t == "geo_bounds":
        if p["top"] == -math.inf:
            return {}                  # no located docs: empty bounds
        return {"bounds": {
            "top_left": {"lat": p["top"], "lon": p["left"]},
            "bottom_right": {"lat": p["bottom"], "lon": p["right"]}}}
    if t == "scripted_metric":
        states = p.get("states", [])
        reduce_src = spec.params.get("reduce_script")
        if reduce_src:
            from ...script.engine import run_agg_script
            value = run_agg_script(
                reduce_src, {"_aggs": states},
                dict(spec.params.get("params") or {}))
            return {"value": value}
        return {"value": states if len(states) != 1 else states[0]}
    if t == "percentiles":
        td = p["tdigest"]
        percents = p.get("percents") or [1, 5, 25, 50, 75, 95, 99]
        return {"values": {f"{float(pc)}": td.quantile(float(pc) / 100.0)
                           for pc in percents}}
    count, s = p["count"], p["sum"]
    if t == "value_count":
        return {"value": count}
    if t == "sum":
        return {"value": s}
    if t == "min":
        return {"value": p["min"] if count else None}
    if t == "max":
        return {"value": p["max"] if count else None}
    if t == "avg":
        return {"value": (s / count) if count else None}
    avg = s / count if count else None
    base = {"count": count, "min": p["min"] if count else None,
            "max": p["max"] if count else None, "avg": avg, "sum": s}
    if t == "stats":
        return base
    # extended_stats
    if count:
        var = max(p["sum_sq"] / count - (s / count) ** 2, 0.0)
        base.update({"sum_of_squares": p["sum_sq"], "variance": var,
                     "std_deviation": math.sqrt(var)})
    else:
        base.update({"sum_of_squares": 0.0, "variance": None,
                     "std_deviation": None})
    return base


# ---------------------------------------------------------------------------
# Pipeline aggregations (host-side, post-reduce)
# ---------------------------------------------------------------------------

def _bucket_path_value(bucket: dict, path) -> float | None:
    """Resolve a buckets_path against one RENDERED bucket (pipelines run
    after sub-agg rendering, so values read from response shapes):
    `_count` -> doc_count, `agg` -> agg.value, `agg.prop` -> that stat,
    `a>b.prop` descends nested single-bucket aggs. None = gap."""
    path = str(path).strip()
    if path == "_count":
        return float(bucket.get("doc_count", 0))
    node: Any = bucket
    parts = [s.strip() for s in path.split(">")]
    for hop in parts[:-1]:
        node = node.get(hop) if isinstance(node, dict) else None
        if node is None:
            return None
    last = parts[-1]
    if last == "_count":
        val = node.get("doc_count") if isinstance(node, dict) else None
    else:
        if "." in last:
            name, prop = last.rsplit(".", 1)
        else:
            name, prop = last, "value"
        inner = node.get(name) if isinstance(node, dict) else None
        val = inner.get(prop) if isinstance(inner, dict) else None
    if isinstance(val, bool) or not isinstance(val, (int, float)):
        return None
    return float(val)


def _apply_pipelines(spec: AggSpec, buckets: list[dict]) -> list[dict]:
    """Apply this spec's pipeline children over the final sorted bucket
    list, in declaration order (a later pipeline may read an earlier
    one's output through its buckets_path)."""
    for ps in spec.pipelines:
        _apply_one_pipeline(ps, buckets)
    return buckets


def _apply_one_pipeline(ps: AggSpec, buckets: list[dict]) -> None:
    path = ps.params.get("buckets_path")
    if ps.type == "derivative":
        # ref pipeline/derivative/DerivativePipelineAggregator: value =
        # current - previous; gap_policy "skip" carries the last non-null
        # value forward, and the first bucket never emits
        prev = None
        for b in buckets:
            v = _bucket_path_value(b, path)
            if v is not None and prev is not None:
                b[ps.name] = {"value": v - prev}
            if v is not None:
                prev = v
        return
    if ps.type == "cumulative_sum":
        # ref pipeline/cumulativesum/: running total, gaps add 0 and the
        # sum is emitted on EVERY bucket (insert_zeros semantics)
        total = 0.0
        for b in buckets:
            v = _bucket_path_value(b, path)
            total += v if v is not None else 0.0
            b[ps.name] = {"value": total}
        return
    if ps.type == "moving_avg":
        # ref pipeline/movavg/ simple model: trailing mean over the last
        # `window` non-null values INCLUDING the current bucket; gaps
        # neither emit nor perturb the window
        window = int(ps.params.get("window", 5))
        if window <= 0:
            raise AggregationParsingException(
                f"moving_avg [{ps.name}]: window must be positive")
        ring: list[float] = []
        for b in buckets:
            v = _bucket_path_value(b, path)
            if v is None:
                continue
            ring.append(v)
            if len(ring) > window:
                ring.pop(0)
            b[ps.name] = {"value": sum(ring) / len(ring)}
        return
    # bucket_script (ref pipeline/bucketscript/): resolve every named
    # path; any gap skips the bucket; the expression runs through the
    # SAME AST-whitelisted engine as script fields — both `params.x`
    # and bare `x` name forms resolve
    paths: dict = ps.params.get("buckets_path") or {}
    script = ps.params.get("script")
    base_params = {}
    if isinstance(script, dict):
        base_params = dict(script.get("params") or {})
    from ...script.engine import run_search_script
    for b in buckets:
        vals = {k: _bucket_path_value(b, pth) for k, pth in paths.items()}
        if any(v is None for v in vals.values()):
            continue
        try:
            out = run_search_script(script, {}, {**base_params, **vals},
                                    extra_names=vals)
        except AggregationParsingException:
            raise
        except Exception as e:  # noqa: BLE001 — surface as a 400, not a 500
            raise AggregationParsingException(
                f"bucket_script [{ps.name}] failed: {e}") from e
        if isinstance(out, (int, float)) and not isinstance(out, bool):
            b[ps.name] = {"value": float(out)}
