"""Merging t-digest for the percentiles aggregation.

The mergeable-sketch analog of the reference's T-Digest dependency
(pom.xml:278, used by search/aggregations/metrics/percentiles/ —
InternalPercentiles reduce merges per-shard digests). Implements the
"merging digest" variant: buffer values, sort, and re-cluster into centroids
whose sizes respect the k-scale function q(1-q), giving high resolution at
the tails.
"""

from __future__ import annotations

import numpy as np


class TDigest:
    def __init__(self, compression: float = 100.0,
                 means: np.ndarray | None = None,
                 weights: np.ndarray | None = None):
        self.compression = compression
        self.means = means if means is not None else np.zeros(0)
        self.weights = weights if weights is not None else np.zeros(0)
        self._buf: list[np.ndarray] = []

    def add(self, values: np.ndarray) -> None:
        v = np.asarray(values, np.float64).ravel()
        if v.size:
            self._buf.append(v)
        if sum(b.size for b in self._buf) > 8192:
            self._compress()

    def merge(self, other: "TDigest") -> "TDigest":
        out = TDigest(self.compression)
        self._compress()
        other._compress()
        out._buf = []
        m = np.concatenate([self.means, other.means])
        w = np.concatenate([self.weights, other.weights])
        out.means, out.weights = m, w
        out._compress()
        return out

    def _compress(self) -> None:
        if self._buf:
            vals = np.concatenate(self._buf)
            self._buf = []
            m = np.concatenate([self.means, vals])
            w = np.concatenate([self.weights, np.ones(vals.size)])
        else:
            m, w = self.means, self.weights
        if m.size == 0:
            self.means, self.weights = m, w
            return
        order = np.argsort(m, kind="stable")
        m, w = m[order], w[order]
        total = w.sum()
        # greedy left-to-right clustering under the k1 scale-function bound
        out_m, out_w = [], []
        cur_m, cur_w, seen = m[0], w[0], 0.0
        for i in range(1, m.size):
            q = (seen + cur_w / 2) / total
            limit = 4 * total * q * (1 - q) / self.compression
            if cur_w + w[i] <= max(limit, 1.0):
                cur_m = (cur_m * cur_w + m[i] * w[i]) / (cur_w + w[i])
                cur_w += w[i]
            else:
                out_m.append(cur_m)
                out_w.append(cur_w)
                seen += cur_w
                cur_m, cur_w = m[i], w[i]
        out_m.append(cur_m)
        out_w.append(cur_w)
        self.means = np.asarray(out_m)
        self.weights = np.asarray(out_w)

    def quantile(self, q: float) -> float:
        self._compress()
        if self.means.size == 0:
            return float("nan")
        if self.means.size == 1:
            return float(self.means[0])
        total = self.weights.sum()
        target = q * total
        # centroid cumulative midpoints, linear interpolation between them
        cum = np.cumsum(self.weights) - self.weights / 2
        if target <= cum[0]:
            return float(self.means[0])
        if target >= cum[-1]:
            return float(self.means[-1])
        i = int(np.searchsorted(cum, target) - 1)
        frac = (target - cum[i]) / (cum[i + 1] - cum[i])
        return float(self.means[i] + frac * (self.means[i + 1] - self.means[i]))
