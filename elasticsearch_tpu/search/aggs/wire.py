"""Wire codec for per-shard aggregation partials.

The reference streams InternalAggregation objects between data nodes and the
coordinating node (Streamable readFrom/writeTo; SearchPhaseController.merge
then reduces them). Here partials cross the transport seam as JSON-safe
trees: HLL registers travel as tagged bytes, t-digest centroids as float
lists, bucket maps as [key, entry] PAIR LISTS so non-string keys (histogram
floats, numeric terms) survive JSON — a plain dict would stringify them and
desynchronize the cross-shard merge.
"""

from __future__ import annotations

import numpy as np

from .aggregators import AggSpec, BUCKET_TYPES, METRIC_TYPES
from .hll import HyperLogLog
from .tdigest import TDigest


def _key_to_wire(k):
    if isinstance(k, np.integer):
        return int(k)
    if isinstance(k, np.floating):
        return float(k)
    if isinstance(k, (np.str_, np.bool_)):
        return k.item()
    if isinstance(k, tuple):     # composite key tuples ride as JSON lists
        return [_key_to_wire(v) for v in k]
    return k


def _num(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def partial_to_wire(spec: AggSpec, p: dict) -> dict:
    t = spec.type
    if t == "cardinality":
        hll: HyperLogLog = p["hll"]
        return {"hll": {"p": hll.p, "regs": hll.registers.tobytes()}}
    if t == "percentiles":
        td: TDigest = p["tdigest"]
        td._compress()
        return {"tdigest": {"means": [float(x) for x in td.means],
                            "weights": [float(x) for x in td.weights],
                            "compression": td.compression},
                "percents": p.get("percents")}
    if t == "top_hits":
        return {"total": _num(p.get("total", 0)),
                "top": [{k: (_num(v) if k == "_score" else v)
                         for k, v in h.items()} for h in p.get("top", [])]}
    if t in METRIC_TYPES:
        return {k: _num(v) for k, v in p.items()}
    # bucket aggs: encode the bucket map as pairs, recurse into subs
    out: dict = {k: _num(v) for k, v in p.items() if k != "buckets"}
    pairs = []
    for key, entry in p.get("buckets", {}).items():
        e: dict = {k: _num(v) for k, v in entry.items() if k != "subs"}
        if "subs" in entry:
            e["subs"] = {s.name: partial_to_wire(s, entry["subs"][s.name])
                         for s in spec.subs}
        pairs.append([_key_to_wire(key), e])
    out["buckets"] = pairs
    return out


def partial_from_wire(spec: AggSpec, w: dict) -> dict:
    t = spec.type
    if t == "cardinality":
        regs = np.frombuffer(w["hll"]["regs"], np.uint8).copy()
        return {"hll": HyperLogLog(precision=w["hll"]["p"], registers=regs)}
    if t == "percentiles":
        td = TDigest(compression=w["tdigest"].get("compression", 100.0),
                     means=np.asarray(w["tdigest"]["means"], np.float64),
                     weights=np.asarray(w["tdigest"]["weights"], np.float64))
        return {"tdigest": td, "percents": w.get("percents")}
    if t == "top_hits" or t in METRIC_TYPES:
        return dict(w)
    out = {k: v for k, v in w.items() if k != "buckets"}
    buckets = {}
    for key, e in w.get("buckets", []):
        if spec.type == "composite":   # JSON list -> hashable key tuple
            key = tuple(key)
        entry = {k: v for k, v in e.items() if k != "subs"}
        if "subs" in e:
            entry["subs"] = {s.name: partial_from_wire(s, e["subs"][s.name])
                             for s in spec.subs}
        buckets[key] = entry
    out["buckets"] = buckets
    return out


def partials_to_wire(specs: list[AggSpec], partials: dict) -> dict:
    return {s.name: partial_to_wire(s, partials[s.name])
            for s in specs if s.name in partials}


def partials_from_wire(specs: list[AggSpec], wire: dict) -> dict:
    return {s.name: partial_from_wire(s, wire[s.name])
            for s in specs if s.name in wire}
