"""Aggregations (ref search/aggregations/, SURVEY.md §2.6)."""

from .aggregators import (
    AggSpec, AggregationParsingException, parse_aggs, collect_shard,
    merge_partial, merge_shard_partials, render,
    BUCKET_TYPES, METRIC_TYPES, PIPELINE_TYPES,
)
from .hll import HyperLogLog
from .tdigest import TDigest

__all__ = [
    "AggSpec", "AggregationParsingException", "parse_aggs", "collect_shard",
    "merge_partial", "merge_shard_partials", "render",
    "BUCKET_TYPES", "METRIC_TYPES", "PIPELINE_TYPES", "HyperLogLog",
    "TDigest",
]
