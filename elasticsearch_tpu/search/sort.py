"""Multi-key field sort: parsing, device sort-key construction, value
materialization, and host comparators for the cross-segment / cross-shard
reduce.

Design (ref search/sort/SortParseElement.java:68, index/fielddata
comparators): within a segment, docs are selected ON DEVICE by a
lexicographic top-k over f64 comparator keys — keyword keys are the
segment's lexicographically-sorted ordinals, so intra-segment order is
exact. Across segments and shards ordinals are NOT comparable, so every
merge step compares *materialized* values (the actual strings / numbers)
instead: selection stays on device, the host k-way merge compares only
k real values per shard, never ordinals. This is the "materialize at
reduce time" strategy and is also what makes the `sort` array in the
response carry real values.

Sorting an analyzed text field is rejected with a 400, like the
reference's "can't sort on analyzed fields" fielddata errors
(ref index/fielddata/plain/PagedBytesIndexFieldData + SortParseElement).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np
import jax.numpy as jnp

from ..mapping import mapper as m
from .query_dsl import QueryParsingException

SCORE = "_score"
DOC = "_doc"
GEO = "_geo_distance"

# large-but-finite missing fill: +/-inf is reserved for "not a match"
_BIG = float(np.finfo(np.float64).max) / 4


@dataclasses.dataclass(frozen=True)
class SortSpec:
    """One sort key (ref search/sort/FieldSortBuilder +
    GeoDistanceSortParser for _geo_distance keys)."""
    field: str                 # field path, "_score", "_doc", "_geo_distance"
    order: str = "asc"         # "asc" | "desc"
    missing: Any = "_last"     # "_first" | "_last" | numeric literal
    unmapped_ok: bool = False  # ignore_unmapped / unmapped_type given
    geo_field: str | None = None    # _geo_distance: the geo_point field
    geo_lat: float = 0.0
    geo_lon: float = 0.0
    geo_unit: str = "m"


def parse_sort(sort_spec, mappers) -> list[SortSpec] | None:
    """Normalize the ES sort clause into a list of SortSpec, validating each
    field against the mapping. Returns None for the default score sort.

    Accepts: "field" | {"field": "desc"} | {"field": {...params}} | a list
    of any of those (ref search/sort/SortParseElement.java:68-121).
    """
    if sort_spec is None:
        return None
    items = sort_spec if isinstance(sort_spec, list) else [sort_spec]
    specs: list[SortSpec] = []
    for item in items:
        if isinstance(item, str):
            field, params = item, {}
        elif isinstance(item, dict):
            if len(item) != 1:
                raise QueryParsingException(
                    f"sort clause must have exactly one field: {item}")
            (field, params), = item.items()
            if isinstance(params, str):
                params = {"order": params}
            elif not isinstance(params, dict):
                raise QueryParsingException(
                    f"malformed sort parameters for [{field}]")
        else:
            raise QueryParsingException(f"malformed sort clause: {item!r}")
        if field == GEO:
            # {"_geo_distance": {"<field>": <point>, "order", "unit"}}
            # (ref search/sort/GeoDistanceSortParser)
            from .geo import parse_geo_point, unit_meters
            params = dict(params)
            order = params.pop("order", "asc")
            unit = params.pop("unit", "m")
            unit_meters(unit)    # validate (accepts long forms too)
            params.pop("distance_type", None)
            params.pop("mode", None)
            if len(params) != 1:
                raise QueryParsingException(
                    "_geo_distance sort needs exactly one geo field")
            (gfield, point), = params.items()
            lat, lon = parse_geo_point(point)
            specs.append(SortSpec(field=GEO, order=order,
                                  geo_field=gfield, geo_lat=lat,
                                  geo_lon=lon, geo_unit=unit))
            continue
        order = params.get("order", "desc" if field == SCORE else "asc")
        if order not in ("asc", "desc"):
            raise QueryParsingException(f"illegal sort order [{order}]")
        missing = params.get("missing", "_last")
        if isinstance(missing, str) and missing not in ("_first", "_last"):
            # ES parses numeric-string missing values ("50") as numbers
            try:
                missing = float(missing)
            except ValueError:
                raise QueryParsingException(
                    f"illegal missing value [{missing}] for [{field}]; "
                    f"expected _first, _last, or a number") from None
        unmapped_ok = bool(params.get("ignore_unmapped")) \
            or "unmapped_type" in params
        specs.append(SortSpec(field=field, order=order, missing=missing,
                              unmapped_ok=unmapped_ok))
    if not specs or (len(specs) == 1 and specs[0].field == SCORE
                     and specs[0].order == "desc"):
        return None  # the default: score descending
    for sp in specs:
        _validate(sp, mappers)
    return specs


def _validate(sp: SortSpec, mappers) -> None:
    """mappers: one MapperService or a list of them (multi-index search).
    A field mapped sortable in ANY index is allowed — other indices treat
    it as missing, like the reference. Analyzed text anywhere is a 400."""
    if sp.field in (SCORE, DOC, GEO) or mappers is None:
        return
    svcs = mappers if isinstance(mappers, (list, tuple)) else [mappers]
    fts = [svc.field_type(sp.field) for svc in svcs if svc is not None]
    for ft in fts:
        if ft is None:
            continue
        # analyzed TEXT sorts via uninverted fielddata (min/max term per
        # doc — Lucene MultiValueMode over fielddata; Segment.text_fielddata)
        if ft.type in (m.DENSE_VECTOR, m.OBJECT, m.GEO_POINT):
            raise QueryParsingException(
                f"can't sort on field [{sp.field}] of type [{ft.type}]")
    if all(ft is None for ft in fts) and not sp.unmapped_ok:
        raise QueryParsingException(
            f"No mapping found for [{sp.field}] in order to sort on")


# ---------------------------------------------------------------------------
# Device comparator keys (per segment)
# ---------------------------------------------------------------------------

def _raw_key(seg, sp: SortSpec, scores, Q: int, seg_idx: int = 0,
             shard_id: int = 0):
    """(vals f64 [Q,N] or [N], missing bool [N] or None) before order/fill."""
    if sp.field == SCORE:
        return scores.astype(jnp.float64), None
    if sp.field == DOC:
        # shard<<42 | seg<<32 | local: a TOTAL order across shards AND
        # segments (exact in f64 below 2^53) — the scroll cursor tiebreak.
        # Same-key collisions across shards would make strict-after cursors
        # skip docs, so the shard id must be part of the key.
        return (jnp.float64((shard_id << 42) + (seg_idx << 32))
                + jnp.arange(seg.n_pad, dtype=jnp.float64)), None
    if sp.field == GEO:
        return _geo_distance_m(seg, sp)
    nc = seg.numerics.get(sp.field)
    if nc is not None:
        return nc.vals.astype(jnp.float64), nc.missing
    kc = seg.keywords.get(sp.field)
    if kc is not None:
        return kc.ords.astype(jnp.float64), kc.ords < 0
    fd = seg.text_fielddata(sp.field)
    if fd is not None:
        mn, mx, miss, _, _ = fd
        # MultiValueMode: asc compares each doc's MIN term, desc its MAX
        ords = mn if sp.order == "asc" else mx
        return jnp.asarray(ords, jnp.float64), jnp.asarray(miss)
    return (jnp.zeros((seg.n_pad,), jnp.float64),
            jnp.ones((seg.n_pad,), bool))


def _geo_distance_m(seg, sp: SortSpec):
    """(distance-in-meters f64[N], missing bool[N]) for a _geo_distance key
    — haversine over the <field>.lat/.lon doc-value columns (the same fused
    expression GeoDistanceNode uses, via the shared geo helper)."""
    from .geo import haversine_m
    la = seg.numerics.get(f"{sp.geo_field}.lat")
    lo = seg.numerics.get(f"{sp.geo_field}.lon")
    if la is None or lo is None:
        return (jnp.zeros((seg.n_pad,), jnp.float64),
                jnp.ones((seg.n_pad,), bool))
    return haversine_m(sp.geo_lat, sp.geo_lon, la.vals, lo.vals), la.missing


_GEO_CACHE_MAX = 4    # per segment: per-request origins must not pile up


def _geo_distance_np(seg, sp: SortSpec):
    """Bounded cached host mirror of _geo_distance_m — materialization
    touches k hits, not one device round-trip per hit. A per-segment
    common.cache.Cache holds at most _GEO_CACHE_MAX origins (LRU, byte-
    weighed): a different-origin-per-request workload would otherwise grow
    n_pad*9 bytes per origin, unbounded and unobservable."""
    from ..common.cache import Cache
    cache = getattr(seg, "_geo_dist_cache", None)
    if cache is None:
        cache = Cache("geo_distance", max_entries=_GEO_CACHE_MAX,
                      weigher=lambda v: v[0].nbytes + v[1].nbytes)
        seg._geo_dist_cache = cache
    key = (sp.geo_field, sp.geo_lat, sp.geo_lon)
    hit = cache.get(key)
    if hit is None:
        dist, miss = _geo_distance_m(seg, sp)
        hit = (np.asarray(dist), np.asarray(miss))
        cache.put(key, hit)
    return hit


def segment_keys(seg, specs: Sequence[SortSpec], scores, Q: int,
                 seg_idx: int = 0, shard_id: int = 0) -> list:
    """Ascending-comparable f64 keys, one [Q, n_pad] array per sort key.

    desc keys are negated; missing docs filled with +/-_BIG so _first/_last
    placement survives the negation. _score is a valid sort key here because
    the query phase always has per-doc scores in hand.
    """
    out = []
    for sp in specs:
        vals, miss = _raw_key(seg, sp, scores, Q, seg_idx, shard_id)
        if miss is not None and _is_number(sp.missing):
            vals = jnp.where(miss, jnp.float64(float(sp.missing)), vals)
            miss = None
        if sp.order == "desc":
            vals = -vals
        if miss is not None:
            fill = jnp.float64(_BIG if sp.missing == "_last" else -_BIG)
            vals = jnp.where(miss, fill, vals)
        if vals.ndim == 1:
            vals = jnp.broadcast_to(vals[None, :], (Q, seg.n_pad))
        out.append(vals)
    return out


def after_mask(seg, specs: Sequence[SortSpec], cursor: Sequence,
               keys: list) -> Any:
    """bool [Q, n_pad]: docs strictly after `cursor` in sort order
    (ref search/searchafter semantics: resume exactly past the last hit).

    `keys` are the arrays from segment_keys (desc already negated), so
    "after" is simply lexicographically-greater on the encoded keys; the
    cursor values get the same encoding. Keyword cursors map onto the
    segment's ordinal space via binary search; values absent from the
    segment land between ordinals (x.5) so strict comparison stays exact.
    """
    if len(cursor) != len(specs):
        raise QueryParsingException(
            f"search_after must have {len(specs)} values, one per sort key")
    enc: list[float] = []
    for sp, cv in zip(specs, cursor):
        enc.append(_encode_cursor(seg, sp, cv))
    after = jnp.zeros(keys[0].shape, bool)
    for key_arr, c in zip(reversed(keys), reversed(enc)):
        c = jnp.float64(c)
        after = (key_arr > c) | ((key_arr == c) & after)
    return after


def _encode_cursor(seg, sp: SortSpec, cv) -> float:
    """Map one user-facing cursor value into the same comparable f64 space
    as segment_keys produced for this segment."""
    if cv is None:
        c = _BIG if sp.missing == "_last" else -_BIG
        return c  # fills are sign-fixed, not order-negated
    if sp.field == GEO:
        from .geo import unit_meters
        c = float(cv) * unit_meters(sp.geo_unit)  # cursor is in sort units
        return -c if sp.order == "desc" else c
    if sp.field not in (SCORE, DOC) and sp.field not in seg.numerics \
            and sp.field not in seg.keywords and sp.field not in seg.text:
        # the segment has no column for this field: every doc's key here is
        # the +/-_BIG missing fill, so any real cursor value compares as 0
        # (strictly between the fills) — never parse the cursor itself
        return 0.0
    if sp.field in seg.keywords:
        kc = seg.keywords[sp.field]
        s = str(cv)
        pos = _bisect(kc.values, s)
        if pos < len(kc.values) and kc.values[pos] == s:
            c = float(pos)
        else:
            c = pos - 0.5   # between ordinals: nothing compares equal
    elif sp.field not in seg.numerics and sp.field in seg.text:
        vocab = seg.text_fielddata(sp.field)[3]
        s = str(cv)
        pos = _bisect(vocab, s)
        c = float(pos) if pos < len(vocab) and vocab[pos] == s else pos - 0.5
    else:
        try:
            c = float(cv)
        except (TypeError, ValueError) as e:
            raise QueryParsingException(
                f"bad search_after value {cv!r} for [{sp.field}]") from e
    return -c if sp.order == "desc" else c


def _bisect(values: list[str], x: str) -> int:
    import bisect
    return bisect.bisect_left(values, x)


# ---------------------------------------------------------------------------
# Host-side value materialization + merge comparators
# ---------------------------------------------------------------------------

def materialize(seg, specs: Sequence[SortSpec], local: int, score: float,
                doc_key: int, shard_id: int = 0) -> list:
    """Real user-facing sort values for one doc (the response `sort` array).
    None = missing. Strings for keywords, numbers for numerics."""
    out: list = []
    for sp in specs:
        if sp.field == SCORE:
            out.append(float(score))
            continue
        if sp.field == DOC:
            out.append((shard_id << 42) + int(doc_key))
            continue
        if sp.field == GEO:
            dist, miss = _geo_distance_np(seg, sp)
            if miss[local]:
                out.append(None)
            else:
                from .geo import unit_meters
                out.append(float(dist[local]) / unit_meters(sp.geo_unit))
            continue
        nc = seg.numerics.get(sp.field)
        if nc is not None:
            vals, miss = _host_numeric(nc)
            if miss[local]:
                out.append(float(sp.missing) if _is_number(sp.missing)
                           else None)
            else:
                v = vals[local]
                out.append(int(v) if nc.dtype == "i64" else float(v))
            continue
        kc = seg.keywords.get(sp.field)
        if kc is not None:
            o = _host_ords(kc)[local]
            out.append(None if o < 0 else kc.values[int(o)])
            continue
        fd = seg.text_fielddata(sp.field)
        if fd is not None:
            mn, mx, miss, vocab, _ = fd
            if miss[local]:
                out.append(None)
            else:
                o = mn[local] if sp.order == "asc" else mx[local]
                out.append(vocab[int(o)])
            continue
        out.append(float(sp.missing) if _is_number(sp.missing) else None)
    return out


def _host_numeric(nc):
    vals = getattr(nc, "_vals_np", None)
    if vals is None:
        vals = np.asarray(nc.vals)
        miss = np.asarray(nc.missing)
        object.__setattr__(nc, "_vals_np", vals)
        object.__setattr__(nc, "_miss_np", miss)
    return vals, nc._miss_np


def _host_ords(kc):
    ords = getattr(kc, "_ords_np", None)
    if ords is None:
        ords = np.asarray(kc.ords)
        object.__setattr__(kc, "_ords_np", ords)
    return ords


class _Rev:
    """Reverses comparison order — desc sort over types (strings) that can't
    be negated numerically."""
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return other.v == self.v


def compare_key(values: Sequence, specs: Sequence[SortSpec]) -> tuple:
    """Turn materialized sort values into a Python-sortable tuple honoring
    per-key order + missing placement — the cross-segment / cross-shard
    merge comparator (ref SearchPhaseController.sortDocs via TopDocs.merge)."""
    out = []
    for v, sp in zip(values, specs):
        if v is None and _is_number(sp.missing):
            v = float(sp.missing)
        if v is None:
            rank = 1 if sp.missing == "_last" else -1
            out.append((rank, 0, 0))
        else:
            # type rank keeps cross-index comparisons total when the same
            # sort field is keyword in one index and numeric in another:
            # numbers < strings < everything else, never str-vs-float
            # TypeError from the cross-shard reduce (advisor r4).
            trank = 0 if _is_number(v) else (1 if isinstance(v, str) else 2)
            if sp.order == "desc":
                out.append((0, -trank, _Rev(v)))   # desc mirrors asc exactly
            else:
                out.append((0, trank, v))
    return tuple(out)


def _is_number(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def normalize(sort) -> list[SortSpec] | None:
    """Accept legacy single-key dicts ({"field":..., "order":...}) used by
    internal callers/tests, or an already-parsed SortSpec list."""
    if sort is None:
        return None
    if isinstance(sort, dict):
        return [SortSpec(field=sort["field"],
                         order=sort.get("order", "asc"),
                         missing=sort.get("missing", "_last"),
                         unmapped_ok=True)]
    return list(sort)
