"""Segment-stacked dense execution: one device program over a shard's stack.

The dense query phase used to pay one kernel dispatch AND one host
`device_fetch` round-trip per segment, sequentially — ~G serialized device
RTTs per shard per query batch on any index that hasn't been force-merged.
This module packs a shard's live segments into pow2-bucketed stacked tensors
with a leading segment axis `[G_pad, ...]` (the packing idiom of
parallel/packed.py, applied to segments instead of shards), executes the
parsed DSL tree ONCE over the stack (vmap / leading-axis broadcast), and
fuses per-segment totals, the masked row-max and the cross-segment top-k
merge into one jitted reduce — so the whole shard comes down to host in ONE
`device_fetch` instead of one per segment.

Shapes are pow2-bucketed on every axis (G_pad segments, N_pad docs, P_pad
postings) so refresh→query cycles that stay inside the same bucket reuse
every jit cache entry — zero retraces (tests/test_no_retrace.py tripwire).

Node coverage: the columnar/text node types that dominate dense traffic
(match/term/terms/range/exists/ids/bool/constant_score/dis_max/boosting)
execute natively over the stack via vmapped kernels. Every OTHER node type
goes through `_generic_exec`, which runs the node's ordinary per-segment
`execute` and stacks the padded results — per-node dispatches stay
per-segment for those, but the query still performs exactly one
`device_fetch` per shard (the reduce below). Sorted / search_after paths
and oversized stacks fall back to the per-segment loop entirely
(search/shard_searcher.py).

The packed stack itself is cached on the PR-3 Cache core
(indices/cache_service.SegmentStackCache): keyed by (index, shard,
incarnation, segment-id set), charged to the `fielddata` breaker, and
invalidated by refresh/merge/`_cache/clear`.

When the stack's doc axis exceeds `index.search.block_docs`, the searcher
hands this SAME stack to the streaming blockwise executor
(search/blockwise.execute_stacked): the tree then runs per doc block under
a running on-device top-k instead of materializing `[G, Q, N]` here, and
the cross-segment merge below (`stacked_reduce`'s tail) is reused verbatim
inside its one jitted program — same candidate order, bitwise-identical
results, O(Q × block) peak score memory.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field as dc_field
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..index.segment import Segment, next_pow2
from ..ops import bm25
from .query_dsl import (
    BoolNode, BoostingNode, ConstantScoreNode, DisMaxNode, ExistsNode,
    IdsNode, MatchAllNode, MatchNode, MatchNoneNode, Node, RangeNode,
    SegmentContext, TermFilterNode, _bisect, _coerce_to_column, _next_down,
    _next_up, _pow2_window,
)

SEG_SHIFT = 32


# ---------------------------------------------------------------------------
# The stack: a shard's live segments as leading-axis device tensors
# ---------------------------------------------------------------------------

@dataclass
class StackedTextField:
    """One text field across G segments. CSR starts/lens stay host-side per
    segment (each segment keeps its own term dictionary, exactly like
    per-segment Lucene term dicts); only the postings payload stacks."""
    doc_ids: jax.Array               # i32[G_pad, P_pad] (PAD sentinel = n_pad)
    tf: jax.Array                    # f32[G_pad, P_pad]
    doc_len: jax.Array               # f32[G_pad, N_pad]
    max_postings: int = 0


@dataclass
class StackedKeywordField:
    ords: jax.Array                  # i32[G_pad, N_pad], -1 = missing


@dataclass
class StackedNumericField:
    vals: jax.Array                  # [G_pad, N_pad] i64 | f64
    missing: jax.Array               # bool[G_pad, N_pad]
    dtype: str


@dataclass
class SegmentStack:
    """Immutable packed view of one shard's live (non-empty) segments.

    `segments[g]` is the source Segment of stack row g and `seg_indices[g]`
    its index in the searcher's full segment list — the top-k reduce encodes
    THAT index into doc keys so the fetch phase resolves unchanged.
    Tombstone liveness is NOT baked in: `live_stack()` re-assembles the
    [G_pad, N_pad] mask whenever any segment's live_gen moves, so deletes
    invalidate one device row, never the stack."""
    segments: tuple                  # live Segments, stack-row order
    seg_indices: tuple               # original index per stack row
    g_pad: int
    n_pad: int
    text: dict = dc_field(default_factory=dict)
    keywords: dict = dc_field(default_factory=dict)
    numerics: dict = dc_field(default_factory=dict)
    mixed: frozenset = frozenset()   # fields with inconsistent column kinds
    nbytes: int = 0
    seg_ids_dev: jax.Array | None = None   # i64[G_pad] original seg index

    def __post_init__(self):
        self._live_key = None
        self._live_dev = None

    def live_stack(self) -> jax.Array:
        """bool[G_pad, N_pad] root-doc liveness; padding rows all-False.
        Cached on the segments' tombstone generations."""
        key = tuple(s.live_gen for s in self.segments)
        if self._live_key != key or self._live_dev is None:
            arr = np.zeros((self.g_pad, self.n_pad), bool)
            for gi, seg in enumerate(self.segments):
                arr[gi, : seg.n_pad] = np.asarray(seg.root_live_host)
            self._live_dev = jnp.asarray(arr)
            self._live_key = key
        return self._live_dev


def _field_kinds(segments: Sequence[Segment]):
    text, kw, num = set(), set(), set()
    for seg in segments:
        text.update(seg.text)
        kw.update(seg.keywords)
        num.update(seg.numerics)
    mixed = (text & kw) | (text & num) | (kw & num)
    return text, kw, num, mixed


def estimate_stack_bytes(segments: Sequence[Segment]) -> int:
    """Device bytes a stack over `segments` will occupy — the pre-build
    breaker charge. Mirrors build_stack()'s allocation arithmetic exactly
    so charge and weigher stay balanced."""
    live = [s for s in segments if s.n_docs > 0]
    if not live:
        return 0
    g_pad = next_pow2(len(live), floor=1)
    n_pad = max(s.n_pad for s in live)
    text, kw, num, _ = _field_kinds(live)
    total = g_pad * n_pad + g_pad * 8          # live mask + seg ids
    for f in text:
        p_pad = next_pow2(max((s.text[f].n_postings for s in live
                               if f in s.text), default=1), floor=8)
        total += g_pad * (p_pad * 8 + n_pad * 4)   # doc_ids+tf, doc_len
    total += len(kw) * g_pad * n_pad * 4
    total += len(num) * g_pad * n_pad * 9          # vals(8) + missing(1)
    return total


def build_stack(segments: Sequence[Segment]) -> SegmentStack | None:
    """Pack live segments into the stacked tensors. Empty segments are
    skipped HERE, once, instead of being re-checked inside every query's
    loop. Returns None when there is nothing live to stack. A traced
    request that pays the build sees it as a `stack_build` span — the
    cache-miss cost of the stacked lane, attributed."""
    from ..common import tracing
    with tracing.span("stack_build", segments=sum(
            1 for s in segments if s.n_docs > 0)) as _sp:
        out = _build_stack(segments)
        if _sp is not None and out is not None:
            _sp.attrs["bytes"] = out.nbytes
    return out


def _build_stack(segments: Sequence[Segment]) -> SegmentStack | None:
    rows = [(i, s) for i, s in enumerate(segments) if s.n_docs > 0]
    if not rows:
        return None
    live = [s for _, s in rows]
    g = len(rows)
    g_pad = next_pow2(g, floor=1)
    n_pad = max(s.n_pad for s in live)
    text_f, kw_f, num_f, mixed = _field_kinds(live)
    nbytes = g_pad * n_pad + g_pad * 8

    text: dict[str, StackedTextField] = {}
    for f in sorted(text_f):
        p_max = max((s.text[f].n_postings for s in live if f in s.text),
                    default=1)
        p_pad = next_pow2(p_max, floor=8)
        doc_ids = np.full((g_pad, p_pad), n_pad, np.int32)   # PAD sentinel
        tf = np.zeros((g_pad, p_pad), np.float32)
        doc_len = np.ones((g_pad, n_pad), np.float32)        # 1.0: no div-0
        for gi, seg in enumerate(live):
            fx = seg.text.get(f)
            if fx is None:
                continue
            P = fx.n_postings
            if P:
                src = fx.doc_ids_host if fx.doc_ids_host is not None \
                    else np.asarray(fx.doc_ids)[:P]
                doc_ids[gi, :P] = src[:P]
                tf[gi, :P] = np.asarray(fx.tf)[:P]
            doc_len[gi, : fx.doc_len.shape[0]] = np.asarray(fx.doc_len)
        text[f] = StackedTextField(doc_ids=jnp.asarray(doc_ids),
                                   tf=jnp.asarray(tf),
                                   doc_len=jnp.asarray(doc_len),
                                   max_postings=p_max)
        nbytes += g_pad * (p_pad * 8 + n_pad * 4)

    keywords: dict[str, StackedKeywordField] = {}
    for f in sorted(kw_f):
        ords = np.full((g_pad, n_pad), -1, np.int32)
        for gi, seg in enumerate(live):
            kc = seg.keywords.get(f)
            if kc is not None:
                o = np.asarray(kc.ords)
                ords[gi, : o.shape[0]] = o
        keywords[f] = StackedKeywordField(ords=jnp.asarray(ords))
        nbytes += g_pad * n_pad * 4

    numerics: dict[str, StackedNumericField] = {}
    for f in sorted(num_f):
        dtypes = {s.numerics[f].dtype for s in live if f in s.numerics}
        if len(dtypes) > 1:
            mixed = mixed | {f}      # inconsistent dtype: generic path
            nbytes += g_pad * n_pad * 9   # keep the estimate arithmetic
            continue
        dt = dtypes.pop()
        vals = np.zeros((g_pad, n_pad),
                        np.int64 if dt == "i64" else np.float64)
        missing = np.ones((g_pad, n_pad), bool)
        for gi, seg in enumerate(live):
            nc = seg.numerics.get(f)
            if nc is not None:
                v = np.asarray(nc.vals)
                vals[gi, : v.shape[0]] = v
                missing[gi, : v.shape[0]] = np.asarray(nc.missing)
        numerics[f] = StackedNumericField(vals=jnp.asarray(vals),
                                          missing=jnp.asarray(missing),
                                          dtype=dt)
        nbytes += g_pad * n_pad * 9

    seg_ids = np.zeros(g_pad, np.int64)
    seg_ids[:g] = [i for i, _ in rows]
    return SegmentStack(
        segments=tuple(live), seg_indices=tuple(i for i, _ in rows),
        g_pad=g_pad, n_pad=n_pad, text=text, keywords=keywords,
        numerics=numerics, mixed=frozenset(mixed), nbytes=nbytes,
        seg_ids_dev=jnp.asarray(seg_ids))


# ---------------------------------------------------------------------------
# Stacked kernels: module-level jitted wrappers (stable compile-cache keys)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("W", "n_pad"))
def _bm25_stack(doc_ids, tf, doc_len, starts, lens, weights,
                k1, b, avgdl, *, W: int, n_pad: int):
    """vmap of the dense BM25 kernel over the segment axis: per-segment CSR
    pointers [G,Q,T], shared idf weights [Q,T] -> scores f32[G,Q,n_pad]."""
    def one(di, tfv, dl, st, ln):
        return bm25.bm25_score_batch(di, tfv, dl, st, ln, weights,
                                     k1, b, avgdl, W=W, n_pad=n_pad)
    return jax.vmap(one)(doc_ids, tf, doc_len, starts, lens)


@functools.partial(jax.jit, static_argnames=("W", "n_pad"))
def _classic_stack(doc_ids, tf, doc_len, starts, lens, weights,
                   *, W: int, n_pad: int):
    def one(di, tfv, dl, st, ln):
        return bm25.classic_score_batch(di, tfv, dl, st, ln, weights,
                                        W=W, n_pad=n_pad)
    return jax.vmap(one)(doc_ids, tf, doc_len, starts, lens)


@functools.partial(jax.jit, static_argnames=("W", "n_pad"))
def _term_mask_stack(doc_ids, starts, lens, *, W: int, n_pad: int):
    def one(di, st, ln):
        return bm25.term_match_mask(di, st, ln, W=W, n_pad=n_pad)
    return jax.vmap(one)(doc_ids, starts, lens)


@functools.partial(jax.jit, static_argnames=("k",))
def stacked_reduce(scores, match, live, seg_ids, *, k: int):
    """The fused shard reduce: liveness gate, per-query totals, masked
    row-max, per-segment top-k AND the cross-segment merge (one top-k over
    G·k candidates with segment-encoded keys) — one program, one fetch.

    scores f32[G,Q,N], match bool[G,Q,N], live bool[G,N], seg_ids i64[G]
    -> (keys i64[Q,k'], top f32[Q,k'], total i64[Q], mx f32[Q])."""
    m = match & live[:, None, :]
    total = jnp.sum(m, axis=(0, 2), dtype=jnp.int64)
    masked = jnp.where(m, scores, -jnp.inf)
    mx = masked.max(axis=(0, 2))
    # a single segment holds at most N candidates, but the MERGED winner
    # list may need up to k of the G·kk candidates
    kk = min(k, scores.shape[2])
    top, idx = jax.lax.top_k(masked, kk)                     # [G,Q,kk]
    keys = jnp.where(top > -jnp.inf,
                     (seg_ids[:, None, None] << SEG_SHIFT)
                     | idx.astype(jnp.int64),
                     jnp.int64(-1))
    Q = scores.shape[1]
    # candidate order = segment order then within-segment rank — the same
    # tie order the per-segment loop's stable merge produces, and
    # lax.top_k keeps the lower-index (earlier) candidate on equal scores
    cand_s = jnp.moveaxis(top, 0, 1).reshape(Q, -1)          # [Q, G*kk]
    cand_k = jnp.moveaxis(keys, 0, 1).reshape(Q, -1)
    best, pos = jax.lax.top_k(cand_s, min(k, cand_s.shape[1]))
    return (jnp.take_along_axis(cand_k, pos, axis=1), best, total, mx)


@functools.partial(jax.jit, static_argnames=("k",))
def stacked_sorted_reduce(scores, match, live, seg_ids, keys, cursor,
                          *, k: int):
    """Sorted-shard reduce: the stacked lane's analog of the per-segment
    loop's sort branch, fused into one program (ISSUE 17). The encoded
    key columns (search/sort_encode.py) are comparable across segments,
    so ONE variadic lexicographic `lax.sort` over the flattened [Q, G*N]
    candidate axis replaces the host merge; the dockey operand breaks
    ties in `(seg, local)` order — the loop's `(sort keys, _doc)` cursor
    order bitwise. `cursor` is the encoded search_after row (−inf per
    key = all-pass), applied AFTER totals/mx, exactly like the loop.

    scores f32[G,Q,N], match bool[G,Q,N], live bool[G,N], seg_ids i64[G],
    keys f64[nk,G,N], cursor f64[nk]
    -> (keys i64[Q,k'], top f32[Q,k'], total i64[Q], mx f32[Q])."""
    m = match & live[:, None, :]
    total = jnp.sum(m, axis=(0, 2), dtype=jnp.int64)
    masked = jnp.where(m, scores, -jnp.inf)
    mx = masked.max(axis=(0, 2))
    nk = keys.shape[0]
    after = jnp.zeros(keys.shape[1:], bool)
    for i in range(nk - 1, -1, -1):
        after = (keys[i] > cursor[i]) | ((keys[i] == cursor[i]) & after)
    sel = m & after[:, None, :]
    G, Q, N = match.shape
    dockey = (seg_ids[:, None] << SEG_SHIFT) \
        | jnp.arange(N, dtype=jnp.int64)[None, :]

    def flat(x):                                     # [G,Q,N] -> [Q,G*N]
        return jnp.moveaxis(x, 0, 1).reshape(Q, -1)
    # invalid rows push to the tail: the primary key becomes +inf, and
    # every real key is finite (the largest missing fill is ±_BIG)
    ops = [flat(jnp.where(sel, keys[0][:, None, :], jnp.inf))]
    ops += [flat(jnp.broadcast_to(keys[i][:, None, :], (G, Q, N)))
            for i in range(1, nk)]
    ops.append(flat(jnp.broadcast_to(dockey[:, None, :], (G, Q, N))))
    ops.append(flat(masked))
    out = jax.lax.sort(tuple(ops), num_keys=nk + 1)
    kk = min(k, G * N)
    valid = out[0][:, :kk] < jnp.inf
    return (jnp.where(valid, out[nk][:, :kk], jnp.int64(-1)),
            jnp.where(valid, out[nk + 1][:, :kk], -jnp.inf),
            total, mx)


# ---------------------------------------------------------------------------
# Stacked tree execution
# ---------------------------------------------------------------------------

class StackedContext:
    """Binds a compiled query batch to one shard's SegmentStack — the
    stacked analog of SegmentContext."""

    def __init__(self, stack: SegmentStack, n_queries: int, stats):
        self.stack = stack
        self.Q = n_queries
        self.stats = stats

    @property
    def n_pad(self) -> int:
        return self.stack.n_pad

    @property
    def g_pad(self) -> int:
        return self.stack.g_pad


def _zeros(ctx: StackedContext):
    return jnp.zeros((ctx.g_pad, ctx.Q, ctx.n_pad), jnp.float32)


def _false(ctx: StackedContext):
    return jnp.zeros((ctx.g_pad, ctx.Q, ctx.n_pad), bool)


def _true(ctx: StackedContext):
    return jnp.ones((ctx.g_pad, ctx.Q, ctx.n_pad), bool)


def execute_tree(node: Node, ctx: StackedContext):
    """-> (scores f32[G_pad,Q,N_pad], match bool[G_pad,Q,N_pad]). Node
    types without a stacked handler run their ordinary per-segment execute
    and stack the padded results (_generic_exec) — the final reduce/fetch
    stays fused either way."""
    h = _EXEC.get(type(node))
    if h is None:
        return _generic_exec(node, ctx)
    from ..common.metrics import current_profiler
    prof = current_profiler()
    if prof is None:
        return h(node, ctx)
    t0 = time.perf_counter()
    out = h(node, ctx)
    prof.record_node(type(node).__name__, "score",
                     (time.perf_counter() - t0) * 1000)
    return out


def match_tree(node: Node, ctx: StackedContext):
    """Filter-context stacked evaluation (the match_mask analog)."""
    h = _MATCH.get(type(node))
    if h is None:
        return execute_tree(node, ctx)[1]
    return h(node, ctx)


def _generic_exec(node: Node, ctx: StackedContext):
    """Universal fallback: per-segment execute, results padded to the
    bucket and stacked. Costs per-segment dispatches for THIS node only;
    totals/top-k/fetch stay fused at the shard level."""
    stack, Q, N = ctx.stack, ctx.Q, ctx.n_pad
    rows_s, rows_m = [], []
    for seg in stack.segments:
        s, m = node.execute(SegmentContext(seg, Q, ctx.stats))
        pad = N - seg.n_pad
        if pad:
            s = jnp.pad(s, ((0, 0), (0, pad)))
            m = jnp.pad(m, ((0, 0), (0, pad)))
        rows_s.append(s)
        rows_m.append(m)
    for _ in range(stack.g_pad - len(stack.segments)):
        rows_s.append(jnp.zeros((Q, N), jnp.float32))
        rows_m.append(jnp.zeros((Q, N), bool))
    return jnp.stack(rows_s), jnp.stack(rows_m)


# -- leaf handlers -----------------------------------------------------------

def _h_match_all(node: MatchAllNode, ctx):
    return jnp.full((ctx.g_pad, ctx.Q, ctx.n_pad), node.boost,
                    jnp.float32), _true(ctx)


def _h_match_none(node: MatchNoneNode, ctx):
    return _zeros(ctx), _false(ctx)


def _match_host(node: MatchNode, ctx: StackedContext):
    """Per-segment CSR pointers with a leading G axis + the shared
    (stats-derived, segment-independent) idf weights."""
    stack, Q = ctx.stack, ctx.Q
    T = max((len(t) for t in node.terms_per_query), default=1) or 1
    starts = np.zeros((stack.g_pad, Q, T), np.int32)
    lens = np.zeros((stack.g_pad, Q, T), np.int32)
    weights = np.zeros((Q, T), np.float32)
    n_terms = np.zeros((Q,), np.int32)
    for gi, seg in enumerate(stack.segments):
        s_, l_, w_, n_ = node._host_arrays(SegmentContext(seg, Q, ctx.stats))
        starts[gi], lens[gi] = s_, l_
        weights, n_terms = w_, n_
    return starts, lens, weights, n_terms


def _h_match(node: MatchNode, ctx: StackedContext):
    if node.sim in ("lm_dirichlet", "lm_jm"):
        # LM scoring needs the per-term collection-probability plane the
        # stacked kernels don't carry — the generic per-segment exec is
        # the documented lane for these fields (index/similarity.py)
        return _generic_exec(node, ctx)
    sf = ctx.stack.text.get(node.field_name)
    if sf is None:
        return _zeros(ctx), _false(ctx)
    starts, lens, weights, n_terms = _match_host(node, ctx)
    W = _pow2_window(lens)
    starts_d, lens_d = jnp.asarray(starts), jnp.asarray(lens)
    if node.sim == "classic":
        scores = _classic_stack(sf.doc_ids, sf.tf, sf.doc_len,
                                starts_d, lens_d, jnp.asarray(weights),
                                W=W, n_pad=ctx.n_pad)
    else:
        scores = _bm25_stack(sf.doc_ids, sf.tf, sf.doc_len,
                             starts_d, lens_d, jnp.asarray(weights),
                             jnp.float32(node.k1), jnp.float32(node.b),
                             jnp.float32(ctx.stats.avgdl(node.field_name)),
                             W=W, n_pad=ctx.n_pad)
    if node.operator == "and" or node.minimum_should_match > 1:
        need = np.maximum(node.minimum_should_match, 1) \
            if node.operator != "and" else n_terms
        counts = _bm25_stack(sf.doc_ids, jnp.ones_like(sf.tf),
                             jnp.full_like(sf.doc_len, 1.0),
                             starts_d, lens_d,
                             jnp.asarray(np.ones_like(weights)),
                             jnp.float32(0.0), jnp.float32(0.0),
                             jnp.float32(1.0), W=W, n_pad=ctx.n_pad)
        need_arr = jnp.asarray(np.broadcast_to(
            np.asarray(need, np.float32), (ctx.Q,)))
        match = counts >= jnp.maximum(need_arr, 1.0)[None, :, None]
    else:
        match = scores > 0
    return jnp.where(match, scores, 0.0), match


def _m_match(node: MatchNode, ctx: StackedContext):
    """Presence-only filter mask (the term_match_mask fast path)."""
    if node.operator == "and" or node.minimum_should_match > 1:
        return _h_match(node, ctx)[1]
    sf = ctx.stack.text.get(node.field_name)
    if sf is None:
        return _false(ctx)
    starts, lens, _, _ = _match_host(node, ctx)
    return _term_mask_stack(sf.doc_ids, jnp.asarray(starts),
                            jnp.asarray(lens), W=_pow2_window(lens),
                            n_pad=ctx.n_pad)


def _h_term(node: TermFilterNode, ctx: StackedContext):
    stack, Q = ctx.stack, ctx.Q
    f = node.field_name
    if f in stack.mixed:
        return _generic_exec(node, ctx)
    V = max((len(v) for v in node.values_per_query), default=1) or 1
    kw = stack.keywords.get(f)
    num = stack.numerics.get(f)
    if kw is not None:
        targets = np.full((stack.g_pad, Q, V), -2, np.int64)
        for gi, seg in enumerate(stack.segments):
            kc = seg.keywords.get(f)
            if kc is None:
                continue
            for qi, vals in enumerate(node.values_per_query):
                for vi, v in enumerate(vals):
                    o = kc.ord_of(str(v))
                    if o >= 0:
                        targets[gi, qi, vi] = o
        col = kw.ords.astype(jnp.int64)
        match = (col[:, None, :, None]
                 == jnp.asarray(targets)[:, :, None, :]).any(axis=3)
    elif num is not None:
        if num.dtype == "f64":
            tf64 = np.full((Q, V), np.nan)
            for qi, vals in enumerate(node.values_per_query):
                for vi, v in enumerate(vals):
                    tf64[qi, vi] = float(v)
            match = (num.vals[:, None, :, None]
                     == jnp.asarray(tf64)[None, :, None, :]).any(axis=3)
            match = match & ~num.missing[:, None, :]
            return jnp.where(match, node.boost, 0.0), match
        targets = np.full((Q, V), np.iinfo(np.int64).min, np.int64)
        for qi, vals in enumerate(node.values_per_query):
            for vi, v in enumerate(vals):
                targets[qi, vi] = _coerce_to_column(v, num)
        match = (num.vals[:, None, :, None]
                 == jnp.asarray(targets)[None, :, None, :]).any(axis=3)
        match = match & ~num.missing[:, None, :]
    else:
        if ctx.stack.text.get(f) is None:
            return _zeros(ctx), _false(ctx)
        sub = MatchNode(boost=node.boost, field_name=f,
                        terms_per_query=[[str(v) for v in vals]
                                         for vals in node.values_per_query])
        return _h_match(sub, ctx)
    return jnp.where(match, jnp.float32(node.boost), 0.0), match


def _h_range(node: RangeNode, ctx: StackedContext):
    stack, Q = ctx.stack, ctx.Q
    f = node.field_name
    if f in stack.mixed:
        return _generic_exec(node, ctx)
    num = stack.numerics.get(f)
    kw = stack.keywords.get(f)
    if num is not None:
        if num.dtype == "i64":
            lo_fill, hi_fill = np.iinfo(np.int64).min, np.iinfo(np.int64).max
            dt = np.int64
        else:
            lo_fill, hi_fill = -np.inf, np.inf
            dt = np.float64
        los = np.full(Q, lo_fill, dt)
        his = np.full(Q, hi_fill, dt)
        for qi, (lo, hi, inc_lo, inc_hi) in enumerate(node.bounds_per_query):
            if lo is not None:
                los[qi] = lo if inc_lo else _next_up(lo, dt)
            if hi is not None:
                his[qi] = hi if inc_hi else _next_down(hi, dt)
        match = (num.vals[:, None, :] >= jnp.asarray(los)[None, :, None]) \
            & (num.vals[:, None, :] <= jnp.asarray(his)[None, :, None]) \
            & ~num.missing[:, None, :]
        return jnp.where(match, jnp.float32(node.boost), 0.0), match
    if kw is not None:
        los = np.zeros((stack.g_pad, Q), np.int32)
        his = np.full((stack.g_pad, Q), -1, np.int32)   # default: empty
        for gi, seg in enumerate(stack.segments):
            kc = seg.keywords.get(f)
            if kc is None:
                continue
            his[gi, :] = len(kc.values) - 1
            for qi, (lo, hi, inc_lo, inc_hi) \
                    in enumerate(node.bounds_per_query):
                if lo is not None:
                    i = _bisect(kc.values, str(lo), left=True)
                    if not inc_lo and i < len(kc.values) \
                            and kc.values[i] == str(lo):
                        i += 1
                    los[gi, qi] = i
                if hi is not None:
                    i = _bisect(kc.values, str(hi), left=False) - 1
                    if not inc_hi and i >= 0 and kc.values[i] == str(hi):
                        i -= 1
                    his[gi, qi] = i
        ords = kw.ords
        match = (ords[:, None, :] >= jnp.asarray(los)[:, :, None]) \
            & (ords[:, None, :] <= jnp.asarray(his)[:, :, None]) \
            & (ords[:, None, :] >= 0)
        return jnp.where(match, jnp.float32(node.boost), 0.0), match
    return _zeros(ctx), _false(ctx)


def _h_exists(node: ExistsNode, ctx: StackedContext):
    stack = ctx.stack
    f = node.field_name
    if f in stack.mixed:
        return _generic_exec(node, ctx)
    num = stack.numerics.get(f)
    kw = stack.keywords.get(f)
    sf = stack.text.get(f)
    if num is not None:
        match = jnp.broadcast_to(~num.missing[:, None, :],
                                 (ctx.g_pad, ctx.Q, ctx.n_pad))
    elif kw is not None:
        match = jnp.broadcast_to((kw.ords >= 0)[:, None, :],
                                 (ctx.g_pad, ctx.Q, ctx.n_pad))
    elif sf is not None:
        starts = np.zeros((stack.g_pad, 1, 1), np.int32)
        lens = np.zeros((stack.g_pad, 1, 1), np.int32)
        for gi, seg in enumerate(stack.segments):
            fx = seg.text.get(f)
            if fx is not None:
                lens[gi, 0, 0] = fx.n_postings
        W = max(8, 1 << (max(int(lens.max()), 1) - 1).bit_length())
        hits = _term_mask_stack(sf.doc_ids, jnp.asarray(starts),
                                jnp.asarray(lens), W=W, n_pad=ctx.n_pad)
        match = jnp.broadcast_to(hits, (ctx.g_pad, ctx.Q, ctx.n_pad))
    else:
        return _zeros(ctx), _false(ctx)
    return jnp.where(match, jnp.float32(node.boost), 0.0), match


def _h_ids(node: IdsNode, ctx: StackedContext):
    mask = np.zeros((ctx.g_pad, ctx.Q, ctx.n_pad), bool)
    for gi, seg in enumerate(ctx.stack.segments):
        for qi, ids in enumerate(node.ids_per_query):
            for i in ids:
                local = seg.id_to_local.get(i)
                if local is not None:
                    mask[gi, qi, local] = True
    match = jnp.asarray(mask)
    return jnp.where(match, jnp.float32(node.boost), 0.0), match


# -- structural handlers -----------------------------------------------------

def _h_bool(node: BoolNode, ctx: StackedContext):
    scores = _zeros(ctx)
    match = _true(ctx)
    any_positive = bool(node.must or node.filter)
    for n in node.must:
        s, m = execute_tree(n, ctx)
        scores = scores + s
        match = match & m
    for n in node.filter:
        _, m = execute_tree(n, ctx)
        match = match & m
    if node.should:
        msm = node.minimum_should_match
        if msm is None:
            msm = 0 if any_positive else 1
        should_count = jnp.zeros((ctx.g_pad, ctx.Q, ctx.n_pad), jnp.int32)
        for n in node.should:
            s, m = execute_tree(n, ctx)
            scores = scores + jnp.where(m, s, 0.0)
            should_count = should_count + m.astype(jnp.int32)
        if msm > 0:
            match = match & (should_count >= msm)
    for n in node.must_not:
        _, m = execute_tree(n, ctx)
        match = match & ~m
    scores = jnp.where(match, scores * node.boost, 0.0)
    return scores, match


def _m_bool(node: BoolNode, ctx: StackedContext):
    match = _true(ctx)
    for n in node.must + node.filter:
        match = match & match_tree(n, ctx)
    if node.should:
        msm = node.minimum_should_match
        if msm is None:
            msm = 0 if (node.must or node.filter) else 1
        if msm == 1:
            any_should = _false(ctx)
            for n in node.should:
                any_should = any_should | match_tree(n, ctx)
            match = match & any_should
        elif msm > 1:
            cnt = jnp.zeros((ctx.g_pad, ctx.Q, ctx.n_pad), jnp.int32)
            for n in node.should:
                cnt = cnt + match_tree(n, ctx).astype(jnp.int32)
            match = match & (cnt >= msm)
    for n in node.must_not:
        match = match & ~match_tree(n, ctx)
    return match


def _h_const(node: ConstantScoreNode, ctx: StackedContext):
    m = match_tree(node.inner, ctx)
    return jnp.where(m, jnp.float32(node.boost), 0.0), m


def _m_const(node: ConstantScoreNode, ctx: StackedContext):
    return match_tree(node.inner, ctx)


def _h_dis_max(node: DisMaxNode, ctx: StackedContext):
    best = _zeros(ctx)
    total = _zeros(ctx)
    match = _false(ctx)
    for n in node.queries:
        s, m = execute_tree(n, ctx)
        s = jnp.where(m, s, 0.0)
        best = jnp.maximum(best, s)
        total = total + s
        match = match | m
    scores = best + node.tie_breaker * (total - best)
    return jnp.where(match, scores * node.boost, 0.0), match


def _h_boosting(node: BoostingNode, ctx: StackedContext):
    s, m = execute_tree(node.positive, ctx)
    _, nm = execute_tree(node.negative, ctx)
    s = jnp.where(nm, s * node.negative_boost, s)
    return jnp.where(m, s * node.boost, 0.0), m


_EXEC = {
    MatchAllNode: _h_match_all,
    MatchNoneNode: _h_match_none,
    MatchNode: _h_match,
    TermFilterNode: _h_term,
    RangeNode: _h_range,
    ExistsNode: _h_exists,
    IdsNode: _h_ids,
    BoolNode: _h_bool,
    ConstantScoreNode: _h_const,
    DisMaxNode: _h_dis_max,
    BoostingNode: _h_boosting,
}

_MATCH = {
    MatchNode: _m_match,
    BoolNode: _m_bool,
    ConstantScoreNode: _m_const,
}


# dispatch accounting: the stacked-lane kernels enter the device_stats
# registry (call sites resolve these module globals at call time)
from ..common.device_stats import instrument as _instrument  # noqa: E402

_bm25_stack = _instrument("stacked:bm25", _bm25_stack)
_classic_stack = _instrument("stacked:classic", _classic_stack)
_term_mask_stack = _instrument("stacked:term_mask", _term_mask_stack)
stacked_reduce = _instrument("stacked:reduce", stacked_reduce)
stacked_sorted_reduce = _instrument("stacked:sorted_reduce",
                                    stacked_sorted_reduce)
